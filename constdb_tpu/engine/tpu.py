"""Batched JAX MergeEngine: the TPU path for bulk CRDT merges.

Device strategies, picked per CRDT family:

  * bulk (the fast path, ops/bulk.py): each batch ships as COMPACT rows
    (int32 slot ids + value columns) and folds into full per-slot device
    state, one gather→merge→scatter kernel call per batch.  State is
    donated between calls (never re-uploaded), uploads are async (batch
    b+1 transfers while b merges), and when every touched slot is brand
    new — snapshot ingest into an empty region — the initial state is
    materialized ON device and only the merged block downloads.
  * scatter (ops/segment.py): touched-slot gather + scatter-max kernels.
    Chosen for sparse merges when state is host-resident.

**Resident mode** (`TpuMergeEngine(resident=True)`): the per-family device
state persists ACROSS merge calls, so streaming replica catch-up — the
replica link applies a snapshot chunk-by-chunk, and each chunk is one
`merge()` — pays row uploads only, never a state round-trip per chunk.
Merged state flushes back to the host keyspace lazily (`flush()`), which
the Node triggers before any command touches the numeric plane
(`Node.ensure_flushed`); op-path writes bump the touched plane's
`KeySpace.fam_ver` entry, so the engine rebuilds ONLY that plane's mirror
(mixed op/merge traffic keeps the other mirrors resident).  Win VALUES
(dict fields / register bytes) resolve through a device src plane at
flush — no per-call win-flag download; value bytes live only on the host.

**Steady state** (round 12): op-stream micro-batches — the
serve/replication coalescers' flushes, previously always routed to the
host micro strategy — merge IN PLACE against the resident planes too
(`_merge_micro_resident`): duplicate slots fold on host with the shared
hostbatch reductions, unique winners scatter once per family
(ops/pallas_dense.py `scatter_pair_src` or its XLA twin), the env plane
stays host-authoritative, and `flush()` downloads only the rows touched
since the last flush (dirty-row accounting; counter sums update
incrementally or re-derive via the device `segment_sum`).  This inverts
HOST_SCATTER_MAX into a FALLBACK threshold — per family for cold planes
(`_micro_placement`), whole-round only when the steady path is off
(CONSTDB_RESIDENT=0, non-resident engines, mesh-partitioned state).

Bulk batches whose rows are NOT unique per slot (raw op streams) above the
micro ceiling take the scatter path — its reductions tolerate intra-batch
collisions; the bulk kernels require `rows_unique_per_slot` (one scatter
per slot per call).

Must be semantically bit-identical to engine/cpu.py — differential-tested in
tests/test_engine_equivalence.py and tests/test_resident_engine.py.
"""

from __future__ import annotations

import logging
from typing import Optional

import numpy as np

from ..crdt import semantics as S
from ..ops import bulk as B
from ..ops import segment as K
from ..store.keyspace import FAMILIES, KeySpace
from .base import ColumnarBatch, MergeStats, has_values
from .hostbatch import HOST_MICRO_MAX

log = logging.getLogger(__name__)

_I64 = np.int64
_I32 = np.int32


def _pad(arr: np.ndarray, size: int, fill) -> np.ndarray:
    arr = np.asarray(arr)
    if len(arr) == size:
        return arr
    # empty + two slice writes touches each element once (np.full would
    # write the fill over the whole buffer first)
    out = np.empty((size,) + arr.shape[1:], dtype=arr.dtype)
    out[: len(arr)] = arr
    out[len(arr):] = fill
    return out


# family -> [(column name in the family's host table, neutral fill)]
_FAMILIES = {
    "env": [("ct", 0), ("mt", 0), ("dt", 0), ("expire", 0)],
    "reg": [("rv_t", 0), ("rv_node", 0)],
    "cnt": [("val", 0), ("uuid", K.NEUTRAL_T), ("base", 0),
            ("base_t", K.NEUTRAL_T)],
    "el": [("add_t", 0), ("add_node", 0), ("del_t", 0)],
}


def _host_table(store: KeySpace, fam: str):
    return store.el if fam == "el" else (store.cnt if fam == "cnt"
                                         else store.keys)


# ------------------------------------------------------- host group combine
# Transfer-bound devices (a TPU behind a tunnel moves ~100 MB/s with ~80 ms
# per-transfer latency) pay per BYTE and per TRANSFER, so a group of staged
# batches is pre-combined ON HOST whenever that shrinks either:
#   * aligned rows (R replica snapshots of one keyspace) fold R× down with
#     vectorized numpy lex-max — upload drops R×;
#   * disjoint rows (consecutive chunks of ONE snapshot) concatenate into a
#     single batch — same bytes, one transfer + one kernel instead of R.
# Both reductions compute exactly crdt/semantics.py (lexicographic (t, v)
# max / plain max), so device results are bit-identical either way.


def _rows_aligned(staged) -> bool:
    if len(staged) < 2:
        return False
    r0 = staged[0][0]
    return all(len(s[0]) == len(r0) and np.array_equal(s[0], r0)
               for s in staged[1:])


def _rows_disjoint_cat(staged):
    """Concatenated row array if no row repeats across entries, else None.

    Cheap interval test first: slot rows are created in contiguous blocks
    during catch-up, so non-overlapping [min, max] ranges prove cross-part
    disjointness without the O(n log n) sort."""
    parts = [np.asarray(s[0]) for s in staged]
    nonempty = [p for p in parts if len(p)]
    if len(nonempty) < 2:
        return np.concatenate(parts) if parts else np.zeros(0, _I64)
    iv = sorted((int(p.min()), int(p.max())) for p in nonempty)
    if all(iv[i][1] < iv[i + 1][0] for i in range(len(iv) - 1)):
        return np.concatenate(parts)
    cat = np.concatenate(parts)
    if len(np.unique(cat)) == len(cat):
        return cat
    return None


def _lex_fold(t_list, v_list):
    """RUNNING lexicographic (t, v) max over R same-shape arrays ->
    (t[N], v[N], win_batch[N]).  Mirrors ops/bulk.py _pair_win /
    crdt/semantics.py lww_wins; ties keep the EARLIEST batch (the
    stacked-argmax formulation's winner).  Running beats stacking: no
    [R, N] materialization, ~3 memory-bound passes per batch."""
    t = np.array(t_list[0], copy=True)
    v = np.array(v_list[0], copy=True)
    wb = np.zeros(len(t), dtype=_I64)
    for i in range(1, len(t_list)):
        ti = np.asarray(t_list[i])
        vi = np.asarray(v_list[i])
        win = (ti > t) | ((ti == t) & (vi > v))
        np.copyto(t, ti, where=win)
        np.copyto(v, vi, where=win)
        wb[win] = i
    return t, v, wb


def _sel_obj(lists, wb: np.ndarray) -> np.ndarray:
    """Pick lists[wb[j]][j] for every j, vectorized via an object matrix.
    A None entry in `lists` stands for an all-None value column (valueless
    batches skip materializing [None] * n lists entirely)."""
    obj = np.empty((len(lists), len(wb)), dtype=object)
    for i, v in enumerate(lists):
        obj[i, :] = v  # numpy broadcasts a bare None across the row
    return obj[wb, np.arange(len(wb))]


def _fam_rows(store: KeySpace, fam: str) -> int:
    return _host_table(store, fam).n


class TpuMergeEngine:
    name = "tpu"
    # bulk when staged rows cover >= 1/BULK_FRACTION of the slot region
    # (resident mode always prefers bulk: there is no state upload to avoid)
    BULK_FRACTION = 8
    # contiguous-row batches at or above this length derive their idx
    # vector on device (iota) instead of uploading it; below it the jit
    # dispatch overhead outweighs the saved bytes (tests lower it to 1)
    IDX_IOTA_MIN = 4096
    # op-stream micro-batches (rows_unique_per_slot=False) at or below
    # this many total rows merge on HOST (engine/hostbatch.py): at that
    # scale device dispatch fixed costs dwarf the merge, and the
    # steady-state coalescer flushes such batches every few ms
    # single source of truth in engine/hostbatch.py: the CPU engine's
    # micro routing and this ceiling must move together, or the two
    # engines route the same batch onto different strategies
    HOST_SCATTER_MAX = HOST_MICRO_MAX
    # win-source pool ids live in an int32 device plane; merge_many flushes
    # before staging a round that could cross this (tests lower it)
    POOL_ID_CEILING = 1 << 31
    # pow2 pad FLOORS for the steady micro path: batch/dirty vectors pad
    # up to these before the pow2 round, so the jitted scatter/gather
    # kernels re-trace per PLANE CAP only, not per batch-size bucket —
    # per-shape tracing dominated small-stream walls, while scattering/
    # gathering a few hundred padded rows costs microseconds on any
    # backend.  (Scatter pads engage only while a free pad row exists —
    # see _micro_scatter_pair.)
    MICRO_SCATTER_PAD = 256
    FLUSH_GATHER_PAD = 512
    # staging order = dispatch order = the on-store plane contract
    FAM_ORDER = ("env", "reg", "cnt", "el")

    def __init__(self, resident: bool = False, mesh=None,
                 dense_fold: str = "auto",
                 pipeline: Optional[bool] = None,
                 steady: Optional[bool] = None,
                 warmup: Optional[int] = None) -> None:
        """`mesh`: an optional jax.sharding.Mesh with a "kv" axis.  When
        given, per-slot device state range-partitions over that axis
        (NamedSharding P("kv")) while batch rows replicate — GSPMD then
        partitions the very same bulk kernels across the slice, with each
        device scattering the rows that land in its slot range.  Sharding
        is placement policy only: kernels, semantics, and host plumbing
        are identical to the single-chip path (SURVEY.md §7 item 6).

        `dense_fold`: strategy for ALIGNED multi-batch merges (several
        batches staging the exact same slot rows — R replica snapshots of
        one keyspace, the bulk catch-up shape).  Aligned batches reduce
        on-device in one fused [R, N] pass, then scatter ONCE instead of
        R times.  "auto" = fused Pallas kernels (ops/pallas_dense.py) on
        TPU backends, XLA dense kernels (ops/dense.py) elsewhere; "pallas"
        / "pallas-interpret" / "xla" force a backend; "off" disables
        folding.  Both backends are differential-tested bit-identical.

        `steady`: device-resident STEADY-STATE path — op-stream
        micro-batches (the serve/replication coalescers' flushes) merge
        IN PLACE against the resident device planes instead of falling
        back to the host micro strategy; flushes then download only the
        rows those merges touched (dirty-row accounting).  This is the
        routing inversion that makes HOST_SCATTER_MAX a FALLBACK
        threshold: the host micro path runs only when the engine is not
        resident, a mesh partitions the state, or — per family — a
        touched plane is COLD (no warm mirror and the plane's host
        version has not been stable for `warmup` consecutive micro
        rounds — op-path writes between rounds would otherwise force a
        full mirror re-upload per round).  None = CONSTDB_RESIDENT:
        "auto" (default) engages only over a real non-CPU backend, "1"
        forces on, "0" off; `warmup` defaults to
        CONSTDB_RESIDENT_WARMUP (2).

        `pipeline`: double-buffered merge dispatch.  Each CRDT family's
        work splits into STAGE (pure host prep: columnarization, slot
        resolution, group combine — touches ONLY that family's host
        plane) and DISPATCH (device uploads/kernels + pool bookkeeping,
        main thread, family order).  With the pipeline on, a background
        pool stages the families concurrently while the main thread
        dispatches each plan as it lands and the device crunches earlier
        kernels — host staging overlaps device compute instead of
        serializing behind it.  Results are byte-identical to the serial
        path: the safety invariant is PER-PLANE INDEPENDENCE, not
        ordering — every plane's appends happen inside exactly one stage,
        in batch order, and no stage reads another family's store plane
        (a stage that needs one must move that read into merge_many's
        serial prologue or its own dispatch).  None = on unless
        CONSTDB_PIPELINE=0.  The serial path stays selectable for
        debugging (pipeline=False / CONSTDB_PIPELINE=0)."""
        import jax  # ensure a backend exists before we advertise ourselves

        self._jax = jax
        self._devices = jax.devices()
        self.dense_fold = dense_fold
        # staged copy of the fold/no-fold decision (merge_many prologue
        # refreshes it; stages must not probe the backend themselves)
        self._fold_on = dense_fold != "off"
        self.folds = 0          # aligned folds performed (observability)
        # stale-mirror rebuilds per family (observability: mixed op/merge
        # traffic must keep these O(writes-to-that-plane), never O(ops))
        self.mirror_rebuilds = dict.fromkeys(FAMILIES, 0)
        # cumulative host-side seconds per family on the CRITICAL PATH
        # (stage-wait + dispatch; device work is async).  The flush entry
        # includes the blocking downloads.  With the pipeline on,
        # `stage_secs` separately records each family's background staging
        # time — staging overlapped with device compute shows up there
        # while family_secs shrinks to the un-overlapped remainder.
        self.family_secs = {"env": 0.0, "reg": 0.0, "cnt": 0.0, "el": 0.0,
                            "flush": 0.0, "host": 0.0, "micro": 0.0}
        self.stage_secs = {"env": 0.0, "reg": 0.0, "cnt": 0.0, "el": 0.0}
        from ..conf import env_flag, env_int
        if pipeline is None:
            pipeline = env_flag("CONSTDB_PIPELINE", True)
        self.pipeline = bool(pipeline)
        # steady-state residency (see __init__ docstring): micro rounds
        # merged in place on device vs routed to the host fallback, and
        # the flush download accounting the acceptance criterion reads.
        # "auto" (the default) engages only over a REAL accelerator: on
        # a CPU-only backend the "device" IS the host, so in-place
        # XLA-CPU scatters just add dispatch overhead over the numpy
        # micro strategy — the healthy-device clause of the routing
        # inversion.  Tests/bench legs force steady=True to exercise the
        # path on CPU builders.
        if steady is None:
            from ..conf import env_str
            mode = env_str("CONSTDB_RESIDENT", "auto")
            steady = jax.default_backend() != "cpu" if mode == "auto" \
                else mode != "0"
        self.steady = bool(steady)
        self.warmup = env_int("CONSTDB_RESIDENT_WARMUP", 2) \
            if warmup is None else int(warmup)
        self._warm_streak: dict[str, tuple[int, int]] = {}
        self.dev_rounds_resident = 0
        self.host_micro_rounds = 0
        self.flush_rows_downloaded = 0
        # rows a whole-plane flush WOULD have downloaded at the same
        # points — the denominator that proves partial, not full,
        # downloads (bench legs report both)
        self.flush_rows_full_equiv = 0
        self._stage_ex = None          # lazy single-worker staging executor
        self._stage_pending = None     # in-flight stage futures (flush joins)
        self._pallas_broken = False
        # resident tensor payload pools (the tensor-register family,
        # crdt/tensor.py): one [cap, Kp] device pool per (dtype, elems)
        # class, holding contributor payload rows; slot STAMPS stay
        # host-authoritative (like the env plane on the micro path), so
        # only payload bytes ever cross the link.  `dirty` pool slots
        # are device-newer than the host side list; flush gathers and
        # downloads exactly those (ops/bulk.py gather_rows).
        self._tns_pools: dict[tuple, dict] = {}
        self._tns_ver = 0
        self._tns_epoch = 0            # bumped whenever pools drop
        self._tns_read_cache: dict = {}
        self._tns_bytes = 0            # device payload bytes resident
        self.tns_dev_rows = 0          # tensor rows merged on device
        self.tns_host_rows = 0         # tensor rows merged on host
        self.tns_pool_cap = env_int("CONSTDB_TENSOR_POOL_MB", 512) << 20
        # host<->device transfer accounting (bench.py turns these into a
        # measured fraction of the link ceiling — the merge is
        # transfer-bound on tunnel-attached devices)
        self.bytes_h2d = 0
        self.bytes_d2h = 0
        self.resident = resident
        self._res: dict[str, dict] = {}   # fam -> {cols: {name: dev arr}, n, cap}
        # deferred win-value resolution (resident mode): host value pool the
        # device-resident `src` planes index into; resolved once at flush.
        # Entries pin their batch column arrays until then, so merge_many
        # auto-flushes once the pinned bytes pass `pool_flush_bytes` —
        # a streamed catch-up with no interleaved reads stays O(cap), not
        # O(total ingested bytes).
        self._val_pool: list[tuple[int, Optional[list], dict]] = []
        self._pool_size = 0
        self._pool_bytes = 0
        # el rows whose HOST del_t advanced since the last flush (the del
        # plane never touches the device in the src path); flush turns
        # newly-dead ones into GC queue entries after add_t reconstruction
        self._el_del_touched: list[np.ndarray] = []
        self._jit_cache: dict = {}  # keyed per-shape jitted builders
        self.pool_flush_bytes = env_int("CONSTDB_POOL_FLUSH_MB", 1536) << 20
        self.needs_flush = False
        self._mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            self._kv_n = int(mesh.shape["kv"])
            self._sh_state = (None, NamedSharding(mesh, PartitionSpec("kv")),
                              NamedSharding(mesh, PartitionSpec("kv", None)))
            self._sh_rep = NamedSharding(mesh, PartitionSpec())
        else:
            self._kv_n = 1

    def _host_combine(self) -> bool:
        """Host group pre-combine is on unless a device fold backend is
        explicitly forced (those test paths must still execute) or folding
        is off entirely."""
        return self.dense_fold == "auto"

    def _combine_groups(self, staged, fold_fn, cat_fn):
        """Collapse a multi-batch staged list on host (see the host-combine
        block comment above), hierarchically: entries with IDENTICAL row
        sets cluster and fold R× via `fold_fn` (a large group covering
        several key ranges from several replicas folds per range); then,
        if the folded survivors are pairwise disjoint, they concatenate
        into one transfer via `cat_fn`.  Overlapping-unaligned leftovers
        stay as-is (sequential kernels).  -> (combined, n_folds) — the
        fold COUNT is returned, not applied to self.folds: this runs on
        the staging worker, and the dispatching main thread applies it
        (no racing `+=` on shared counters)."""
        if not self._host_combine() or len(staged) < 2:
            return staged, 0
        clusters: list[list] = []
        by_sig: dict = {}
        for s in staged:
            r = s[0]
            sig = (len(r), int(r[0]) if len(r) else -1,
                   int(r[-1]) if len(r) else -1)
            placed = False
            for cl in by_sig.get(sig, ()):
                r0 = cl[0][0]
                # identity first: replica batches stage the very same row
                # array object (memoized key/element resolution), so most
                # clusters match without an O(n) compare
                if r0 is r or np.array_equal(r0, r):
                    cl.append(s)
                    placed = True
                    break
            if not placed:
                cl = [s]
                clusters.append(cl)
                by_sig.setdefault(sig, []).append(cl)
        folded = []
        n_folds = 0
        for cl in clusters:
            if len(cl) > 1:
                n_folds += 1
                folded.append(fold_fn(cl))
            else:
                folded.append(cl[0])
        if len(folded) == 1:
            return folded, n_folds
        cat = _rows_disjoint_cat(folded)
        if cat is not None:
            return [cat_fn(folded, cat)], n_folds
        return folded, n_folds

    def _pool_add(self, vals, **cols) -> np.int32:
        """Stage one batch's winner-carried payload in the host pool and
        return its base pool id (the kernels derive per-row ids as
        base + iota — ids never upload).  `vals` feeds win-value
        resolution (None = every value is None — a winning valueless row
        still CLEARS the slot's value, without materializing a list);
        `cols` are the host column arrays reconstructed at flush (e.g.
        add_t=..., add_node=...), held by reference until the next
        flush (merge_many bounds the pinned bytes via auto-flush).

        The int32 src-plane ceiling is checked BEFORE any pool state
        mutates; merge_many pre-flushes rounds that could cross it, so
        tripping this means one single round stages > 2^31 rows."""
        base = self._pool_size
        n = -1
        nbytes = 0
        if vals is not None:
            vals = list(vals)
            n = len(vals)
            # count the real pinned payload, not just pointers: the
            # auto-flush bound must trip on value-heavy ingests too
            # (filter(None) drops None at C speed; empty bytes are falsy
            # too, but len(b"") contributes 0 anyway)
            nbytes += 8 * n + sum(map(len, filter(None, vals)))
        for a in cols.values():
            n = len(a)
            nbytes += int(getattr(a, "nbytes", 8 * n))
        if base + n >= self.POOL_ID_CEILING:  # int32 src plane ceiling
            raise RuntimeError(
                "win-source pool would exceed int32 range within a single "
                "merge round; split the ingest into smaller merge_many "
                "calls so flush() can run between them")
        self._val_pool.append((base, vals, cols))
        self._pool_size = base + n
        self._pool_bytes += nbytes
        return np.int32(base)

    def _src_state(self, fam: str, sp: int):
        """Device win-source plane for `fam`, grown to sp (fill -1).
        int32 — pool ids fit, and the plane is downloaded every flush."""
        jnp = self._jax.numpy
        res = self._res.get(fam) or {}
        src = res.get("src")
        if src is None:
            return B.device_full(sp, -1, i32=True)
        if src.shape[0] < sp:
            src = jnp.concatenate(
                [src, B.device_full(sp - src.shape[0], -1, i32=True)])
        return src

    # ----------------------------------------------------- device placement

    def _sp_size(self, size: int) -> int:
        """Padded state size: pow2, rounded up to a multiple of the kv
        axis (a non-pow2 device count otherwise fails sharding)."""
        sp = K.next_pow2(max(size, 1))
        if self._kv_n > 1 and sp % self._kv_n:
            sp = -(-sp // self._kv_n) * self._kv_n
        return sp

    def _put_state(self, host: np.ndarray):
        self.bytes_h2d += host.nbytes
        if self._mesh is None:
            return self._jax.device_put(host)
        return self._jax.device_put(host, self._sh_state[host.ndim])

    def _put_batch(self, arr: np.ndarray):
        self.bytes_h2d += arr.nbytes
        if self._mesh is None:
            return self._jax.device_put(arr)
        return self._jax.device_put(arr, self._sh_rep)

    def _device_get(self, x):
        out = self._jax.device_get(x)
        seq = out if isinstance(out, (tuple, list)) else (out,)
        self.bytes_d2h += sum(int(a.nbytes) for a in seq)
        return out

    def _full(self, n: int, fill: int, cols: int = 0):
        """Neutral state materialized on device with the state sharding
        (cols=0 → [n]; cols=C → [n, C])."""
        if self._mesh is None:
            if cols:
                return self._jax.numpy.zeros((n, cols),
                                             dtype=self._jax.numpy.int64)
            return B.device_full(n, fill)
        key = ("full", n, fill, cols)
        fn = self._jit_cache.get(key)
        if fn is None:
            jnp = self._jax.numpy
            shape = (n, cols) if cols else (n,)
            fn = self._jax.jit(
                lambda: jnp.full(shape, fill, dtype=jnp.int64),
                out_shardings=self._sh_state[2 if cols else 1])
            self._jit_cache[key] = fn
        return fn()

    def _grow(self, old, delta: int, fill: int, cols: int = 0):
        """Extend resident state by `delta` neutral rows, preserving the
        state sharding."""
        jnp = self._jax.numpy
        if self._mesh is None:
            if cols:
                return jnp.concatenate(
                    [old, jnp.zeros((delta, cols), dtype=jnp.int64)])
            return jnp.concatenate([old, B.device_full(delta, fill)])
        key = ("grow", delta, fill, cols)
        fn = self._jit_cache.get(key)
        if fn is None:
            shape = (delta, cols) if cols else (delta,)
            fn = self._jax.jit(
                lambda o: jnp.concatenate(
                    [o, jnp.full(shape, fill, dtype=jnp.int64)]),
                out_shardings=self._sh_state[2 if cols else 1])
            self._jit_cache[key] = fn
        return fn(old)

    # ------------------------------------------------------------------ API

    def merge(self, store: KeySpace, batch: ColumnarBatch) -> MergeStats:
        return self.merge_many(store, [batch])

    def merge_many(self, store: KeySpace, batches: list[ColumnarBatch]) -> MergeStats:
        """Fold any number of columnar batches into the store.  Reductions
        are associative + commutative, so all batches merge in one device
        pass per CRDT family — and the same properties license the
        pipelined stage/dispatch overlap (see __init__).

        The returned MergeStats carries this call's device-transfer
        deltas (dev_upload_bytes / dev_download_bytes /
        dev_rounds_resident / flush_rows_downloaded) sliced out of the
        engine's cumulative counters."""
        h0, d0 = self.bytes_h2d, self.bytes_d2h
        r0, f0 = self.dev_rounds_resident, self.flush_rows_downloaded
        st = self._merge_many_impl(store, batches)
        st.dev_upload_bytes = self.bytes_h2d - h0
        st.dev_download_bytes = self.bytes_d2h - d0
        st.dev_rounds_resident = self.dev_rounds_resident - r0
        st.flush_rows_downloaded = self.flush_rows_downloaded - f0
        return st

    def _merge_many_impl(self, store: KeySpace,
                         batches: list[ColumnarBatch]) -> MergeStats:
        st = MergeStats()
        # the bulk path scatters each slot once per batch, which is only a
        # merge if slots are unique within every batch
        self._unique_ok = all(b.rows_unique_per_slot for b in batches)
        # resident-mirror staleness is checked PER FAMILY in
        # _resident_state (KeySpace.fam_ver): an op write to one CRDT
        # plane no longer drops every other plane's device mirror
        self._n0_keys = store.keys.n
        # pool-id headroom (int32 src plane): flush completed rounds BEFORE
        # staging one that could cross the ceiling — the round boundary is
        # the only safe flush point (mid-round, in-flight family state is
        # not yet in self._res and its pool ids would be dropped)
        if self.resident and self._pool_size and \
                self._pool_size + sum(b.n_rows for b in batches) >= \
                self.POOL_ID_CEILING:
            log.info("win-source pool near int32 ceiling; flushing before "
                     "this merge round")
            self.flush(store)
        # replica snapshots of one keyspace share the key-list object (or,
        # when chunked, a key_shape identity token — batch_chunks); resolve
        # each distinct list/shape once (ids are stable within this merge,
        # and shape tokens pin their parents via shape_refs)
        memo: dict = {}
        resolved = []
        for b in batches:
            mk = b.key_shape if b.key_shape is not None \
                else ("id", id(b.keys), id(b.key_enc))
            kid_of = memo.get(mk)
            if kid_of is None:
                kid_of = self._resolve_keys(store, b, st)
                memo[mk] = kid_of
            resolved.append((b, kid_of))
        if not self._unique_ok and self._mesh is None and \
                sum(b.n_rows for b in batches) <= self.HOST_SCATTER_MAX:
            # op-stream micro-batches (the steady-state coalescers'
            # flushes).  DEFAULT placement for a resident engine: fold
            # each batch's duplicate slots on host (a few hundred rows)
            # and scatter-merge the unique winners IN PLACE against the
            # resident device planes — state never round-trips, and the
            # next flush downloads only the touched (dirty) rows.  The
            # host micro strategy (engine/hostbatch.py) is the FALLBACK,
            # per family (cold planes — see _micro_placement) or for the
            # whole round (non-resident engines, CONSTDB_RESIDENT=0,
            # mesh-partitioned state).
            import time as _time
            placement = self._micro_placement(store, resolved)
            if placement is not None:
                t0 = _time.perf_counter()
                for b, kid_of in resolved:
                    self._merge_micro_resident(store, b, kid_of, st,
                                               placement)
                if any(placement.values()):
                    self.dev_rounds_resident += 1
                elif placement:
                    self.host_micro_rounds += 1
                # empty placement (env-only / delete-only round): neither
                # gauge — no device family was touched at all
                self.family_secs["micro"] += _time.perf_counter() - t0
                if self.needs_flush and \
                        self._pool_bytes > self.pool_flush_bytes:
                    self.flush(store)
                return st
            # legacy whole-round fallback (steady path off): any resident
            # mirror of the touched planes syncs down first, exactly like
            # the device scatter path would via _drop_family
            from .hostbatch import merge_host_batch
            for fam in list(self._res):
                self._drop_family(store, fam)
            self.host_micro_rounds += 1
            t0 = _time.perf_counter()
            rows0 = st.tensor_rows
            for b, kid_of in resolved:
                merge_host_batch(store, b, kid_of, st)
            self.tns_host_rows += st.tensor_rows - rows0
            self.family_secs["host"] += _time.perf_counter() - t0
            return st
        import time as _time
        # a src-tracked pool from resident MICRO rounds must resolve
        # before a bulk branch that does not track src (forced dense_fold
        # configs skip the src kernels) scatters into the same planes —
        # flush would otherwise assign stale pool values over the bulk
        # round's winners
        if self.resident and self._pool_size and not self._host_combine():
            self.flush(store)
        # the fold/no-fold decision is STAGED (the [R, N] stack builds it
        # gates are host work that belongs on the staging pool, not the
        # dispatch critical path) but _fold_backend reads device state
        # (jax default backend / pallas health), so resolve it HERE in the
        # serial prologue and let stages read the plain boolean
        self._fold_on = self._fold_backend() != "off"
        stage = {"env": self._stage_envelopes, "reg": self._stage_registers,
                 "cnt": self._stage_counter_rows, "el": self._stage_elem_rows}
        dispatch = {"env": self._dispatch_envelopes,
                    "reg": self._dispatch_registers,
                    "cnt": self._dispatch_counter_rows,
                    "el": self._dispatch_elem_rows}
        if self.pipeline:
            # double-buffered: the staging pool runs the family stages
            # (possibly concurrently — each touches only its own host
            # plane) while the main thread dispatches each plan in family
            # order as it lands.  The only cross-plane seam is flush,
            # which joins the in-flight stages first.
            ex = self._staging_executor()
            futs = {f: ex.submit(self._timed_stage, f, stage[f],
                                 store, resolved, st)
                    for f in self.FAM_ORDER}
            self._stage_pending = futs
            try:
                for fam in self.FAM_ORDER:
                    t0 = _time.perf_counter()
                    plan = futs[fam].result()
                    dispatch[fam](store, plan, st)
                    self.family_secs[fam] += _time.perf_counter() - t0
            finally:
                # a dispatch error must not leave stages mutating the
                # store behind the caller's back
                import concurrent.futures as _cf
                _cf.wait(list(futs.values()))
                self._stage_pending = None
        else:
            for fam in self.FAM_ORDER:
                t0 = _time.perf_counter()
                plan = self._timed_stage(fam, stage[fam], store, resolved, st)
                dispatch[fam](store, plan, st)
                self.family_secs[fam] += _time.perf_counter() - t0
        # tensor rows (few, payload-heavy) ride the resident payload
        # pools whenever the steady path is on — bulk catch-up seeds the
        # pools the micro rounds then merge into; the host twin covers
        # everything else (meshes partition slot rows the pools don't)
        tns_device = self.resident and self.steady and self._mesh is None
        for b, kid_of in resolved:
            if len(b.tns_ki):
                self._merge_micro_tns(store, b, kid_of, st,
                                      device=tns_device)
        for b, _ in resolved:
            for i, key in enumerate(b.del_keys):
                store.record_key_delete(key, int(b.del_t[i]))
        # slot merges bypass the incremental sum cache — re-derive it in one
        # vectorized pass (envelope-only merges cannot change counter sums);
        # resident mode re-derives at flush time instead
        if not (self.resident and self.needs_flush) and \
                any(len(b.cnt_ki) for b, _ in resolved):
            store.recompute_counter_sums()
        # bound the win pool: a long streamed catch-up with no interleaved
        # reads would otherwise pin every staged batch's columns in host
        # RAM until the (read-triggered) flush
        if self.resident and self.needs_flush and \
                self._pool_bytes > self.pool_flush_bytes:
            self.flush(store)
        return st

    # ------------------------------------------------------ stage pipeline

    def _staging_executor(self):
        """Staging pool.  Family stages are mutually independent (each
        touches only its own host plane — see the per-stage docstrings),
        so they stage CONCURRENTLY, not just ahead of dispatch; results
        stay byte-identical because each plane's appends happen inside
        exactly one stage, in batch order.  Sized to the spare cores
        (CONSTDB_STAGE_WORKERS overrides)."""
        if self._stage_ex is None:
            import os as _os
            from concurrent.futures import ThreadPoolExecutor

            from ..conf import env_int
            n = env_int("CONSTDB_STAGE_WORKERS",
                        max(1, min(len(self.FAM_ORDER),
                                   (_os.cpu_count() or 2) - 1)))
            self._stage_ex = ThreadPoolExecutor(
                max_workers=max(n, 1), thread_name_prefix="constdb-stage")
        return self._stage_ex

    def close(self) -> None:
        """Release the staging pool's threads (idempotent; the pool is
        recreated lazily if the engine merges again).  Engines are
        long-lived in production, but short-lived ones — bench repeats,
        full-resync rebuilds — should not each strand a thread pool
        until interpreter exit."""
        ex = self._stage_ex
        if ex is not None:
            self._stage_ex = None
            ex.shutdown(wait=False)

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def _timed_stage(self, fam: str, fn, store, resolved, st):
        import time as _time
        t0 = _time.perf_counter()
        try:
            return fn(store, resolved, st)
        finally:
            self.stage_secs[fam] += _time.perf_counter() - t0

    def _join_staging(self) -> None:
        """Wait for in-flight family stages before any cross-plane mutation
        (flush rebuilds/writes tables a stage may be appending to).  Errors
        are NOT swallowed here — the merge loop re-raises them from
        future.result()."""
        futs = self._stage_pending
        if futs:
            import concurrent.futures as _cf
            _cf.wait(list(futs.values()))

    # ---------------------------------------------------------------- flush

    def flush(self, store: KeySpace) -> None:
        """Write resident device state back into the host keyspace (resident
        mode only; a no-op otherwise).  Also re-derives counter sums and
        enqueues element tombstones whose del_t advanced on device.

        Dirty-row accounting: a family whose merges since the last flush
        were all resident MICRO rounds carries an explicit dirty-row set —
        only those rows are gathered on device (ops/bulk.py gather_rows)
        and downloaded; whole-plane downloads happen only for bulk
        catch-up merges (dirty=None) that really did touch the plane
        wholesale, and an untouched family costs nothing.  Counter sums
        update INCREMENTALLY over the dirty rows (old-vs-new contribution
        delta) instead of the O(table) recompute.

        Download protocol: EVERY family's downloads dispatch up front
        (device-side [:n] slice / dirty-row gather so padding and
        untouched rows never cross the link; copy_to_host_async overlaps
        transfers), then families are consumed one at a time — family f's
        host-side application (column writes, src resolution, tombstone
        scans) runs while the remaining families' transfers are still in
        flight, and each consumed device slice is dropped immediately so
        its buffer frees without waiting for the whole flush."""
        if not self.needs_flush:
            return
        self._join_staging()
        import time as _time
        t0 = _time.perf_counter()
        pending: dict[str, dict] = {}
        partial: dict[str, tuple] = {}  # fam -> (rows_d, {name: dev}, src)
        for fam, res in self._res.items():
            n = res["n"]
            if n == 0:
                continue
            dirty = res.get("dirty")
            if dirty is not None and not dirty:
                continue  # untouched since the last flush: host == device
            cols = res["cols"]
            names = ["stack"] if fam == "env" else \
                [name for name, _ in _FAMILIES[fam]]
            written = res.get("written")
            recon = res.get("recon") if res.get("src") is not None else None
            want = [name for name in names
                    # mirror column never scattered into: the host column
                    # it was built from is still exact
                    if not (written is not None and name not in written)
                    # winner-carried column: reconstructed on host from
                    # the win pool via the (int32) src plane — the int64
                    # column itself never crosses the link
                    and not (recon and name in recon)]
            self.flush_rows_full_equiv += n
            if dirty is None:
                fp = {name: cols[name][:n] for name in want}
                if res.get("src") is not None:
                    fp["src"] = res["src"][:n]
                if fp:
                    pending[fam] = fp
                    self.flush_rows_downloaded += n
                continue
            rows_d = np.unique(np.concatenate(dirty))
            # pow2-padded gather idx (pad rows re-gather row 0 and are
            # sliced off after download): with the FLUSH_GATHER_PAD
            # floor, the gather jit re-traces per plane cap only
            np2 = K.next_pow2(max(len(rows_d), self.FLUSH_GATHER_PAD))
            idx_dev = self._put_batch(_pad(rows_d.astype(_I32), np2, 0))
            g = {name: B.gather_rows(cols[name], idx_dev) for name in want}
            src_dev = B.gather_rows(res["src"], idx_dev) \
                if res.get("src") is not None else None
            partial[fam] = (rows_d, g, src_dev)
            self.flush_rows_downloaded += len(rows_d)
        for fp in pending.values():
            for arr in fp.values():
                try:
                    arr.copy_to_host_async()
                except AttributeError:
                    pass
        for _rows_d, g, src_dev in partial.values():
            for arr in g.values():
                try:
                    arr.copy_to_host_async()
                except AttributeError:
                    pass
            if src_dev is not None:
                try:
                    src_dev.copy_to_host_async()
                except AttributeError:
                    pass

        for fam, fp in pending.items():
            res = self._res[fam]
            n = res["n"]
            host = {}
            for name in list(fp):
                h = np.asarray(fp.pop(name))  # blocks on THIS slice only
                self.bytes_d2h += int(h.nbytes)
                host[name] = h
            table = _host_table(store, fam)
            # the tombstone scan below only matters when the device could
            # have advanced del_t — skipped (all-add catch-up) it is
            # old_dt == del_t by construction
            el_dt_changed = fam == "el" and "del_t" in host
            if el_dt_changed:
                old_dt = table.del_t[:n].copy()
            if fam == "env":
                out = host["stack"]
                for i, (name, _) in enumerate(_FAMILIES["env"]):
                    table.col(name)[:n] = out[:, i]
            else:
                for name, _ in _FAMILIES[fam]:
                    if name in host:
                        table.col(name)[:n] = host[name]
            if "src" in host:
                self._apply_src(store, fam, host["src"], res)
                res["src"] = None  # resolved; fresh tracking next round
            if res.get("written") is not None:
                # downloaded state now equals the host columns: only columns
                # dirtied AFTER this flush need the next download
                res["written"] = set()
            if el_dt_changed:
                self._enqueue_elem_garbage(store, np.arange(n),
                                           table.add_t[:n], table.del_t[:n],
                                           old_dt)
            # host now equals device for the whole plane: later flushes
            # skip this family until new merges dirty it again
            res["dirty"] = []

        for fam, (rows_d, g, src_dev) in partial.items():
            res = self._res[fam]
            table = _host_table(store, fam)
            nd = len(rows_d)
            if fam == "cnt":
                # incremental sum delta needs the PRE-flush host
                # contributions of exactly the dirty rows
                old_contrib = store.cnt.val[rows_d] - store.cnt.base[rows_d]
            host = {}
            for name in list(g):
                h = np.asarray(g.pop(name))[:nd]
                self.bytes_d2h += int(h.nbytes)
                host[name] = h
            if fam == "env":
                out = host.get("stack")
                if out is not None:
                    for i, (name, _) in enumerate(_FAMILIES["env"]):
                        table.col(name)[rows_d] = out[:, i]
            else:
                for name, _ in _FAMILIES[fam]:
                    if name in host:
                        table.col(name)[rows_d] = host[name]
            if src_dev is not None:
                src_h = np.asarray(src_dev)[:nd]
                self.bytes_d2h += int(src_h.nbytes)
                self._apply_src(store, fam, src_h, res, rows=rows_d)
                res["src"] = None
            if fam == "cnt":
                new_contrib = store.cnt.val[rows_d] - store.cnt.base[rows_d]
                delta = new_contrib - old_contrib
                changed = np.nonzero(delta)[0]
                if len(changed):
                    np.add.at(store.keys.cnt_sum,
                              store.cnt.kid[rows_d[changed]],
                              delta[changed])
            # el del side is host-maintained on the micro path; its GC
            # entries ride _el_del_touched below
            res["written"] = set()
            res["dirty"] = []

        if self._el_del_touched:
            # host-maintained del side (el src path): with add_t now
            # reconstructed, queue rows that ended up dead.  old_dt=-1:
            # every touched row's del_t advanced by construction, so the
            # shared helper's "newly dead" filter reduces to at < dt.
            rows = np.unique(np.concatenate(self._el_del_touched))
            self._el_del_touched.clear()
            self._enqueue_elem_garbage(
                store, rows, store.el.add_t[rows], store.el.del_t[rows],
                np.full(len(rows), -1, dtype=_I64))
        self._val_pool.clear()
        self._pool_size = 0
        self._pool_bytes = 0
        # host val/base mutate ONLY through the two consume loops above:
        # a whole-plane cnt flush re-derives every sum (device segment-sum
        # when the backend supports it), the dirty path already applied
        # its incremental deltas, and an untouched cnt mirror left the
        # sums exact from the previous flush
        if "cnt" in pending and self._res["cnt"]["n"]:
            self._recompute_sums(store)
        self._flush_tns(store)
        self.needs_flush = False
        self.family_secs["flush"] += _time.perf_counter() - t0

    def release_device_pools(self, store: KeySpace) -> None:
        """Hard-watermark memory reclaim (server/overload.py): flush
        resident state down to the host, then RELEASE the device
        mirrors, win-value pools, and tensor payload pools — they
        refill lazily on the next merge round (mirror_rebuilds counts
        it).  Unlike discard_resident this is loss-free: flush() runs
        first, so host state is exact when the device copies drop."""
        self.flush(store)
        self._res.clear()
        self._val_pool.clear()
        self._pool_size = 0
        self._pool_bytes = 0
        self._el_del_touched.clear()
        if self._tns_pools:
            self._tns_pools.clear()
            self._tns_bytes = 0
            self._tns_epoch += 1

    def discard_resident(self) -> None:
        """Forget ALL resident device state WITHOUT flushing — only valid
        when the host store itself is being discarded (Node.
        reset_for_full_resync); a fresh store's fam_ver could otherwise
        collide with a stale mirror's recorded version."""
        self._res.clear()
        self._val_pool.clear()
        self._pool_size = 0
        self._pool_bytes = 0
        self._el_del_touched.clear()
        self._tns_pools.clear()
        self._tns_bytes = 0
        self._tns_epoch += 1
        self.needs_flush = False

    def _apply_src(self, store: KeySpace, fam: str, src_h: np.ndarray,
                   res: dict, rows: Optional[np.ndarray] = None) -> None:
        """Consume the downloaded src plane: (a) RECONSTRUCT the
        winner-carried int64 columns from the host pool (bit-identical to
        the device state by construction — the kernels set column and src
        under the same win predicate), and (b) assign deferred win VALUES
        (set rows — valueless by construction — are skipped wholesale).

        `rows`: table rows src_h's positions map to (the dirty-row
        partial flush downloads a GATHERED src slice); None = src_h is
        the whole plane and positions ARE table rows."""
        rows_all = np.nonzero(src_h >= 0)[0]
        if not len(rows_all):
            return
        pool = self._val_pool
        gids_all = src_h[rows_all].astype(_I64)
        if rows is not None:
            # sorted-unique dirty rows: positions map through in order,
            # so rows_all stays strictly ascending (the contiguity fast
            # path below still holds)
            rows_all = rows[rows_all]
        if len(pool) == 1:
            # single staged segment (fully combined round): skip the
            # segment sort entirely
            order = np.arange(len(gids_all))
            uniq = np.zeros(1, dtype=_I64)
            starts = np.zeros(1, dtype=_I64)
            ends = np.array([len(order)])
        else:
            bases = np.fromiter((b for b, _, _ in pool), dtype=_I64,
                                count=len(pool))
            segs_all = np.searchsorted(bases, gids_all, side="right") - 1
            order = np.argsort(segs_all, kind="stable")
            uniq, starts = np.unique(segs_all[order], return_index=True)
            ends = np.append(starts[1:], len(order))
        # (a) column reconstruction, vectorized one pool segment at a time
        recon = res.get("recon")
        if recon:
            table = _host_table(store, fam)
            for s, lo, hi in zip(uniq.tolist(), starts.tolist(),
                                 ends.tolist()):
                sel = order[lo:hi]
                r_sel = rows_all[sel]
                off = gids_all[sel] - pool[s][0]
                cols = pool[s][2]
                for host_col, pool_col in recon.items():
                    table.col(host_col)[r_sel] = \
                        np.asarray(cols[pool_col])[off]
        # (b) win values — per SEGMENT, not per row: catch-up slots are
        # created in contiguous blocks, so most segments assign via one
        # C-speed list-slice write (the per-row loop with a pool lookup
        # each iteration dominated value-heavy flushes)
        if fam == "cnt":
            return  # counters carry no object values
        if fam == "reg":
            vmask = np.ones(len(rows_all), dtype=bool)
            target = store.reg_val
        else:
            vmask = np.isin(store.keys.enc[store.el.kid[rows_all]],
                            S.VALUE_ENCS)
            target = store.el_val
        for s, lo, hi in zip(uniq.tolist(), starts.tolist(), ends.tolist()):
            sel = order[lo:hi]
            m = vmask[sel]
            if not m.any():
                continue
            sel = sel[m]
            r_sel = rows_all[sel]
            b, vals, _ = pool[s]
            if vals is None:
                # all-valueless batch: winning rows CLEAR the slot value
                # (CPU parity — local-loses replaces with None)
                picked = [None] * len(r_sel)
            else:
                picked = list(map(vals.__getitem__,
                                  (gids_all[sel] - b).tolist()))
            r0 = int(r_sel[0])
            # r_sel is strictly ascending and unique by construction
            # (np.nonzero order preserved through the stable argsort), so
            # the endpoint check alone proves contiguity
            if int(r_sel[-1]) == r0 + len(r_sel) - 1:
                target[r0:r0 + len(r_sel)] = picked
            else:
                for r, v in zip(r_sel.tolist(), picked):
                    target[r] = v

    # ------------------------------------------------------ resident state

    def _resident_state(self, store: KeySpace, fam: str, n: int,
                        micro: bool = False):
        """Device state dict for family `fam` covering rows [0, n); grows
        (neutral-filled) as the host table grows.  Returns (cols, cap).

        Staleness: the mirror records the host plane's write version at
        build time; an op-path write or GC to THIS plane (KeySpace.touch)
        forces a rebuild from host — other planes' mirrors survive.

        `micro`: the caller is the steady scatter path, which keeps LWW
        pair columns PRE-SPLIT as hi/lo 32-bit planes between rounds
        (`res["split"]` — ops/pallas_dense.py scatter_pair_src_split).
        Bulk callers (micro=False) and the grow path speak int64, so
        they JOIN any split cache back into `cols` first; the micro
        reuse path leaves the split intact — that is the whole point of
        the layout (zero O(plane) split/join passes in steady state)."""
        res = self._res.get(fam)
        ver = store.fam_ver[fam]
        if res is not None and res.get("split") and \
                (not micro or n > res["cap"]):
            self._join_split(res)
        if res is not None and res.get("ver") != ver:
            # rebuild from host.  A stale mirror never holds unflushed
            # device data: the Node flushes before every op-path write, so
            # whatever bumped this plane's version found the mirror already
            # synced.  (needs_flush may be True here from EARLIER families
            # of this same merge round — their mirrors are not stale.)
            # Dropping a stale mirror that still holds unflushed merged
            # columns would silently lose merge results — that is a broken
            # flush-before-touch invariant somewhere upstream; fail loud
            # (a real raise, not an assert: `python -O` must not strip the
            # only guard between a dispatch-table bug and silent data loss)
            if res.get("written"):
                raise RuntimeError(
                    f"{fam} mirror invalidated with unflushed merge data "
                    "(flush-before-touch invariant broken upstream)")
            self.mirror_rebuilds[fam] += 1
            res = None
        cap = self._sp_size(n)
        spec = _FAMILIES[fam]
        if res is None:
            table = _host_table(store, fam)
            if fam == "env":
                host = np.stack([table.col(c)[:n] for c, _ in spec], axis=-1)
                cols = {"stack": self._put_state(_pad(host, cap, 0))}
            else:
                cols = {c: self._put_state(
                    _pad(table.col(c)[:n], cap, fill)) for c, fill in spec}
        elif n > res["cap"]:
            old = res["cols"]
            delta = cap - res["cap"]
            if fam == "env":
                cols = {"stack": self._grow(old["stack"], delta, 0,
                                            cols=len(spec))}
            else:
                cols = {c: self._grow(old[c], delta, fill)
                        for c, fill in spec}
        else:
            cols = res["cols"]
            cap = res["cap"]
        # `dirty`/`recon`/`split` survive a reuse/grow (the micro path
        # appends touched rows between flushes); a fresh build starts
        # CLEAN (dirty=[] — host == device at build, nothing to download)
        self._res[fam] = {"cols": cols, "n": n, "cap": cap, "ver": ver,
                          "src": res.get("src") if res else None,
                          "written": res.get("written", set()) if res
                          else set(),
                          "recon": res.get("recon") if res else None,
                          "split": res.get("split") if res else None,
                          "dirty": res.get("dirty") if res else []}
        return cols, cap

    @staticmethod
    def _join_split(res: dict) -> None:
        """Fold a family's pre-split hi/lo pair cache back into its int64
        `cols` (bulk kernels, state growth, and mirror rebuilds speak
        int64).  One O(plane) pass per steady→bulk transition — the
        per-round pass the split layout exists to remove."""
        from ..ops import pallas_dense as PD
        for name, (hi, lo) in res["split"].items():
            res["cols"][name] = PD.join_plane(hi, lo)
        res["split"] = None

    def _family_done(self, fam: str, cols: dict, n: int, cap: int,
                     src=None, written=None, recon=None) -> None:
        """Record post-merge device state.  `written` marks which columns
        the kernels actually scattered into since the mirror was created —
        flush downloads only those (an untouched mirror column equals the
        host column it was uploaded from, padding included).  None = all.
        `recon` maps winner-carried device columns to their pool column
        name — those skip the flush download entirely and reconstruct on
        host from the win pool (valid only while `src` is tracked)."""
        prev = self._res.get(fam) or {}
        w = prev.get("written", set())
        w |= set(cols) if written is None else written
        self._res[fam] = {"cols": cols, "n": n, "cap": cap, "written": w,
                          "ver": prev.get("ver"),
                          "src": src if src is not None else prev.get("src"),
                          "recon": recon if recon is not None
                          else prev.get("recon")}
        self.needs_flush = True

    def _drop_family(self, store: KeySpace, fam: str) -> None:
        """A host-side (scatter) update is about to touch this family: sync
        device state down first, then forget the mirror."""
        if fam in self._res:
            self.flush(store)
            del self._res[fam]

    # ------------------------------------------------- resident micro merges
    # The steady-state placement (the ISSUE 8 routing inversion): op-stream
    # micro-batches — the serve/replication coalescers' flushes — merge IN
    # PLACE against the resident device planes instead of falling back to
    # the host micro strategy.  Duplicate slots fold on host with the exact
    # shared reductions from engine/hostbatch.py, the unique winners
    # scatter once per family (Pallas gather-compare-scatter or its XLA
    # twin, per _pallas_or_xla), the env plane stays HOST-AUTHORITATIVE
    # (its merge is a collision-free max into host columns — zero device
    # bytes, and key-dt reads never need a flush), and every scatter's
    # rows land in the family's dirty set so flush() downloads only them.

    def host_stale(self, families) -> bool:
        """True when any of `families` holds unflushed device-side merge
        state (its host columns lag the device).  Callers that provably
        read only planes OUTSIDE the stale set may skip the flush — the
        narrow read-barrier Node.ensure_flushed_for exposes to the
        steady-state coalescers (env is host-authoritative on the micro
        path, so dt reads cost no round-trip)."""
        if not self.needs_flush:
            return False
        for fam in families:
            if fam == "tns":
                if any(p["dirty"] for p in self._tns_pools.values()):
                    return True
                continue
            res = self._res.get(fam)
            if res is not None and (res.get("written")
                                    or res.get("src") is not None):
                return True
        return False

    @staticmethod
    def _micro_touched(resolved):
        """Device families a micro round actually merges (env is host-side
        and never gates the routing decision)."""
        from ..utils.native_tables import nonnull_mask
        fams = set()
        for b, _ in resolved:
            if "reg" not in fams and b.n_keys and \
                    nonnull_mask(b.reg_val).any():
                fams.add("reg")
            if len(b.cnt_ki):
                fams.add("cnt")
            if len(b.el_ki):
                fams.add("el")
            if len(b.tns_ki):
                fams.add("tns")
        return fams

    def _micro_placement(self, store: KeySpace, resolved):
        """Per-family steady-state routing: {fam: True=device in-place,
        False=host twin} over the device families this round touches —
        or None when the steady path is off entirely (the legacy
        whole-round host fallback, pre-round-12 behavior).  Families
        route INDEPENDENTLY: CRDT planes are independent by construction
        (the same property that licenses the stage/dispatch overlap), so
        a cold el plane — its version just bumped by a barrier op —
        merges on its host twin while a warm cnt plane keeps merging in
        place.  Warm = mirror already resident and fresh, or host
        version stable for more than `warmup` consecutive micro rounds
        (mixed op/merge traffic would otherwise re-upload a full mirror
        every round just to merge a few hundred rows into it)."""
        if not (self.steady and self.resident):
            return None
        placement = {}
        for fam in self._micro_touched(resolved):
            ver = store.fam_ver[fam]
            if fam == "tns":
                # the tensor plane's mirror is its payload pool set
                if self._tns_pools and self._tns_ver == ver:
                    placement[fam] = True
                    continue
                res = None
            else:
                res = self._res.get(fam)
            if res is not None and res.get("ver") == ver:
                placement[fam] = True  # resident and fresh: free to ride
                continue
            last_ver, streak = self._warm_streak.get(fam, (-1, 0))
            streak = streak + 1 if last_ver == ver else 1
            self._warm_streak[fam] = (ver, streak)
            placement[fam] = streak > self.warmup
        return placement

    def _merge_micro_resident(self, store: KeySpace, b: ColumnarBatch,
                              kid_of: np.ndarray, st: MergeStats,
                              placement: dict) -> None:
        """Merge ONE op-stream micro-batch under the steady placement:
        warm families scatter in place against resident device planes —
        the device twin of engine/hostbatch.merge_host_batch, fold for
        fold (both sides use the very same fold_* reductions, so the
        scattered winners ARE the host path's winners) — and cold
        families take their host twins directly.  Differential-tested
        byte-identical in tests/test_resident_steady.py."""
        from ..utils.native_tables import nonnull_mask
        from .hostbatch import (_apply_cnt_pair, _merge_el, _merge_env,
                                _merge_reg, _resolve_el_rows, fold_el_rows,
                                fold_pair_rows)
        if "env" in self._res:
            # forced-fold catch-ups can leave a device env mirror; the
            # micro path keeps env host-authoritative, so sync it down
            # once and merge on host from here on
            self._drop_family(store, "env")
        valid = kid_of >= 0
        all_valid = bool(valid.all())
        if b.n_keys:
            kids = kid_of if all_valid else kid_of[valid]
            if len(kids):
                mat = np.stack([b.key_ct, b.key_mt, b.key_dt,
                                b.key_expire], axis=-1)
                _merge_env(store, kids, mat if all_valid else mat[valid])
            em = valid & (b.key_enc == S.ENC_BYTES) & \
                nonnull_mask(b.reg_val)
            idx = np.nonzero(em)[0]
            if len(idx):
                if placement.get("reg"):
                    wk, wt, wn, srci = fold_pair_rows(
                        kid_of[idx], b.reg_t[idx], b.reg_node[idx])
                    vals = list(map(b.reg_val.__getitem__,
                                    idx[srci].tolist()))
                    self._micro_scatter_pair(store, "reg",
                                             ("rv_t", "rv_node"),
                                             wk, wt, wn, vals)
                else:
                    _merge_reg(store, kid_of[idx], b.reg_t[idx],
                               b.reg_node[idx],
                               list(map(b.reg_val.__getitem__,
                                        idx.tolist())))

        if len(b.cnt_ki):
            kid_arr = kid_of[b.cnt_ki]
            keep = np.nonzero(kid_arr >= 0)[0]
            if len(keep):
                st.counter_rows += len(keep)
                sel = slice(None) if len(keep) == len(kid_arr) else keep
                rows = self._resolve_cnt_rows(store, kid_arr[sel],
                                              b.cnt_node[sel])
                bt = b.cnt_base_t[sel]
                base_neutral = bool((bt == K.NEUTRAL_T).all())
                if placement.get("cnt"):
                    # (uuid, val) pair: LWW on uuid, max-value tie — the
                    # winners reconstruct from the pool at flush, so the
                    # two widest counter columns never download
                    wr, wu, wv, _ = fold_pair_rows(rows, b.cnt_uuid[sel],
                                                   b.cnt_val[sel])
                    self._micro_scatter_pair(store, "cnt", ("uuid", "val"),
                                             wr, wu, wv, None)
                    if not base_neutral:
                        # base pair (counter deletes — rare): no src
                        # tracking, its dirty rows download at flush
                        wr2, wbt, wb, _ = fold_pair_rows(rows, bt,
                                                         b.cnt_base[sel])
                        self._micro_scatter_pair(store, "cnt",
                                                 ("base_t", "base"),
                                                 wr2, wbt, wb, None,
                                                 src=False)
                else:
                    _apply_cnt_pair(store, rows, b.cnt_val[sel],
                                    b.cnt_uuid[sel], "val", "uuid", 1)
                    if not base_neutral:
                        _apply_cnt_pair(store, rows, b.cnt_base[sel], bt,
                                        "base", "base_t", -1)

        if len(b.el_ki):
            kid_arr = kid_of[b.el_ki]
            keep = np.nonzero(kid_arr >= 0)[0]
            if len(keep):
                st.elem_rows += len(keep)
                if len(keep) == len(kid_arr):
                    sel = slice(None)
                    members = b.el_member
                    vals = b.el_val
                else:
                    sel = keep
                    members = list(map(b.el_member.__getitem__,
                                       keep.tolist()))
                    vals = list(map(b.el_val.__getitem__, keep.tolist()))
                rows = _resolve_el_rows(store, kid_arr[sel], members)
                if not placement.get("el"):
                    _merge_el(store, rows, b.el_add_t[sel],
                              b.el_add_node[sel], b.el_del_t[sel], vals)
                else:
                    wr, wat, wan, d_red, srci = fold_el_rows(
                        rows, b.el_add_t[sel], b.el_add_node[sel],
                        b.el_del_t[sel])
                    if b.el_has_vals is False or not has_values(vals):
                        wvals = None  # winning valueless adds still CLEAR
                        # the slot value at flush (pool vals=None contract)
                    else:
                        wvals = list(map(vals.__getitem__, srci.tolist()))
                    self._micro_scatter_pair(store, "el",
                                             ("add_t", "add_node"),
                                             wr, wat, wan, wvals)
                    # del side: plain max applied straight to the HOST
                    # column, with the DEVICE del_t plane advanced in
                    # lockstep (one max scatter, only when the batch
                    # actually carries deletes — rare in steady state).
                    # A host-only write would leave the mirror's del_t
                    # stale-but-"fresh", and a later forced-fold bulk
                    # round (bulk_elems reads and re-downloads del_t)
                    # would regress the host column and resurrect the
                    # deleted elements.  Newly-dead rows queue for GC at
                    # flush, after add_t reconstruction.
                    nz = np.flatnonzero(d_red)
                    if len(nz):
                        sel_r = wr[nz]
                        cur = store.el.del_t[sel_r]
                        dv = d_red[nz]
                        adv = dv > cur
                        if adv.any():
                            rows_adv = sel_r[adv]
                            dv_adv = dv[adv]
                            store.el.del_t[rows_adv] = dv_adv
                            self._el_del_touched.append(rows_adv)
                            res = self._res["el"]
                            sp = res["cap"]
                            np2 = K.next_pow2(max(len(rows_adv),
                                                  self.MICRO_SCATTER_PAD))
                            res["cols"]["del_t"] = B.bulk_max1(
                                res["cols"]["del_t"],
                                self._batch_idx(rows_adv, 0, sp, np2),
                                self._put_batch(_pad(dv_adv, np2, 0)))

        if len(b.tns_ki):
            self._merge_micro_tns(store, b, kid_of, st,
                                  device=bool(placement.get("tns")))

        for i, key in enumerate(b.del_keys):
            store.record_key_delete(key, int(b.del_t[i]))

    def _micro_scatter_pair(self, store: KeySpace, fam: str, pair, wr,
                            wp, ws, vals, src: bool = True) -> None:
        """Scatter one folded LWW pair in place against `fam`'s resident
        planes.  `pair` = (primary, secondary) column names; the win rule
        is lexicographic (primary, secondary) > current — exactly
        hostbatch's fold rule and ops/bulk._pair_win.  With `src`
        tracking (default) the winners' pool ids land in the resident
        src plane: flush downloads the int32 src slice and reconstructs
        both columns AND win values from the host pool.  src=False (the
        rare counter base pair) keeps its winner on device and downloads
        its dirty rows at flush."""
        nw = len(wr)
        if not nw:
            return
        n = _fam_rows(store, fam)
        cols, sp = self._resident_state(store, fam, n, micro=True)
        res = self._res[fam]
        pcol, scol = pair
        # pad-floor the batch length (see MICRO_SCATTER_PAD) — but only
        # while a free pad-target row exists (nw < sp); a batch covering
        # every plane row pads to itself (nw == sp == pow2, no pads)
        np2 = K.next_pow2(nw if nw >= sp
                          else max(nw, self.MICRO_SCATTER_PAD))
        if src:
            src_d = self._src_state(fam, sp)
            pb = self._pool_add(vals, **{pcol: wp, scol: ws})
            from ..ops import pallas_dense as PD

            def _pallas(interp):
                # the pair columns live PRE-SPLIT between rounds (the
                # retired PR 8 follow-up): a warm plane pays no O(plane)
                # int64<->hi/lo pass — only the first round after a bulk
                # merge / rebuild splits, and only a bulk round joins
                split = res.get("split") or {}
                p_sp = split.get(pcol) or PD.split_plane(cols[pcol])
                s_sp = split.get(scol) or PD.split_plane(cols[scol])
                pad = self._scatter_pad_row(wr, nw, sp) if np2 > nw else 0
                o = PD.scatter_pair_src_split(
                    p_sp[0], p_sp[1], s_sp[0], s_sp[1], src_d,
                    self._put_batch(_pad(wr.astype(_I32), np2, pad)),
                    self._put_batch(_pad(wp, np2, K.NEUTRAL_T)),
                    self._put_batch(_pad(ws, np2, K.NEUTRAL_T)),
                    np.int32(pb), interpret=interp)
                return ("split", o)

            def _xla():
                if res.get("split"):
                    # a mid-stream pallas→XLA fallback: re-join so the
                    # int64 kernels see the split cache's truth
                    self._join_split(res)
                return B.bulk_lww_src(
                    cols[pcol], cols[scol], src_d,
                    self._batch_idx(wr, 0, sp, np2),
                    self._put_batch(_pad(wp, np2, K.NEUTRAL_T)),
                    self._put_batch(_pad(ws, np2, K.NEUTRAL_T)), pb)

            out = self._pallas_or_xla(_pallas, _xla)
            if isinstance(out, tuple) and len(out) == 2 and \
                    out[0] == "split":
                o_p_hi, o_p_lo, o_s_hi, o_s_lo, src2 = out[1]
                split = res.get("split") or {}
                split[pcol] = (o_p_hi, o_p_lo)
                split[scol] = (o_s_hi, o_s_lo)
                res["split"] = split
                self._micro_done(fam, {}, src=src2,
                                 recon={pcol: pcol, scol: scol},
                                 written={pcol, scol}, rows=wr)
                return
            p2, s2, src2 = out
            self._micro_done(fam, {pcol: p2, scol: s2}, src=src2,
                             recon={pcol: pcol, scol: scol},
                             written={pcol, scol}, rows=wr)
        else:
            # (the rare counter base pair: XLA int64 kernels; these
            # columns are never split-cached — only src-tracked pairs)
            p2, s2, _win = B.bulk_lww(
                cols[pcol], cols[scol], self._batch_idx(wr, 0, sp, np2),
                self._put_batch(_pad(wp, np2, K.NEUTRAL_T)),
                self._put_batch(_pad(ws, np2, K.NEUTRAL_T)))
            self._micro_done(fam, {pcol: p2, scol: s2}, src=None,
                             recon=None, written={pcol, scol}, rows=wr)

    @staticmethod
    def _scatter_pad_row(rows: np.ndarray, n: int, sp: int) -> int:
        """An in-range state row NO real batch row targets (`rows` is
        sorted unique over [0, sp)): a Pallas pad step re-writes its
        target from a read that may predate a real step's merge, so a pad
        aliased onto a real target would silently revert the merge
        (ops/pallas_dense.py contract; pinned in test_pallas_dense.py).
        Unique rows over a pow2 plane always leave a free row whenever
        padding is needed (n < pow2(n) <= sp)."""
        last = int(rows[n - 1])
        if last + 1 < sp:
            return last + 1
        # rows - iota is non-decreasing; its first step to >= 1 marks the
        # first absent row
        d = rows - np.arange(n, dtype=np.int64)
        return int(np.searchsorted(d, 1))

    def _micro_done(self, fam: str, cols: dict, src, recon,
                    written: set, rows: np.ndarray) -> None:
        """Fold a micro scatter's results into the family record: updated
        device columns, src/recon tracking, written columns, and the
        touched rows appended to the dirty set (a bulk-merged plane —
        dirty None — stays whole-plane)."""
        res = self._res[fam]
        res["cols"].update(cols)
        if src is not None:
            res["src"] = src
        if recon is not None:
            res["recon"] = dict(recon) if res.get("recon") is None \
                else {**res["recon"], **recon}
        res["written"] |= written
        if res.get("dirty") is not None:
            res["dirty"].append(np.asarray(rows))
        self.needs_flush = True

    def _recompute_sums(self, store: KeySpace) -> None:
        """Counter-sum re-derivation after a whole-plane cnt flush.  On a
        Pallas-capable backend the segment-sum runs ON DEVICE over the
        resident slot contributions (slot kids upload as int32, only the
        [n_keys] sums download — val/base never cross the link); the
        host bincount pass covers everything else (the CPU default,
        where uploading to sum would cost more than it saves).  All
        paths are exact int64 — bit-identical to
        KeySpace.recompute_counter_sums."""
        from ..ops import pallas_dense as PD
        res = self._res.get("cnt")
        n = store.cnt.n
        nk = store.keys.n
        be = self._fold_backend()
        if not (be.startswith("pallas") and res is not None
                and res["n"] == n and n and nk
                and nk <= PD.SEGMENT_SUM_MAX_SEG):
            store.recompute_counter_sums()
            return
        from ..ops import dense as D
        if res.get("split"):
            # steady micro rounds left the val/uuid truth in the
            # pre-split pair cache — the int64 cols are stale-by-design
            # (the split-plane law); join before the device sum reads
            # them, or cnt_sum would re-derive from pre-merge values
            self._join_split(res)
        cols = res["cols"]
        ids = self._put_batch(store.cnt.kid[:n].astype(_I32))
        contrib = cols["val"][:n] - cols["base"][:n]
        sums = self._pallas_or_xla(
            lambda interp: PD.segment_sum(ids, contrib, n_seg=nk,
                                          interpret=interp),
            lambda: D.segment_sum(ids, contrib, n_seg=nk))
        store.keys.cnt_sum[:nk] = np.asarray(self._device_get(sums))

    # ------------------------------------------------------ tensor registers
    # The tensor-valued register family (crdt/tensor.py): contributor
    # slot STAMPS (uuid/cnt columns) are host-authoritative — the merge
    # decisions are tiny LWW compares, exactly the env-plane rule — while
    # the payload ARRAYS, the part whose per-value work actually
    # dominates, live in resident device pools keyed by (dtype, elems).
    # A micro round folds each batch's duplicate slots on host, wins
    # against the host uuid column, and scatters ONLY the winning
    # payloads into the pool (one device call per class per batch);
    # flush gathers and downloads exactly the dirty pool slots.  Batched
    # reads (`tensor_read_many`) reduce contributor stacks ON DEVICE
    # with the canonical-order kernels (ops/pallas_dense.py
    # tensor_reduce + XLA twins) — byte-identical to the host reference
    # (KeySpace.tensor_read), differential-tested.

    def _tns_check(self, store: KeySpace) -> None:
        """Tensor-pool staleness: an op-path tensor write bumped the
        plane version, so every clean payload mirror may be stale —
        drop the pools (they refill lazily).  Dirty slots present at a
        version bump mean the flush-before-touch invariant broke
        upstream: fail loud, exactly like _resident_state."""
        ver = store.fam_ver["tns"]
        if self._tns_ver != ver:
            if any(p["dirty"] for p in self._tns_pools.values()):
                raise RuntimeError(
                    "tns pools invalidated with unflushed payloads "
                    "(flush-before-touch invariant broken upstream)")
            self._tns_pools.clear()
            self._tns_bytes = 0
            self._tns_ver = ver
            self._tns_epoch += 1

    def _tns_pool(self, store: KeySpace, meta) -> dict:
        key = (meta.dtype_code, meta.elems)
        pool = self._tns_pools.get(key)
        if pool is None:
            from ..ops import pallas_dense as PD
            kp = max(K.next_pow2(meta.elems), PD.TENSOR_BLOCK)
            pool = {"buf": None, "rows": np.full(0, -1, dtype=_I64),
                    "map": {}, "n": 0, "cap": 0, "dirty": set(),
                    "Kp": kp, "elems": meta.elems, "dtype": meta.dtype}
            self._tns_pools[key] = pool
        return pool

    def _tns_slots(self, pool: dict, rows_store) -> np.ndarray:
        """Pool slots for store rows, allocating (and growing the device
        buffer with zero rows) for rows not yet resident."""
        jnp = self._jax.numpy
        m = pool["map"]
        need = sum(1 for r in rows_store if r not in m)
        if pool["n"] + need > pool["cap"]:
            cap = K.next_pow2(max(pool["n"] + need, 64))
            grown = np.full(cap, -1, dtype=_I64)
            grown[: len(pool["rows"])] = pool["rows"]
            pool["rows"] = grown
            zeros = jnp.zeros((cap - pool["cap"], pool["Kp"]),
                              dtype=pool["dtype"].name)
            pool["buf"] = zeros if pool["buf"] is None else \
                jnp.concatenate([pool["buf"], zeros])
            self._tns_bytes += \
                (cap - pool["cap"]) * pool["Kp"] * pool["dtype"].itemsize
            pool["cap"] = cap
        out = np.empty(len(rows_store), dtype=_I64)
        for j, r in enumerate(rows_store):
            slot = m.get(r)
            if slot is None:
                slot = pool["n"]
                pool["n"] = slot + 1
                m[r] = slot
                pool["rows"][slot] = r
            out[j] = slot
        return out

    # pow2 pad floor for tensor scatter stacks: winner counts vary per
    # micro round, and each pow2 bucket is a pool_scatter re-trace —
    # padding to a floor collapses the shape space (same reasoning as
    # MICRO_SCATTER_PAD; pad rows scatter out of range and drop)
    TNS_SCATTER_PAD = 128

    def _tns_scatter(self, pool: dict, slots: np.ndarray,
                     mats: list, dirty: bool) -> None:
        """Scatter payload rows into a pool in one device call.  `mats`
        are SIZE-VALIDATED payloads (wire bytes or flat arrays of the
        pool dtype); `dirty` marks the slots device-newer than the host
        list (merge winners) — uploads that MIRROR host payloads (read
        staging) stay clean.

        Hot path: an all-bytes batch whose elems fill the pool width
        stacks via one C-speed join + zero-copy frombuffer instead of a
        per-row fill loop (the fill loop was a top merge cost in the
        tensor bench)."""
        from ..ops import dense as D
        w = len(slots)
        wp = K.next_pow2(max(w, self.TNS_SCATTER_PAD))
        kp = pool["Kp"]
        dt = pool["dtype"]
        # (wire payloads are little-endian; the zero-copy path needs the
        # native order to match — every supported target is LE)
        if pool["elems"] == kp and w and np.little_endian and \
                all(type(m) is bytes for m in mats):
            flat = np.frombuffer(b"".join(mats), dtype=dt).reshape(w, kp)
            stack = flat if wp == w else \
                np.concatenate([flat, np.zeros((wp - w, kp), dtype=dt)])
        else:
            stack = np.zeros((wp, kp), dtype=dt)
            for j, m in enumerate(mats):
                arr = m if isinstance(m, np.ndarray) \
                    else np.frombuffer(m, dtype=dt.newbyteorder("<"))
                stack[j, : len(arr)] = arr
        idx = np.empty(wp, dtype=_I32)
        idx[:w] = slots
        if wp > w:  # out-of-range pads drop
            idx[w:] = pool["cap"] + np.arange(wp - w, dtype=_I32)
        pool["buf"] = D.pool_scatter(pool["buf"], self._put_batch(idx),
                                     self._put_batch(stack))
        if dirty:
            pool["dirty"].update(slots.tolist())

    def _merge_micro_tns(self, store: KeySpace, b: ColumnarBatch,
                         kid_of: np.ndarray, st: MergeStats,
                         device: bool) -> None:
        """Merge one batch's tensor rows.  `device=False` is the host
        reference (engine/hostbatch.merge_host_tns — the per-row loop);
        `device=True` makes the same decisions in batch: fold duplicate
        slots, win against the host uuid column, scatter the winning
        payloads into the resident pools.  Differential-tested
        byte-identical (tests/test_tensor_family.py)."""
        from ..crdt import tensor as T
        from .hostbatch import merge_host_tns
        if not device:
            n0 = st.tensor_rows
            merge_host_tns(store, b, kid_of, st)
            self.tns_host_rows += st.tensor_rows - n0
            return
        self._tns_check(store)
        kid_arr = kid_of[b.tns_ki]
        keep = np.nonzero(kid_arr >= 0)[0]
        if not len(keep):
            return
        st.tensor_rows += len(keep)
        self.tns_dev_rows += len(keep)
        # count gate FIRST, matching the host reference's check order:
        # tensor_merge_row runs check_count BEFORE installing a fresh
        # key's config, so a batch whose every row for a key is
        # count-invalid must leave tns_meta uninstalled on BOTH paths
        cnt_ok = b.tns_cnt[keep] >= 1
        if not cnt_ok.all():
            log.error("skipping %d tensor rows: contribution count < 1",
                      int((~cnt_ok).sum()))
            keep = keep[cnt_ok]
            if not len(keep):
                return
        # per-key config install/validate + per-row payload checks: the
        # same skip rules as KeySpace.tensor_merge_row, decided once per
        # distinct key where possible (bad rows drop exactly like type
        # conflicts).  The common case — one config across the whole
        # batch (a homogeneous aggregation stream) — validates once per
        # DISTINCT KEY plus one vectorized size pass, no per-row python.
        idx_list = keep.tolist()
        metas: dict = {}
        ok = np.ones(len(keep), dtype=bool)
        cfg0 = b.tns_cfg[idx_list[0]]
        uniform = True
        for i in idx_list[1:]:
            c = b.tns_cfg[i]
            if c is not cfg0 and c != cfg0:
                uniform = False
                break
        if uniform:
            bad_kids = None
            for kid in np.unique(kid_arr[keep]).tolist():
                meta = store.tns_meta.get(kid)
                try:
                    if meta is None:
                        meta = T.unpack_config(cfg0)
                        store.tns_meta[kid] = meta
                    elif T.pack_config(meta) != bytes(cfg0):
                        raise T.TensorConfigError("tensor config mismatch")
                    metas[kid] = meta
                except T.TensorConfigError as e:
                    log.error("skipping tensor rows for kid %d: %s",
                              kid, e)
                    metas[kid] = False
                    bad_kids = True
            if bad_kids:
                ok &= np.fromiter(
                    (metas[int(k)] is not False for k in kid_arr[keep]),
                    dtype=bool, count=len(keep))
            meta_u = next((m for m in metas.values()
                           if m is not False), None)
            if meta_u is not None:
                # the shared validity predicate (T.payload_ok) — the
                # same rule tensor_merge_row enforces via payload_array
                bad_sz = np.fromiter(
                    (not T.payload_ok(meta_u, p)
                     for p in (b.tns_payload[i] for i in idx_list)),
                    dtype=bool, count=len(keep))
                if bad_sz.any():
                    log.error("skipping %d tensor rows: bad payload "
                              "(size/dtype)", int(bad_sz.sum()))
                    ok &= ~bad_sz
        else:
            for j, i in enumerate(idx_list):
                kid = int(kid_arr[i])
                meta = metas.get(kid)
                if meta is None:
                    meta = store.tns_meta.get(kid)
                    cfg = b.tns_cfg[i]
                    try:
                        if meta is None:
                            meta = T.unpack_config(cfg)
                            store.tns_meta[kid] = meta
                        elif T.pack_config(meta) != bytes(cfg):
                            raise T.TensorConfigError(
                                "tensor config mismatch")
                    except T.TensorConfigError as e:
                        log.error("skipping tensor rows for kid %d: %s",
                                  kid, e)
                        metas[kid] = False
                        ok[j] = False
                        continue
                    metas[kid] = meta
                elif meta is False:
                    ok[j] = False
                    continue
                else:
                    cfg = b.tns_cfg[i]
                    if T.pack_config(meta) != bytes(cfg):
                        log.error("skipping tensor row for kid %d: "
                                  "config mismatch", kid)
                        ok[j] = False
                        continue
                if not T.payload_ok(meta, b.tns_payload[i]):
                    log.error("skipping tensor row for kid %d: bad "
                              "payload (size/dtype)", kid)
                    ok[j] = False
                    continue
                store.tensor_count_merge(meta)
        keep = keep[ok]
        if not len(keep):
            return
        if uniform:
            # per-strategy gauge: one bump per VALIDATED delivered row
            # (the host reference counts in tensor_merge_row at the
            # same point; a per-win count would depend on routing —
            # the device path folds duplicates before its win test)
            meta0 = next((m for m in metas.values() if m is not False),
                         None)
            if meta0 is not None:
                store.tensor_count_merge(meta0, len(keep))
        kids = kid_arr[keep]
        nodes = b.tns_node[keep]
        uuids = b.tns_uuid[keep]
        cnts = b.tns_cnt[keep]
        # resolve (kid, node) -> slot rows (creates neutral rows), then
        # fold intra-batch duplicates: LWW on uuid, FIRST occurrence on
        # exact ties (one node's equal stamps are the same write — the
        # host loop's strict > keeps the first too)
        rows = self._resolve_tns_rows(store, kids, nodes)
        order = np.lexsort((-np.arange(len(rows)), uuids, rows))
        r_s = rows[order]
        last = np.nonzero(np.append(r_s[1:] != r_s[:-1], True))[0]
        src = order[last]
        wr = r_s[last]
        wu = uuids[src]
        cur = store.tns.uuid[wr]
        win = wu > cur
        if not win.any():
            return
        w_rows = wr[win]
        w_src = src[win]
        store.tns.uuid[w_rows] = wu[win]
        store.tns.cnt[w_rows] = cnts[w_src]
        # winners grouped per pool class, scattered in one call each
        # (size-validated RAW payloads — _tns_scatter normalizes); host
        # payload entries stay STALE until flush (the stamps above are
        # what later merge decisions read — host-authoritative)
        if uniform:
            meta = next((m for m in metas.values() if m is not False),
                        None)
            if meta is not None:
                mats = [b.tns_payload[int(keep[s_i])]
                        for s_i in w_src.tolist()]
                pool = self._tns_pool(store, meta)
                slots = self._tns_slots(pool, w_rows.tolist())
                self._tns_scatter(pool, slots, mats, dirty=True)
        else:
            classes: dict = {}
            for r, s_i in zip(w_rows.tolist(), w_src.tolist()):
                kid = int(kids[s_i])
                meta = metas[kid]
                ent = classes.setdefault((meta.dtype_code, meta.elems),
                                         (meta, [], []))
                ent[1].append(r)
                ent[2].append(b.tns_payload[int(keep[s_i])])
            for meta, rws, mats in classes.values():
                pool = self._tns_pool(store, meta)
                slots = self._tns_slots(pool, rws)
                self._tns_scatter(pool, slots, mats, dirty=True)
        self.needs_flush = True
        if self._tns_bytes > self.tns_pool_cap:
            # residency cap: sync the dirty payloads down and release
            # the device pools (they refill lazily); loud in the log —
            # a workload thrashing the cap should raise it
            log.info("tensor pools over CONSTDB_TENSOR_POOL_MB; flushing "
                     "and dropping %d pools (%d bytes)",
                     len(self._tns_pools), self._tns_bytes)
            self._flush_tns(store)
            self._tns_pools.clear()
            self._tns_bytes = 0
            self._tns_epoch += 1

    def _resolve_tns_rows(self, store: KeySpace, kids: np.ndarray,
                          nodes: np.ndarray) -> np.ndarray:
        """(kid, node) -> store tensor slot rows, creating neutral slots
        for misses — the batched twin of KeySpace.tensor_slot_row."""
        ranks = np.fromiter((store.rank_of(int(x)) for x in nodes),
                            dtype=_I64, count=len(nodes))
        combos = (kids << KeySpace.NODE_RANK_BITS) | ranks
        rn0 = store.tns.n
        rows, n_new = store.tns_index.get_or_assign_batch(combos,
                                                          next_val=rn0)
        if n_new:
            created = np.nonzero(rows >= rn0)[0]
            uniq_rows, first = np.unique(rows[created], return_index=True)
            pos = created[first]
            if len(uniq_rows) != n_new or int(uniq_rows[0]) != rn0 or \
                    int(uniq_rows[-1]) != rn0 + n_new - 1:
                span = f"[{int(uniq_rows[0])}, {int(uniq_rows[-1])}]" \
                    if len(uniq_rows) else "[]"
                raise RuntimeError(
                    f"tns combo index issued non-contiguous rows {span} "
                    f"(n={len(uniq_rows)}) for block "
                    f"[{rn0}, {rn0 + n_new - 1}]")
            store.tns.append_block(n_new, kid=kids[pos], node=nodes[pos],
                                   uuid=K.NEUTRAL_T, cnt=0)
            store.tns_payload.extend([None] * n_new)
        return rows

    def _flush_tns(self, store: KeySpace) -> None:
        """Download dirty pool slots back into the host payload list —
        the tensor half of the dirty-row flush discipline."""
        for pool in self._tns_pools.values():
            dirty = pool["dirty"]
            if not dirty:
                continue
            slots = np.fromiter(dirty, dtype=_I64, count=len(dirty))
            slots.sort()
            self.flush_rows_full_equiv += pool["n"]
            self.flush_rows_downloaded += len(slots)
            np2 = K.next_pow2(max(len(slots), 1))
            idx = self._put_batch(_pad(slots.astype(_I32), np2, 0))
            got = np.asarray(self._device_get(
                B.gather_rows(pool["buf"], idx)))[: len(slots)]
            elems = pool["elems"]
            rows = pool["rows"]
            for j, slot in enumerate(slots.tolist()):
                store.tensor_assign_payload(int(rows[slot]),
                                            got[j, :elems].copy())
            pool["dirty"] = set()

    def tensor_read_many(self, store: KeySpace, kids) -> dict:
        """Batched tensor reads: {kid: flat payload array (None when no
        contribution landed)}.  With resident pools on, contributor
        stacks reduce ON DEVICE (canonical-order kernels via
        _pallas_or_xla; f64 and `lww` route to their exact twins) and
        only the [G, K] results download — dirty payloads never
        round-trip through the host.  Host-only engines/config read the
        reference reduction (KeySpace.tensor_read).

        The grouping/upload pass (contributor enumeration, pool-slot
        resolution, missing-row staging, the device idx vector) is
        CACHED between calls: contributor membership and canonical
        order change only when slot rows are created (one slot per
        (key, node), ordered by node), and pool slots only when pools
        drop — the cache stamp covers both, so a steady read loop pays
        per round only the per-round truth (count columns, lww stamps,
        the reduce dispatches, the result download)."""
        from ..crdt import tensor as T
        from ..ops import dense as D
        from ..ops import pallas_dense as PD
        if not (self.resident and self.steady and self._mesh is None):
            return {kid: store.tensor_read(kid) for kid in kids}
        self._tns_check(store)
        kids_t = tuple(kids)
        # one staleness stamp for ALL cached key sets, then one entry
        # per requested kids tuple — interleaved single-key GETs (the
        # production Node.tensor_read pattern) each keep their own
        # cached group/idx structure instead of thrashing one slot
        stamp = (self._tns_epoch, self._tns_ver, store.tns.n)
        rc = self._tns_read_cache
        if rc.get("stamp") != stamp:
            rc = self._tns_read_cache = {"stamp": stamp, "by_kids": {}}
        cache = rc["by_kids"].get(kids_t)
        if cache is None:
            if len(rc["by_kids"]) >= 8192:  # bound a huge-keyspace scan
                rc["by_kids"].clear()
            cache = self._tns_read_build(store, kids_t)
            rc["by_kids"][kids_t] = cache
        out = dict(cache["empty"])
        for grp in cache["groups"]:
            (dcode, elems, strat, n, g, members, pool, idx_dev,
             flat_rows, rows_mat, nodes_mat, slots_mat) = grp
            buf = pool["buf"]
            f32 = dcode == 0
            if strat == T.STRAT_LWW:
                # winner from host-authoritative stamps, vectorized:
                # max uuid per key, writer node breaking exact ties;
                # payload served from the pool (the dirty row's truth)
                u = store.tns.uuid[rows_mat]
                cand = u == u.max(axis=1, keepdims=True)
                w = np.where(cand, nodes_mat,
                             np.int64(-1) << 62).argmax(axis=1)
                idx = slots_mat[np.arange(g), w].astype(_I32)
                got = np.asarray(self._device_get(B.gather_rows(
                    buf, self._put_batch(idx))))
                for j, kid in enumerate(members):
                    out[kid] = got[j, :elems]
                continue
            # trimmed-mean divisor as a RUNTIME scalar (a constant
            # divisor strength-reduces to a reciprocal multiply and
            # rounds away from the host's true division)
            div = pool["dtype"].type(n if n <= 2 else n - 2)
            cnts_f = store.tns.cnt[flat_rows].reshape(g, n).astype(
                pool["dtype"])
            cnts_dev = self._put_batch(cnts_f)

            def _reduce(s_id):
                # XLA fuses the pool gather INTO the fold
                # (tensor_take_reduce — one dispatch, no [G, n, Kp]
                # intermediate); the Pallas leg keeps the
                # correctness-pinned two-step (gather + block kernel)
                if f32:
                    return self._pallas_or_xla(
                        lambda interp: PD.tensor_reduce(
                            B.gather_rows(buf, idx_dev).reshape(
                                g, n, pool["Kp"]),
                            cnts_dev, div, strat=s_id, n=n,
                            interpret=interp),
                        lambda: D.tensor_take_reduce(buf, idx_dev, div,
                                                     strat=s_id, n=n,
                                                     g=g))
                return D.tensor_take_reduce(buf, idx_dev, div,
                                            strat=s_id, n=n, g=g)

            if strat == T.STRAT_AVG:
                # gather+scale fused, then sum+div — the product
                # rounding still lands on the dispatch boundary between
                # them (ops/dense.py tensor_take_scale); count totals
                # accumulate on host with the canonical sequential
                # dtype chain
                # vectorized over KEYS, sequential over contributors:
                # elementwise float adds in the same per-key order as
                # the scalar chain — bit-identical, n numpy ops instead
                # of g*n interpreted iterations per read round
                t = cnts_f[:, 0].copy()
                for i in range(1, n):
                    t = t + cnts_f[:, i]
                tots_dev = self._put_batch(t.reshape(g, 1))
                wmat = D.tensor_take_scale(buf, idx_dev, cnts_dev,
                                           n=n, g=g)
                if f32:
                    red = self._pallas_or_xla(
                        lambda interp: D.tensor_div(
                            PD.tensor_reduce(wmat, cnts_dev, div,
                                             strat=T.STRAT_SUM, n=n,
                                             interpret=interp),
                            tots_dev),
                        lambda: D.tensor_sum_div(wmat, tots_dev, n=n))
                else:
                    red = D.tensor_sum_div(wmat, tots_dev, n=n)
            else:
                red = _reduce(strat)
            got = np.asarray(self._device_get(red))
            for j, kid in enumerate(members):
                out[kid] = got[j, :elems]
        return out

    def _tns_read_build(self, store: KeySpace, kids_t: tuple) -> dict:
        """Build (and stage) the cached read-group structure for one key
        set: contributor rows in canonical order per key, grouped by
        (dtype, elems, strategy, n); rows not yet pool-resident upload
        as CLEAN mirrors; the flat pool-slot idx vector ships to the
        device once."""
        raw: dict = {}
        empty: dict = {}
        for kid in kids_t:
            meta = store.tns_meta.get(kid)
            rows = store.tensor_contrib_rows(kid)
            if meta is None or not rows:
                empty[kid] = None
                continue
            raw.setdefault((meta.dtype_code, meta.elems, meta.strat,
                            len(rows)), []).append((kid, meta, rows))
        groups = []
        for (dcode, elems, strat, n), mem in raw.items():
            pool = self._tns_pool(store, mem[0][1])
            flat = np.fromiter((r for _k, _m, rows in mem for r in rows),
                               dtype=_I64, count=len(mem) * n)
            missing = [r for r in dict.fromkeys(flat.tolist())
                       if r not in pool["map"]]
            if missing:
                mats = [store.tns_payload[r] for r in missing]
                slots = self._tns_slots(pool, missing)
                self._tns_scatter(pool, slots, mats, dirty=False)
            g = len(mem)
            m = pool["map"]
            slots_mat = np.fromiter((m[r] for r in flat.tolist()),
                                    dtype=_I64,
                                    count=g * n).reshape(g, n)
            rows_mat = flat.reshape(g, n)
            groups.append((dcode, elems, strat, n, g,
                           [kid for kid, _m2, _r in mem], pool,
                           self._put_batch(
                               slots_mat.reshape(-1).astype(_I32)),
                           flat, rows_mat, store.tns.node[rows_mat],
                           slots_mat))
        return {"empty": empty, "groups": groups}

    # ------------------------------------------------------- key resolution

    def _resolve_keys(self, store: KeySpace, batch: ColumnarBatch,
                      st: MergeStats) -> np.ndarray:
        """batch key position -> local kid (-1 on type conflict).  ONE
        shared implementation with the host micro path
        (engine/hostbatch.py resolve_keys) — `resident=True` zeroes
        created rows' host ct/dt so host and device mirrors start
        neutral together."""
        from .hostbatch import resolve_keys
        return resolve_keys(store, batch, st, resident=self.resident)

    # --------------------------------------------------- bulk-path plumbing

    def _use_bulk(self, total_rows: int, region: int) -> bool:
        if not self._unique_ok:
            return False
        if self.resident or self._mesh is not None:
            # resident: no state upload to amortize — bulk always wins.
            # mesh: bulk is the sharded path; the scatter fallback would
            # run single-device.
            return True
        return region > 0 and total_rows * self.BULK_FRACTION >= region

    @staticmethod
    def _bulk_region(staged_rows: list[np.ndarray], n0: int, n: int
                     ) -> tuple[int, int, bool]:
        """-> (base, size, all_new): the slot region the kernels operate on.
        When every staged row is brand new (>= n0, the pre-merge table size)
        only the new block [n0, n) participates — its initial state is
        neutral and can be materialized on device with zero upload."""
        lo = min(int(r.min()) for r in staged_rows if len(r))
        if lo >= n0:
            return n0, n - n0, True
        return 0, n, False

    def _upload_batch(self, rows: np.ndarray, base: int, sp: int,
                      cols: list[tuple[np.ndarray, int]]):
        """Async-upload one batch: int32 ids (padded with distinct
        out-of-range slots) + padded value columns.  On a mesh, batch rows
        replicate to every device (each scatters its slot range)."""
        n = len(rows)
        np_ = K.next_pow2(max(n, 1))
        return [self._batch_idx(rows, base, sp, np_)] + \
            [self._put_batch(_pad(c, np_, fill)) for c, fill in cols]

    def _iota_r0(self, rows: np.ndarray, base: int):
        """Device-relative start (np.int32) when `rows` is one long
        contiguous run — the catch-up shape — else None.  The ONE home
        for the contiguity predicate + IDX_IOTA_MIN threshold; the fused
        src kernels and _batch_idx's derived-iota path both use it."""
        n = len(rows)
        if n < self.IDX_IOTA_MIN:
            return None
        r0 = int(rows[0])
        if int(rows[n - 1]) - r0 + 1 != n or not (np.diff(rows) == 1).all():
            return None
        return np.int32(r0 - base)

    def _bulk_src_call(self, fn, fn_iota, states, rows, base: int, sp: int,
                       cols, pb):
        """One src-tracking scatter dispatch: contiguous rows take the
        FUSED variant (idx derived inside the kernel from two scalars —
        one dispatch, no intermediate idx buffer); anything else uploads
        or derives an idx vector and calls the classic kernel."""
        n = len(rows)
        np_ = K.next_pow2(max(n, 1))
        dev = [self._put_batch(_pad(c, np_, fill)) for c, fill in cols]
        if self._mesh is None:  # fused iota kernels are single-device
            r0 = self._iota_r0(rows, base)
            if r0 is not None:
                return fn_iota(*states, r0, np.int32(n), *dev, pb, np_=np_)
        idx = self._batch_idx(rows, base, sp, np_)
        return fn(*states, idx, *dev, pb)

    def _batch_idx(self, rows: np.ndarray, base: int, sp: int, np_: int):
        n = len(rows)
        # catch-up chunks create (and re-touch) slot rows in contiguous
        # blocks; a contiguous idx is DERIVED on device from three
        # scalars (iota) — the int32 index vector never crosses the
        # link.  Padded positions land at >= sp (out of range) exactly
        # like the host-built vector's, so scatters drop them.
        r0 = self._iota_r0(rows, base)
        if r0 is not None:
            return self._iota_idx(np_)(r0, np.int32(n), np.int32(sp))
        idx = np.empty(np_, dtype=_I32)
        idx[:n] = rows - base
        if np_ > n:
            idx[n:] = sp + np.arange(np_ - n, dtype=_I32)
        return self._put_batch(idx)

    def _iota_idx(self, np_: int):
        """Jitted idx builder for one padded batch length (cached).  On a
        mesh the idx replicates like every other batch array (out
        sharding = self._sh_rep) so downstream kernels never mix device
        commitments."""
        key = ("iota_idx", np_)
        fn = self._jit_cache.get(key)
        if fn is None:
            jnp = self._jax.numpy

            def make(r0, n, sp_):
                i = self._jax.lax.iota(jnp.int32, np_)
                return jnp.where(i < n, r0 + i, sp_ + i)

            fn = self._jax.jit(make, out_shardings=self._sh_rep) \
                if self._mesh is not None else self._jax.jit(make)
            self._jit_cache[key] = fn
        return fn

    def _state_up(self, col: np.ndarray, base: int, size: int, sp: int,
                  fill: int, all_new: bool):
        if all_new:
            return self._full(sp, fill)
        return self._put_state(_pad(col[base:base + size], sp, fill))

    @staticmethod
    def _i32_up(arr: np.ndarray, fill64: int):
        """Opportunistic int32 upload spec: halves the bytes whenever the
        column's values fit (node ids, small counter values); the kernels
        promote against the int64 state, so results are bit-identical."""
        arr = np.asarray(arr)
        if len(arr) and -(1 << 31) <= int(arr.min()) and \
                int(arr.max()) < (1 << 31):
            # padded rows scatter nowhere (out-of-range idx), so any
            # representable pad value works
            return (arr.astype(np.int32), -1)
        return (arr, fill64)

    # ---------------------------------------------------- aligned-batch fold
    # R batches staging the exact same slot rows (R replica snapshots of one
    # keyspace — the bulk catch-up shape) reduce on-device in one fused
    # [R, N] pass, then scatter ONCE.  Counter rows fold too, but only
    # align for repeated syncs from the SAME origin (replica snapshots
    # carry per-(key, node) slots, which differ per replica).

    # the device-fold path shares the host pre-combine's alignment rule
    _aligned = staticmethod(_rows_aligned)

    def _fold_prep(self, staged, base: int, sp: int):
        """Common fold staging: (rows0, nA, np_, device idx)."""
        rows0 = staged[0][0]
        nA = len(rows0)
        np_ = K.next_pow2(max(nA, 1))
        self.folds += 1
        return rows0, nA, np_, self._batch_idx(rows0, base, sp, np_)

    @staticmethod
    def _stacked(staged, i: int, fill, np_: int) -> np.ndarray:
        return np.stack([_pad(s[i], np_, fill) for s in staged])

    def _fold_backend(self) -> str:
        mode = self.dense_fold
        if mode in ("off", "pallas", "pallas-interpret", "xla"):
            return mode
        if self._pallas_broken:
            return "xla"
        # Pallas lowers through Mosaic on TPU backends only; the mesh path
        # keeps XLA (pallas_call inside GSPMD needs per-shard shapes)
        if self._mesh is not None:
            return "xla"
        return "pallas" if self._jax.default_backend() != "cpu" else "xla"

    def _pallas_or_xla(self, pallas_fn, xla_fn):
        """ONE home for kernel-backend resolution: run `pallas_fn(interpret)`
        when the resolved backend is a Pallas variant, falling back to
        `xla_fn()` — permanently (self._pallas_broken) — when the Pallas
        lowering fails under dense_fold="auto", and re-raising when a
        Pallas backend was forced.  Every Pallas call site (the three
        fold kernels, the resident scatter, the segment-sum) routes
        through here so a new kernel cannot re-grow its own divergent
        try/except copy."""
        be = self._fold_backend()
        if be.startswith("pallas"):
            try:
                return pallas_fn(be == "pallas-interpret")
            except Exception:
                if self.dense_fold != "auto":
                    raise
                log.warning("pallas kernel unavailable; falling back to "
                            "XLA", exc_info=True)
                self._pallas_broken = True
        return xla_fn()

    def _fold_lex(self, t_s, n_s, d_s):
        """[R, N] stacks -> per-slot lexicographic (t, n) winner, max d,
        winning batch row: (t[N], n[N], d[N], win_batch[N]) on device."""
        from ..ops import dense as D
        from ..ops import pallas_dense as PD
        return self._pallas_or_xla(
            lambda interp: PD.merge_elems(
                self._put_batch(t_s), self._put_batch(n_s),
                self._put_batch(d_s), interpret=interp),
            lambda: D.dense_merge_elems(
                self._put_batch(t_s), self._put_batch(n_s),
                self._put_batch(d_s)))

    def _fold_lww(self, t_s, n_s):
        """[R, N] stacks -> plain (t, node) LWW winner: (t[N], n[N],
        win_batch[N]) on device.  The del side the element kernel wants is
        fabricated ON DEVICE (zeros never cross the host link)."""
        from ..ops import dense as D
        from ..ops import pallas_dense as PD

        def _pallas(interp):
            t_d = self._put_batch(t_s)
            at, an, _dt, win = PD.merge_elems(
                t_d, self._put_batch(n_s),
                self._jax.numpy.zeros_like(t_d), interpret=interp)
            return at, an, win

        return self._pallas_or_xla(
            _pallas,
            lambda: D.dense_merge_lww(self._put_batch(t_s),
                                      self._put_batch(n_s)))

    def _fold_pair(self, v_s, t_s):
        """[R, N] stacks -> per-slot (value @ time) LWW with max-value tie:
        (val[N], t[N]) on device (counter slots — no win flags needed)."""
        from ..ops import dense as D
        from ..ops import pallas_dense as PD
        return self._pallas_or_xla(
            lambda interp: PD.merge_counters(
                self._put_batch(v_s), self._put_batch(t_s),
                interpret=interp),
            lambda: D.dense_merge_counters(self._put_batch(v_s),
                                           self._put_batch(t_s)))

    # ------------------------------------------------------------ envelopes

    def _stage_envelopes(self, store: KeySpace, resolved, st):
        """STAGE (host-only): columnarize + group-combine the envelope
        plane as [n, 4] ct/mt/dt/expire matrices, then make the WHOLE
        placement decision (host-fold vs bulk vs scatter, device fold or
        not) and pre-build every host-side array the dispatch twin will
        upload — including the [R, N, 4] fold stack and the non-resident
        state matrix (both were dispatch-side host work on the critical
        path; STAGE-PURE).  Reading the store's env columns here is safe:
        this plane is only written by _dispatch_envelopes, which the
        pipeline orders strictly after this stage."""
        staged = []  # (pos, [n, 4] matrix)
        for b, kid_of in resolved:
            valid = np.nonzero(kid_of >= 0)[0]
            if not len(valid):
                continue
            if len(valid) == len(kid_of):
                # full batch: stage the shared kid array itself so the
                # combiner can cluster replicas by object identity
                staged.append((kid_of, np.stack(
                    [b.key_ct, b.key_mt, b.key_dt, b.key_expire], axis=-1)))
            else:
                staged.append((kid_of[valid], np.stack(
                    [b.key_ct[valid], b.key_mt[valid], b.key_dt[valid],
                     b.key_expire[valid]], axis=-1)))
        if not staged:
            return None
        staged, folds = self._combine_groups(
            staged,
            lambda st_: (st_[0][0], np.maximum.reduce([s[1] for s in st_])),
            lambda st_, cat: (cat, np.concatenate([s[1] for s in st_])))
        plan = {"staged": staged, "folds": folds}
        if self.resident and self._host_combine() and self._unique_ok:
            plan["mode"] = "host"
            return plan
        total = sum(len(p) for p, _ in staged)
        n = store.keys.n
        base, size, all_new = self._bulk_region([p for p, _ in staged],
                                                self._n0_keys, n)
        if not self._use_bulk(total, size):
            plan["mode"] = "scatter"
            return plan
        plan["mode"] = "bulk"
        plan.update(n=n, base=base, size=size, all_new=all_new)
        plan["fold"] = self._fold_on and self._aligned(staged)
        if plan["fold"]:
            np_ = K.next_pow2(max(len(staged[0][0]), 1))
            plan["stack"] = np.stack([_pad(m, np_, 0) for _, m in staged])
        if not self.resident and not all_new:
            sp = self._sp_size(size)
            host = np.stack([store.keys.ct[base:n], store.keys.mt[base:n],
                             store.keys.dt[base:n],
                             store.keys.expire[base:n]], axis=-1)
            plan["state_host"] = _pad(host, sp, 0)
        return plan

    def _dispatch_envelopes(self, store: KeySpace, plan, st) -> None:
        if plan is None:
            return
        staged = plan["staged"]
        self.folds += plan["folds"]
        if plan["mode"] == "host":
            # envelope merge is plain per-column max with no cross-family
            # device dependency: fold it straight into the host columns
            # (rows are unique per staged entry, so gather-max-scatter is
            # collision-free) — the [N, 4] int64 plane then never crosses
            # the link in either direction.  Bit-identical to the device
            # path: both are int64 max.
            self._drop_family(store, "env")  # sync any device mirror first
            keys = store.keys
            for pos, m in staged:
                for i, (name, _) in enumerate(_FAMILIES["env"]):
                    col = keys.col(name)
                    cur = col[pos]
                    np.maximum(cur, m[:, i], out=cur)
                    col[pos] = cur
            return

        if plan["mode"] == "bulk":
            n, base = plan["n"], plan["base"]
            size, all_new = plan["size"], plan["all_new"]
            if self.resident:
                cols, sp = self._resident_state(store, "env", n)
                state = cols["stack"]
                base = 0
            else:
                sp = self._sp_size(size)
                if all_new:
                    state = self._full(sp, 0, cols=4)
                else:
                    state = self._put_state(plan["state_host"])
            if plan["fold"]:
                # envelopes are plain max — one stacked XLA reduction, one
                # scatter (no win flags to track); the [R, N, 4] stack was
                # pre-built by the stage twin
                from ..ops import dense as D
                rows0, _nA, np_, idx = self._fold_prep(staged, base, sp)
                state = B.bulk_max(state, idx,
                                   D.dense_max(self._put_batch(plan["stack"])))
            else:
                dev = [self._upload_batch(p, base, sp, [(m, 0)])
                       for p, m in staged]
                for idx, c in dev:
                    state = B.bulk_max(state, idx, c)
            if self.resident:
                self._family_done("env", {"stack": state}, n, sp)
                return
            out = np.asarray(self._device_get(state))[:size]
            store.keys.ct[base:n] = out[:, 0]
            store.keys.mt[base:n] = out[:, 1]
            store.keys.dt[base:n] = out[:, 2]
            store.keys.expire[base:n] = out[:, 3]
            return
        # scatter path over touched slots.  The store-state gathers stay
        # HERE (not in the stage): _drop_family may flush a resident
        # mirror into these very columns first.
        self._drop_family(store, "env")
        kv = np.concatenate([p for p, _ in staged])
        cat = np.concatenate([m for _, m in staged])
        trows, slot_idx = np.unique(kv, return_inverse=True)
        n_slots = K.next_pow2(len(trows) + 1)
        n_rows = K.next_pow2(len(kv))
        out = K.scatter_max4(
            _pad(slot_idx.astype(_I64), n_rows, n_slots - 1),
            _pad(cat[:, 0], n_rows, K.NEUTRAL_T),
            _pad(cat[:, 1], n_rows, K.NEUTRAL_T),
            _pad(cat[:, 2], n_rows, K.NEUTRAL_T),
            _pad(cat[:, 3], n_rows, K.NEUTRAL_T),
            _pad(store.keys.ct[trows], n_slots, 0),
            _pad(store.keys.mt[trows], n_slots, 0),
            _pad(store.keys.dt[trows], n_slots, 0),
            _pad(store.keys.expire[trows], n_slots, 0),
            n_slots)
        ct, mt, dt, exp = (a[: len(trows)] for a in self._device_get(out))
        store.keys.ct[trows] = ct
        store.keys.mt[trows] = mt
        store.keys.dt[trows] = dt
        store.keys.expire[trows] = exp

    # ------------------------------------------------------------ registers

    def _stage_registers(self, store: KeySpace, resolved, st):
        """STAGE (host-only): select + columnarize register writes, then
        group-combine.  The (kid_of, key_enc) eligibility mask is memoized
        per shared object pair — replica snapshots of one keyspace compute
        it once, not once per replica."""
        from ..utils.native_tables import nonnull_mask
        staged = []  # (pos=kids, t, node, vals)
        emask_memo: dict = {}
        for b, kid_of in resolved:
            if not b.n_keys:
                continue
            mk = (id(kid_of), id(b.key_enc))
            em = emask_memo.get(mk)
            if em is None:
                em = (kid_of >= 0) & (b.key_enc == S.ENC_BYTES)
                emask_memo[mk] = em
            has = nonnull_mask(b.reg_val)
            idx = np.nonzero(em & has)[0]
            if len(idx):
                staged.append((kid_of[idx], b.reg_t[idx], b.reg_node[idx],
                               list(map(b.reg_val.__getitem__,
                                        idx.tolist()))))
        if not staged:
            return None
        def _fold_reg(st_):
            t_f, n_f, wb = _lex_fold([s[1] for s in st_],
                                     [s[2] for s in st_])
            return (st_[0][0], t_f, n_f,
                    list(_sel_obj([s[3] for s in st_], wb)))

        def _cat_reg(st_, cat):
            vals_cat: list = []
            for s in st_:
                vals_cat.extend(s[3])
            return (cat, np.concatenate([s[1] for s in st_]),
                    np.concatenate([s[2] for s in st_]), vals_cat)

        staged, folds = self._combine_groups(staged, _fold_reg, _cat_reg)
        plan = {"staged": staged, "folds": folds}
        # placement decision + fold-stack builds, staged (STAGE-PURE)
        total = sum(len(p) for p, *_ in staged)
        n = store.keys.n
        base, size, all_new = self._bulk_region([p for p, *_ in staged],
                                                self._n0_keys, n)
        plan.update(n=n, base=base, size=size, all_new=all_new,
                    use_bulk=self._use_bulk(total, size), fold=False)
        if plan["use_bulk"] and not (self.resident and self._host_combine()):
            plan["fold"] = self._fold_on and self._aligned(staged)
            if plan["fold"]:
                np_ = K.next_pow2(max(len(staged[0][0]), 1))
                plan["t_s"] = self._stacked(staged, 1, K.NEUTRAL_T, np_)
                plan["n_s"] = self._stacked(staged, 2, K.NEUTRAL_T, np_)
        return plan

    def _dispatch_registers(self, store: KeySpace, plan, st) -> None:
        if plan is None:
            return
        staged = plan["staged"]
        self.folds += plan["folds"]
        n, base = plan["n"], plan["base"]
        size, all_new = plan["size"], plan["all_new"]

        if plan["use_bulk"]:
            if self.resident:
                cols, sp = self._resident_state(store, "reg", n)
                t, nd = cols["rv_t"], cols["rv_node"]
                base = 0
            else:
                sp = self._sp_size(size)
                t = self._state_up(store.keys.rv_t, base, size, sp, 0, all_new)
                nd = self._state_up(store.keys.rv_node, base, size, sp, 0,
                                    all_new)
            if self.resident and self._host_combine():
                # deferred win resolution: no blocking win download — the
                # winning row's pool id lands in the resident src plane
                # (derived on device as base + iota, zero upload), and at
                # flush BOTH the win values and the rv_t/rv_node columns
                # reconstruct from the host pool (ops/bulk.py bulk_lww_src)
                src = self._src_state("reg", sp)
                for p, bt_, bn_, vals in staged:
                    pb = self._pool_add(vals, rv_t=bt_, rv_node=bn_)
                    t, nd, src = self._bulk_src_call(
                        B.bulk_lww_src, B.bulk_lww_src_iota, (t, nd, src),
                        p, base, sp, [(bt_, K.NEUTRAL_T),
                                      self._i32_up(bn_, K.NEUTRAL_T)], pb)
                self._family_done("reg", {"rv_t": t, "rv_node": nd}, n, sp,
                                  src=src,
                                  recon={"rv_t": "rv_t",
                                         "rv_node": "rv_node"})
                return
            fold = plan["fold"]
            if fold:
                rows0, nA, np_, idx = self._fold_prep(staged, base, sp)
                ft, fn, winb = self._fold_lww(plan["t_s"], plan["n_s"])
                t, nd, win = B.bulk_lww(t, nd, idx, ft, fn)
                wins = [win]
            else:
                dev = [self._upload_batch(p, base, sp,
                                          [(bt, K.NEUTRAL_T),
                                           (bn, K.NEUTRAL_T)])
                       for p, bt, bn, _ in staged]
                wins = []
                for idx, bt, bn in dev:
                    t, nd, win = B.bulk_lww(t, nd, idx, bt, bn)
                    wins.append(win)
            if self.resident:
                self._family_done("reg", {"rv_t": t, "rv_node": nd}, n, sp)
            else:
                store.keys.rv_t[base:n] = np.asarray(t)[:size]
                store.keys.rv_node[base:n] = np.asarray(nd)[:size]
            reg_val = store.reg_val
            if fold:
                winb_h = np.asarray(winb)
                for j in np.nonzero(np.asarray(wins[0])[:nA])[0]:
                    reg_val[int(rows0[j])] = staged[int(winb_h[j])][3][int(j)]
                return
            for (pos, _, _, vals), win in zip(staged, wins):
                for j in np.nonzero(np.asarray(win)[: len(pos)])[0]:
                    reg_val[int(pos[j])] = vals[int(j)]
            return
        # scatter path: registers are LWW slots — reuse the element add-side
        # kernel with a zero del side
        self._drop_family(store, "reg")
        kids = np.concatenate([p for p, *_ in staged])
        vals: list = []
        for _, _, _, v in staged:
            vals.extend(v)
        trows, slot_idx = np.unique(kids, return_inverse=True)
        n_slots = K.next_pow2(len(trows) + 1)
        n_rows = K.next_pow2(len(kids))
        out = K.merge_elems(
            _pad(slot_idx.astype(_I64), n_rows, n_slots - 1),
            _pad(np.concatenate([t for _, t, _, _ in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([n_ for _, _, n_, _ in staged]), n_rows, K.NEUTRAL_T),
            np.zeros(n_rows, dtype=_I64),
            _pad(store.keys.rv_t[trows], n_slots, 0),
            _pad(store.keys.rv_node[trows], n_slots, 0),
            np.zeros(n_slots, dtype=_I64),
            n_slots)
        t, node, _dt, win_row = (a[: len(trows)] for a in self._device_get(out))
        store.keys.rv_t[trows] = t
        store.keys.rv_node[trows] = node
        reg_val = store.reg_val
        for di in np.nonzero(win_row >= 0)[0]:
            reg_val[int(trows[di])] = vals[int(win_row[di])]

    # ------------------------------------------------------------- counters

    def _stage_counter_rows(self, store: KeySpace, resolved, st):
        """STAGE (host-only for OTHER planes; appends missing slot rows to
        the cnt plane itself via _resolve_cnt_rows): columnarize + combine
        counter slot writes."""
        n0 = store.cnt.n
        staged = []  # (rows, total, uuid, base, base_t)
        for b, kid_of in resolved:
            if not len(b.cnt_ki):
                continue
            kid_arr = kid_of[b.cnt_ki]
            keep = np.nonzero(kid_arr >= 0)[0]
            if not len(keep):
                continue
            st.counter_rows += len(keep)
            # slice(None) when every row was kept: views, not copies
            sel = slice(None) if len(keep) == len(kid_arr) else keep
            rows = self._resolve_cnt_rows(store, kid_arr[sel],
                                          b.cnt_node[sel])
            staged.append((rows, b.cnt_val[sel], b.cnt_uuid[sel],
                           b.cnt_base[sel], b.cnt_base_t[sel]))
        if not staged:
            return None
        def _fold_cnt(st_):
            # both (value @ time) pairs fold independently on host
            f_uuid, f_val, _ = _lex_fold([s[2] for s in st_],
                                         [s[1] for s in st_])
            f_bt, f_base, _ = _lex_fold([s[4] for s in st_],
                                        [s[3] for s in st_])
            return (st_[0][0], f_val, f_uuid, f_base, f_bt)

        # disjoint is the common catch-up shape here: R replicas each carry
        # their own node's slots
        staged, folds = self._combine_groups(
            staged, _fold_cnt,
            lambda st_, cat: (cat,) + tuple(
                np.concatenate([s[i] for s in st_]) for i in range(1, 5)))
        plan = {"staged": staged, "folds": folds, "n0": n0}
        # placement decision + fold-stack builds, staged (STAGE-PURE).
        # store.cnt.n is stable from here: only this family's stage
        # appends counter rows, and its dispatch runs strictly after.
        total = sum(len(r) for r, *_ in staged)
        n = store.cnt.n
        base, size, all_new = self._bulk_region([r for r, *_ in staged],
                                                n0, n)
        plan.update(n=n, base=base, size=size, all_new=all_new,
                    use_bulk=self._use_bulk(total, size), fold=False)
        if plan["use_bulk"] and not (self.resident and self._host_combine()):
            plan["fold"] = self._fold_on and self._aligned(staged)
            if plan["fold"]:
                np_ = K.next_pow2(max(len(staged[0][0]), 1))
                plan["v_s"] = self._stacked(staged, 1, 0, np_)
                plan["u_s"] = self._stacked(staged, 2, K.NEUTRAL_T, np_)
                plan["b_s"] = self._stacked(staged, 3, 0, np_)
                plan["bt_s"] = self._stacked(staged, 4, K.NEUTRAL_T, np_)
        return plan

    def _dispatch_counter_rows(self, store: KeySpace, plan, st) -> None:
        if plan is None:
            return
        staged = plan["staged"]
        self.folds += plan["folds"]
        n, base = plan["n"], plan["base"]
        size, all_new = plan["size"], plan["all_new"]

        if plan["use_bulk"]:
            if self.resident:
                cols, sp = self._resident_state(store, "cnt", n)
                val, uuid = cols["val"], cols["uuid"]
                cb, cbt = cols["base"], cols["base_t"]
                base = 0
            else:
                sp = self._sp_size(size)
                val = self._state_up(store.cnt.val, base, size, sp, 0, all_new)
                uuid = self._state_up(store.cnt.uuid, base, size, sp,
                                      K.NEUTRAL_T, all_new)
                cb = self._state_up(store.cnt.base, base, size, sp, 0, all_new)
                cbt = self._state_up(store.cnt.base_t, base, size, sp,
                                     K.NEUTRAL_T, all_new)
            if self.resident and self._host_combine():
                # deferred win resolution (see _dispatch_registers): winners
                # land in the src plane, and at flush the val/uuid pair —
                # the two widest counter columns — reconstructs from the
                # host pool instead of downloading.  The (rare) base pair
                # keeps its own on-device winner and downloads when written.
                src = self._src_state("cnt", sp)
                written = {"val", "uuid"}
                for r, v, u, bb, bt in staged:
                    pb = self._pool_add(None, val=v, uuid=u)
                    if (bt == K.NEUTRAL_T).all():
                        # neutral base plane (no counter deletes anywhere in
                        # the batch, the common case): skip uploading it
                        val, uuid, src = self._bulk_src_call(
                            B.bulk_counters_vu_src,
                            B.bulk_counters_vu_src_iota, (val, uuid, src),
                            r, base, sp, [self._i32_up(v, 0),
                                          (u, K.NEUTRAL_T)], pb)
                    else:
                        idx, dv, du, dbb, dbt = self._upload_batch(
                            r, base, sp, [(v, 0), (u, K.NEUTRAL_T), (bb, 0),
                                          (bt, K.NEUTRAL_T)])
                        val, uuid, cb, cbt, src = B.bulk_counters_src(
                            val, uuid, cb, cbt, src, idx, dv, du, dbb, dbt,
                            pb)
                        written |= {"base", "base_t"}
                self._family_done("cnt", {"val": val, "uuid": uuid,
                                          "base": cb, "base_t": cbt}, n, sp,
                                  src=src, written=written,
                                  recon={"val": "val", "uuid": "uuid"})
                return
            if plan["fold"]:
                # aligned counter rows (same (key, node) slots per batch —
                # repeated syncs from one origin): fold both (value @ time)
                # pairs on-device (stacks pre-built by the stage twin),
                # scatter once
                rows0, _nA, np_, idx = self._fold_prep(staged, base, sp)
                fv, fu = self._fold_pair(plan["v_s"], plan["u_s"])
                fb, fbt = self._fold_pair(plan["b_s"], plan["bt_s"])
                val, uuid, cb, cbt = B.bulk_counters(val, uuid, cb, cbt,
                                                     idx, fv, fu, fb, fbt)
            else:
                dev = []  # [(uploaded arrays, with_base)]
                for r, v, u, bb, bt in staged:
                    if self.resident and (bt == K.NEUTRAL_T).all():
                        dev.append((self._upload_batch(
                            r, base, sp, [(v, 0), (u, K.NEUTRAL_T)]), False))
                    else:
                        dev.append((self._upload_batch(
                            r, base, sp, [(v, 0), (u, K.NEUTRAL_T), (bb, 0),
                                          (bt, K.NEUTRAL_T)]), True))
                for up, with_base in dev:
                    if with_base:
                        idx, v, u, bb, bt = up
                        val, uuid, cb, cbt = B.bulk_counters(
                            val, uuid, cb, cbt, idx, v, u, bb, bt)
                    else:
                        idx, v, u = up
                        val, uuid = B.bulk_counters_vu(val, uuid, idx, v, u)
            if self.resident:
                self._family_done("cnt", {"val": val, "uuid": uuid,
                                          "base": cb, "base_t": cbt}, n, sp)
                return
            store.cnt.val[base:n] = np.asarray(val)[:size]
            store.cnt.uuid[base:n] = np.asarray(uuid)[:size]
            store.cnt.base[base:n] = np.asarray(cb)[:size]
            store.cnt.base_t[base:n] = np.asarray(cbt)[:size]
            return  # sums re-derived in one pass by merge_many

        self._drop_family(store, "cnt")
        all_rows = np.concatenate([s[0] for s in staged])
        trows, slot_idx = np.unique(all_rows, return_inverse=True)
        n_slots = K.next_pow2(len(trows) + 1)
        n_rows = K.next_pow2(len(all_rows))
        slot_ids = _pad(slot_idx.astype(_I64), n_rows, n_slots - 1)
        for vcol, tcol, vi, ti in (("val", "uuid", 1, 2),
                                   ("base", "base_t", 3, 4)):
            out = K.merge_counters(
                slot_ids,
                _pad(np.concatenate([s[vi] for s in staged]), n_rows, 0),
                _pad(np.concatenate([s[ti] for s in staged]), n_rows, K.NEUTRAL_T),
                _pad(store.cnt.col(vcol)[trows], n_slots, 0),
                _pad(store.cnt.col(tcol)[trows], n_slots, K.NEUTRAL_T),
                n_slots)
            new_val, new_t = (a[: len(trows)] for a in self._device_get(out))
            store.cnt.col(vcol)[trows] = new_val
            store.cnt.col(tcol)[trows] = new_t
        if self.resident:
            # merge_many's sum pass is skipped while other families hold
            # unflushed device state — this path already wrote the host
            store.recompute_counter_sums()
        # else: sums re-derived in one pass by merge_many

    def _resolve_cnt_rows(self, store: KeySpace, kids: np.ndarray,
                          nodes: np.ndarray) -> np.ndarray:
        """(kid, node) pairs -> store cnt rows via the per-rank direct
        index (KeySpace.cnt_rows_lookup — dense window or sparse hash,
        the keyspace picks): one vectorized lookup per distinct origin
        node — replica batches carry one or few — with missing slots
        bulk-created as neutral (val=0, t=NEUTRAL_T)."""
        out = np.empty(len(kids), dtype=_I64)
        if not len(kids):
            return out
        # replica batches stage ONE origin node: a single memory-bound
        # equality pass beats np.unique's sort
        first = int(nodes[0])
        if (nodes == first).all():
            groups = [(first, slice(None))]
        else:
            uniq_nodes, inv = np.unique(nodes, return_inverse=True)
            groups = [(int(nd), np.nonzero(inv == i)[0])
                      for i, nd in enumerate(uniq_nodes.tolist())]
        for node, sel in groups:
            k = kids[sel]
            got = store.cnt_rows_lookup(store.rank_of(node), k)
            miss = got < 0
            if miss.any():
                # a raw op-stream batch may repeat a (kid, node): one row
                # per unique missing kid
                mk = k[miss]
                uk = np.unique(mk)
                new_rows = store.cnt.append_block(
                    len(uk), kid=uk, node=node, val=0,
                    uuid=K.NEUTRAL_T, base=0, base_t=K.NEUTRAL_T)
                store.cnt_rows_assign(store.rank_of(node), uk, new_rows)
                # uk is sorted-unique and aligned with new_rows: map each
                # missing kid to its row without a second index probe
                got[miss] = new_rows[np.searchsorted(uk, mk)]
            out[sel] = got
        return out

    # ------------------------------------------------------------- elements

    def _stage_elem_rows(self, store: KeySpace, resolved, st):
        """STAGE (appends missing element rows to the el plane; all other
        work is host prep): resolve (kid, member) combos to rows,
        columnarize, group-combine.  Valueless batches (the set-member
        catch-up shape) stage `vals=None` — no [None] * n list is ever
        materialized or concatenated for them."""
        n0 = store.el.n
        staged = []  # (rows, at, an, dt, vals-or-None, has_vals)
        # replica snapshots of one keyspace share el_ki/el_member list
        # OBJECTS (and, via the caller's key memo, the kid_of array), so
        # their (kid, member) combos resolve to the same rows — resolve
        # each distinct shape once instead of once per replica (the
        # interning + slot resolution was the top dispatch cost for
        # field-heavy workloads)
        row_memo: dict = {}
        for b, kid_of in resolved:
            if not len(b.el_ki):
                continue
            mk = (b.el_shape if b.el_shape is not None
                  else ("id", id(b.el_ki), id(b.el_member)), id(kid_of))
            cached = row_memo.get(mk)
            if cached is not None:
                rows, keep, all_kept = cached
                if rows is None:
                    continue  # nothing kept for this shape
                st.elem_rows += len(keep)
            else:
                kid_arr = kid_of[b.el_ki]
                keep = np.nonzero(kid_arr >= 0)[0]
                if not len(keep):
                    row_memo[mk] = (None, None, False)
                    continue
                st.elem_rows += len(keep)
                all_kept = len(keep) == len(b.el_ki)
                members = b.el_member if all_kept \
                    else list(map(b.el_member.__getitem__, keep.tolist()))
                # two native batch calls: intern members, then
                # resolve/create (kid, member) combo slots — no per-row
                # Python
                mids, _ = store.member_index.get_or_insert_batch(members)
                combos = (kid_arr[keep] << KeySpace.MEMBER_BITS) | mids
                rn0 = store.el.n
                rows, n_new = store.el_index.get_or_assign_batch(
                    combos, next_val=rn0)
                if n_new:
                    created = np.nonzero(rows >= rn0)[0]
                    uniq_rows, first = np.unique(rows[created],
                                                 return_index=True)
                    pos = created[first]
                    # combo-index ids must be exactly the next el block —
                    # checked BEFORE append_block mutates the plane
                    # (CHECK-THEN-MUTATE; real raise, python -O safe)
                    if len(uniq_rows) != n_new or \
                            int(uniq_rows[0]) != rn0 or \
                            int(uniq_rows[-1]) != rn0 + n_new - 1:
                        span = f"[{int(uniq_rows[0])}, " \
                            f"{int(uniq_rows[-1])}]" \
                            if len(uniq_rows) else "[]"
                        raise RuntimeError(
                            f"el combo index issued non-contiguous rows "
                            f"{span} (n={len(uniq_rows)}) for block "
                            f"[{rn0}, {rn0 + n_new - 1}]")
                    store.el.append_block(
                        n_new, kid=kid_arr[keep][pos],
                        add_t=0, add_node=0, del_t=0)
                    store.el_member.extend(
                        map(members.__getitem__, pos.tolist()))
                    store.el_val.extend([None] * n_new)
                row_memo[mk] = (rows, keep, all_kept)
            # has-values: an inherited False hint is exact (any subset of
            # an all-None list is all None) and skips both the scan AND
            # the value-list build; anything else re-scans locally so a
            # lone dict value in the parent cannot push every all-None
            # sibling chunk down the value path.
            if b.el_has_vals is False:
                vals, hv = None, False
            else:
                vals = b.el_val if all_kept \
                    else list(map(b.el_val.__getitem__, keep.tolist()))
                hv = has_values(vals)
                if not hv:
                    vals = None
            esel = slice(None) if all_kept else keep
            staged.append((rows, b.el_add_t[esel], b.el_add_node[esel],
                           b.el_del_t[esel], vals, hv))
        if not staged:
            return None
        def _fold_el(st_):
            f_at, f_an, wb = _lex_fold([s[1] for s in st_],
                                       [s[2] for s in st_])
            f_dt = np.maximum.reduce([s[3] for s in st_])
            hv = any(s[5] for s in st_)
            vals = list(_sel_obj([s[4] for s in st_], wb)) if hv else None
            return (st_[0][0], f_at, f_an, f_dt, vals, hv)

        def _cat_el(st_, cat):
            hv = any(s[5] for s in st_)
            if hv:
                vals_cat: list = []
                for s in st_:
                    vals_cat.extend(s[4] if s[4] is not None
                                    else [None] * len(s[0]))
            else:
                vals_cat = None
            return (cat,
                    np.concatenate([s[1] for s in st_]),
                    np.concatenate([s[2] for s in st_]),
                    np.concatenate([s[3] for s in st_]),
                    vals_cat, hv)

        staged, folds = self._combine_groups(staged, _fold_el, _cat_el)
        plan = {"staged": staged, "folds": folds, "n0": n0,
                "el_epoch": store.el_compact_epoch}
        # placement decision + fold-stack builds, staged (STAGE-PURE).
        # store.el.n is stable from here: only this stage appends element
        # rows, and its dispatch runs strictly after.
        total = sum(len(r) for r, *_ in staged)
        n = store.el.n
        base, size, all_new = self._bulk_region([r for r, *_ in staged],
                                                n0, n)
        plan.update(n=n, base=base, size=size, all_new=all_new,
                    use_bulk=self._use_bulk(total, size), fold=False)
        if plan["use_bulk"] and not (self.resident and self._host_combine()):
            plan["fold"] = self._fold_on and self._aligned(staged)
            if plan["fold"]:
                np_ = K.next_pow2(max(len(staged[0][0]), 1))
                plan["a_s"] = self._stacked(staged, 1, K.NEUTRAL_T, np_)
                plan["x_s"] = self._stacked(staged, 2, K.NEUTRAL_T, np_)
                plan["d_s"] = self._stacked(staged, 3, 0, np_)
        return plan

    def _dispatch_elem_rows(self, store: KeySpace, plan, st) -> None:
        if plan is None:
            return
        # staged element ROW INDICES are only valid while row ids are
        # stable; _compact_elements re-identifies every row and bumps the
        # epoch.  The single-writer discipline means this can never fire in
        # correct usage — if it does, scattering would alias rows, so fail
        # loudly before touching any column.
        if plan["el_epoch"] != store.el_compact_epoch:
            raise RuntimeError(
                "element rows were compacted between stage and dispatch "
                "(row-id stability broken: staged indices are stale)")
        staged = plan["staged"]
        self.folds += plan["folds"]
        n, base = plan["n"], plan["base"]
        size, all_new = plan["size"], plan["all_new"]

        if plan["use_bulk"]:
            if self.resident:
                cols, sp = self._resident_state(store, "el", n)
                at, an, dt = cols["add_t"], cols["add_node"], cols["del_t"]
                base, size = 0, n
                old_dt = None  # garbage enqueue deferred to flush
                if self._host_combine():
                    # deferred win resolution (see _dispatch_registers): the
                    # src plane is ALWAYS tracked — at flush it costs one
                    # int32 download and replaces the add_t + add_node
                    # int64 downloads (4 bytes/slot vs 16) while also
                    # resolving dict win values.
                    #
                    # The DEL side never touches the device here: the add
                    # kernels don't read del_t for win decisions, and
                    # del-merge is a plain max — applied straight to the
                    # host column (rows are unique per staged entry, so
                    # gather-max-scatter is collision-free).  Zero del
                    # bytes cross the link in either direction; newly-dead
                    # rows are queued for GC at flush (after add_t
                    # reconstruction) via _el_del_touched.
                    src = self._src_state("el", sp)
                    host_dt = store.el.del_t
                    for rows_, a_, x_, d_, vals, _hv in staged:
                        x_arr = np.asarray(x_)
                        x_up = self._i32_up(x_arr, K.NEUTRAL_T)
                        pb = self._pool_add(vals, add_t=a_, add_node=x_arr)
                        at, an, src = self._bulk_src_call(
                            B.bulk_elems_src_nodt, B.bulk_elems_src_nodt_iota,
                            (at, an, src), rows_, base, sp,
                            [(a_, K.NEUTRAL_T), x_up], pb)
                        d_arr = np.asarray(d_)
                        nz = np.flatnonzero(d_arr)
                        if len(nz):
                            sel = np.asarray(rows_)[nz]
                            cur = host_dt[sel]
                            dv = d_arr[nz]
                            adv = dv > cur
                            if adv.any():
                                host_dt[sel[adv]] = dv[adv]
                                self._el_del_touched.append(sel[adv])
                    self._family_done("el", {"add_t": at, "add_node": an,
                                             "del_t": dt}, n, sp, src=src,
                                      written={"add_t", "add_node"},
                                      recon={"add_t": "add_t",
                                             "add_node": "add_node"})
                    return
            else:
                sp = self._sp_size(size)
                old_dt = (np.zeros(size, dtype=_I64) if all_new
                          else store.el.del_t[base:n].copy())
                at = self._state_up(store.el.add_t, base, size, sp, 0, all_new)
                an = self._state_up(store.el.add_node, base, size, sp, 0,
                                    all_new)
                dt = self._state_up(store.el.del_t, base, size, sp, 0, all_new)
            fold = plan["fold"]
            if fold:
                rows0, nA, np_, idx = self._fold_prep(staged, base, sp)
                fa, fx, fd, winb = self._fold_lex(plan["a_s"], plan["x_s"],
                                                  plan["d_s"])
                at, an, dt, win = B.bulk_elems(at, an, dt, idx, fa, fx, fd)
                wins = [win]
            else:
                dev = [self._upload_batch(
                    r, base, sp, [(a, K.NEUTRAL_T), (x, K.NEUTRAL_T), (d, 0)])
                    for r, a, x, d, _, _ in staged]
                wins = []
                for idx, a, x, d in dev:
                    at, an, dt, win = B.bulk_elems(at, an, dt, idx, a, x, d)
                    wins.append(win)
            if self.resident:
                self._family_done("el", {"add_t": at, "add_node": an,
                                         "del_t": dt}, n, sp)
            else:
                m_at = np.asarray(at)[:size]
                m_dt = np.asarray(dt)[:size]
                store.el.add_t[base:n] = m_at
                store.el.add_node[base:n] = np.asarray(an)[:size]
                store.el.del_t[base:n] = m_dt
                self._enqueue_elem_garbage(store, np.arange(base, n), m_at,
                                           m_dt, old_dt)
            el_val = store.el_val
            el_kid = store.el.kid
            enc = store.keys.enc
            if fold:
                # CPU parity: the winning row's value — None included —
                # replaces the slot's.  Values live only on dict kids, so
                # the Python loop is vectorized down to dict rows; set rows
                # are None-over-None no-ops.
                winb_h = np.asarray(winb)
                cand = np.asarray(wins[0])[:nA] & \
                    np.isin(enc[el_kid[rows0]], S.VALUE_ENCS)
                for j in np.nonzero(cand)[0]:
                    sv = staged[int(winb_h[j])][4]
                    el_val[int(rows0[j])] = None if sv is None \
                        else sv[int(j)]
                return
            for (pos, _, _, _, vals, has_vals), win in zip(staged, wins):
                win_arr = np.asarray(win)[: len(pos)]
                if has_vals:
                    for j in np.nonzero(win_arr)[0]:
                        el_val[int(pos[j])] = vals[int(j)]
                else:
                    # valueless batch: winning None adds must still CLEAR
                    # stored values (CPU parity); set rows need no touch
                    cand = win_arr & np.isin(enc[el_kid[pos]], S.VALUE_ENCS)
                    for j in np.nonzero(cand)[0]:
                        el_val[int(pos[j])] = None
            return

        self._drop_family(store, "el")
        all_rows = np.concatenate([r for r, *_ in staged])
        vals_flat: list = []
        for r, _, _, _, v, _ in staged:
            vals_flat.extend(v if v is not None else [None] * len(r))
        trows, slot_idx = np.unique(all_rows, return_inverse=True)
        cur_dt = store.el.del_t[trows].copy()
        n_slots = K.next_pow2(len(trows) + 1)
        n_rows = K.next_pow2(len(all_rows))
        out = K.merge_elems(
            _pad(slot_idx.astype(_I64), n_rows, n_slots - 1),
            _pad(np.concatenate([a for _, a, *_ in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([x for _, _, x, *_ in staged]), n_rows, K.NEUTRAL_T),
            _pad(np.concatenate([d for _, _, _, d, _, _ in staged]), n_rows, 0),
            _pad(store.el.add_t[trows], n_slots, 0),
            _pad(store.el.add_node[trows], n_slots, 0),
            _pad(cur_dt, n_slots, 0),
            n_slots)
        kk = len(trows)
        m_at, m_an, m_dt, win_row = (a[:kk] for a in self._device_get(out))
        store.el.add_t[trows] = m_at
        store.el.add_node[trows] = m_an
        store.el.del_t[trows] = m_dt
        el_val = store.el_val
        for di in np.nonzero(win_row >= 0)[0]:
            el_val[int(trows[di])] = vals_flat[int(win_row[di])]
        self._enqueue_elem_garbage(store, trows, m_at, m_dt, cur_dt)

    @staticmethod
    def _enqueue_elem_garbage(store: KeySpace, rows, at, dt, old_dt) -> None:
        """Queue tombstones whose del_t advanced (dead rows need GC once the
        cluster horizon passes).  Bulk path: one heapify, not n pushes —
        a snapshot-merge flush queues millions."""
        newly = np.nonzero((at < dt) & (dt > old_dt))[0]
        if not len(newly):
            return
        rws = np.asarray(rows)[newly]
        kids = store.el.kid[rws].tolist()
        store.enqueue_garbage_bulk(
            np.asarray(dt)[newly].tolist(),
            list(map(store.key_bytes.__getitem__, kids)),
            list(map(store.el_member.__getitem__, rws.tolist())))


class ShardDispatcher:
    """Thin shard-aware dispatcher: one resident engine per hash shard,
    all sharing THIS process's device queue.

    The sharded keyspace (store/sharded_keyspace.py) partitions keys into
    independent stores; each shard gets its own engine so per-shard
    resident mirrors, win pools, and staging pipelines never interact.
    Dispatching shard s+1's merge while shard s's device kernels are
    still in flight interleaves their batches on the same queue — JAX
    dispatch is async, so the host moves on to the next shard's staging
    while the device drains the previous one's scatters.  Semantics need
    no care beyond that: shards share no rows, so any interleaving is
    equivalent to any other.
    """

    def __init__(self, n_shards: int, engine_factory=None) -> None:
        if engine_factory is None:
            engine_factory = lambda: TpuMergeEngine(resident=True)  # noqa: E731
        self.engines = [engine_factory() for _ in range(n_shards)]

    def merge_shard(self, shard: int, store: KeySpace,
                    batches: list) -> MergeStats:
        return self.engines[shard].merge_many(store, batches)

    def flush_all(self, stores: list) -> None:
        for eng, store in zip(self.engines, stores):
            if getattr(eng, "needs_flush", False):
                eng.flush(store)

    @property
    def needs_flush(self) -> bool:
        return any(getattr(e, "needs_flush", False) for e in self.engines)

    def discard_resident(self) -> None:
        for e in self.engines:
            if hasattr(e, "discard_resident"):
                e.discard_resident()

    def close(self) -> None:
        for e in self.engines:
            if hasattr(e, "close"):
                e.close()
