"""Vectorized host merge for op-stream micro-batches.

The steady-state replication coalescer (replica/coalesce.py) lands
micro-batches of a few hundred to a few thousand rows every few
milliseconds.  At that scale the device scatter path pays more in
dispatch fixed costs (kernel launches, transfers, jit-cache probes) than
the merge itself is worth — on a CPU backend, dozens of times more.
This module is the third placement strategy next to `bulk` and
`scatter` (engine/tpu.py picks it for small non-unique batches): the
same CRDT reductions as the device kernels, computed with numpy
sort+reduceat group reductions at C speed, written straight into the
host columns.

Semantics are bit-identical to engine/cpu.py (the per-row reference):
every reduction below is the associative lexicographic/plain max from
crdt/semantics.py, so folding intra-batch duplicates first and merging
the winner against the store equals applying the rows in order —
differential-tested in tests/test_coalesce_apply.py.

GC parity: element rows whose del_t advanced past add_t enqueue
tombstones exactly like KeySpace.elem_merge / the device flush path do;
counter sums update incrementally (the same delta rule as
KeySpace.counter_merge_slot), never by an O(table) recompute.
"""

from __future__ import annotations

import numpy as np

from ..crdt import semantics as S
from ..store.keyspace import KeySpace
from .base import ColumnarBatch, MergeStats

_I64 = np.int64

# row ceiling under which the vectorized host strategy beats both the
# per-row loop (past a couple dozen rows) and a device scatter
# (dispatch fixed costs dominate at micro-batch scale) — shared by
# TpuMergeEngine.HOST_SCATTER_MAX and CpuMergeEngine.merge_many
HOST_MICRO_MAX = 1 << 15
# ...and the row FLOOR under which the per-row reference loop beats the
# vectorized pass's numpy fixed costs (CpuMergeEngine.merge_many routes
# tiny runs — a read-heavy pipeline's interleaved write clusters — back
# onto the loop; byte-identical by the differential pin, r18)
HOST_ROW_MIN = 24


def _group_last(sorted_keys: np.ndarray) -> np.ndarray:
    """Indices (into the sorted array) of each group's LAST element."""
    return np.nonzero(np.append(sorted_keys[1:] != sorted_keys[:-1],
                                True))[0]


def _group_first(sorted_keys: np.ndarray) -> np.ndarray:
    return np.nonzero(np.append(True, sorted_keys[1:] != sorted_keys[:-1]))[0]


# ------------------------------------------------- duplicate-slot folds
# A raw op-stream batch may hit the same slot many times; every reduction
# below folds those duplicates to one winner per slot with the exact
# associative rule from crdt/semantics.py, so "fold then merge once"
# equals "apply in order".  These are THE shared fold implementations:
# the host strategies below use them in place, and the resident device
# path (engine/tpu.py micro merges) folds with the very same functions
# before scattering the unique winners into resident planes.


def fold_env_rows(kids: np.ndarray, mat: np.ndarray):
    """-> (unique kids, [U, 4] per-column max)."""
    order = np.argsort(kids, kind="stable")
    k_s = kids[order]
    first = _group_first(k_s)
    return k_s[first], np.maximum.reduceat(mat[order], first, axis=0)


def fold_pair_rows(rows: np.ndarray, primary: np.ndarray,
                   secondary: np.ndarray):
    """Lexicographic (primary, secondary) max per row group ->
    (unique rows, win primary, win secondary, winning source index).
    Registers fold (t, node); counter pairs fold (uuid, val) /
    (base_t, base)."""
    order = np.lexsort((secondary, primary, rows))
    r_s = rows[order]
    last = _group_last(r_s)
    src = order[last]
    return r_s[last], primary[src], secondary[src], src


def fold_el_rows(rows: np.ndarray, at: np.ndarray, an: np.ndarray,
                 dt: np.ndarray):
    """Element fold: add side = lexicographic (add_t, add_node) winner,
    del side = plain max -> (unique rows, win add_t, win add_node,
    max del_t, winning source index)."""
    order = np.lexsort((an, at, rows))
    r_s = rows[order]
    first = _group_first(r_s)
    last = _group_last(r_s)
    src = order[last]
    return (r_s[last], at[src], an[src],
            np.maximum.reduceat(dt[order], first), src)


def _merge_env(store: KeySpace, kids: np.ndarray, mat: np.ndarray) -> None:
    """Envelope plane: per-column max over (possibly repeated) kids."""
    uniq, red = fold_env_rows(kids, mat)
    keys = store.keys
    for i, name in enumerate(("ct", "mt", "dt", "expire")):
        col = keys.col(name)
        cur = col[uniq]
        np.maximum(cur, red[:, i], out=cur)
        col[uniq] = cur


def _merge_reg(store: KeySpace, kids: np.ndarray, t: np.ndarray,
               node: np.ndarray, vals: list) -> None:
    """Register plane: lexicographic (t, node) LWW; the winner carries
    its value (semantics.merge_register)."""
    wk, wt, wn, src = fold_pair_rows(kids, t, node)
    cur_t = store.keys.rv_t[wk]
    cur_n = store.keys.rv_node[wk]
    win = (wt > cur_t) | ((wt == cur_t) & (wn > cur_n))
    if not win.any():
        return
    rows = wk[win]
    store.keys.rv_t[rows] = wt[win]
    store.keys.rv_node[rows] = wn[win]
    reg_val = store.reg_val
    for r, i in zip(rows.tolist(), src[win].tolist()):
        reg_val[r] = vals[i]


def _resolve_cnt_rows(store: KeySpace, kids: np.ndarray,
                      nodes: np.ndarray) -> np.ndarray:
    """(kid, node) -> store cnt rows, creating neutral slots for misses
    (host twin of TpuMergeEngine._resolve_cnt_rows)."""
    out = np.empty(len(kids), dtype=_I64)
    if not len(kids):
        return out
    first = int(nodes[0])
    if (nodes == first).all():
        groups = [(first, slice(None))]
    else:
        uniq_nodes, inv = np.unique(nodes, return_inverse=True)
        groups = [(int(nd), np.nonzero(inv == i)[0])
                  for i, nd in enumerate(uniq_nodes.tolist())]
    for node, sel in groups:
        k = kids[sel]
        got = store.cnt_rows_lookup(store.rank_of(node), k)
        miss = got < 0
        if miss.any():
            mk = k[miss]
            uk = np.unique(mk)
            new_rows = store.cnt.append_block(
                len(uk), kid=uk, node=node, val=0,
                uuid=S.NEUTRAL_T, base=0, base_t=S.NEUTRAL_T)
            store.cnt_rows_assign(store.rank_of(node), uk, new_rows)
            got[miss] = new_rows[np.searchsorted(uk, mk)]
        out[sel] = got
    return out


def _apply_cnt_pair(store: KeySpace, rows: np.ndarray, vals: np.ndarray,
                    ts: np.ndarray, vcol: str, tcol: str,
                    sign: int) -> None:
    """One (value @ time) LWW pair over slot rows (max value on exact
    time tie — semantics.merge_counter_slot), with the incremental
    per-key sum delta (`sign`: +1 for the total pair, -1 for the base
    pair, mirroring KeySpace.counter_merge_slot)."""
    wr, wt, wv, _src = fold_pair_rows(rows, ts, vals)
    cv = store.cnt.col(vcol)
    ct = store.cnt.col(tcol)
    cur_v = cv[wr]
    cur_t = ct[wr]
    win = (wt > cur_t) | ((wt == cur_t) & (wv > cur_v))
    if not win.any():
        return
    rows_w = wr[win]
    dv = wv[win] - cur_v[win]
    cv[rows_w] = wv[win]
    ct[rows_w] = wt[win]
    changed = np.nonzero(dv)[0]
    if not len(changed):
        return
    kidc = store.cnt.kid[rows_w[changed]]
    delta = dv[changed] * sign
    uk, inv = np.unique(kidc, return_inverse=True)
    amax = int(np.abs(delta).max())
    if amax and len(delta) * amax < (1 << 53):
        # float64 bincount is exact under 2^53 (the same guard as
        # KeySpace.recompute_counter_sums)
        sums = np.bincount(inv, weights=delta,
                           minlength=len(uk)).astype(_I64)
    else:
        sums = np.zeros(len(uk), dtype=_I64)
        np.add.at(sums, inv, delta)
    store.keys.cnt_sum[uk] += sums


def _resolve_el_rows(store: KeySpace, kids: np.ndarray,
                     members: list) -> np.ndarray:
    """(kid, member) -> store el rows, creating neutral rows for misses
    (host twin of the row-creation half of _stage_elem_rows)."""
    mids, _ = store.member_index.get_or_insert_batch(members)
    combos = (kids << KeySpace.MEMBER_BITS) | mids
    rn0 = store.el.n
    rows, n_new = store.el_index.get_or_assign_batch(combos, next_val=rn0)
    if n_new:
        created = np.nonzero(rows >= rn0)[0]
        uniq_rows, first = np.unique(rows[created], return_index=True)
        pos = created[first]
        if len(uniq_rows) != n_new or int(uniq_rows[0]) != rn0 or \
                int(uniq_rows[-1]) != rn0 + n_new - 1:
            span = f"[{int(uniq_rows[0])}, {int(uniq_rows[-1])}]" \
                if len(uniq_rows) else "[]"
            raise RuntimeError(
                f"el combo index issued non-contiguous rows {span} "
                f"(n={len(uniq_rows)}) for block [{rn0}, {rn0 + n_new - 1}]")
        store.el.append_block(n_new, kid=kids[pos], add_t=0, add_node=0,
                              del_t=0)
        store.el_member.extend(map(members.__getitem__, pos.tolist()))
        store.el_val.extend([None] * n_new)
    return rows


def _merge_el(store: KeySpace, rows: np.ndarray, at: np.ndarray,
              an: np.ndarray, dt: np.ndarray, vals) -> None:
    """Element plane: add-side lexicographic (t, node) LWW carrying the
    value, del-side plain max, newly-dead rows queued for GC
    (semantics.merge_elem / KeySpace.elem_merge)."""
    wr, wat, wan, d_red, win_src = fold_el_rows(rows, at, an, dt)
    old_at = store.el.add_t[wr]
    old_an = store.el.add_node[wr]
    old_dt = store.el.del_t[wr]
    win = (wat > old_at) | ((wat == old_at) & (wan > old_an))
    new_at = np.where(win, wat, old_at)
    new_dt = np.maximum(old_dt, d_red)
    store.el.add_t[wr] = new_at
    store.el.add_node[wr] = np.where(win, wan, old_an)
    store.el.del_t[wr] = new_dt
    # winner-carried values (None included — a winning valueless write
    # CLEARS the slot); set members are valueless on both sides, so only
    # value-carrying encodings pay the assignment loop.  Three equality
    # masks beat np.isin's sort machinery at micro-batch scale.
    enc = store.keys.enc[store.el.kid[wr]]
    val_enc = enc == S.VALUE_ENCS[0]
    for e in S.VALUE_ENCS[1:]:
        val_enc |= enc == e
    vsel = win & val_enc
    if vsel.any():
        el_val = store.el_val
        src = win_src[vsel]
        if vals is None:
            for r in wr[vsel].tolist():
                el_val[r] = None
        else:
            for r, i in zip(wr[vsel].tolist(), src.tolist()):
                el_val[r] = vals[i]
    newly = np.nonzero((new_at < new_dt) & (new_dt > old_dt))[0]
    if len(newly):
        rws = wr[newly]
        kids = store.el.kid[rws].tolist()
        store.enqueue_garbage_bulk(
            new_dt[newly].tolist(),
            list(map(store.key_bytes.__getitem__, kids)),
            list(map(store.el_member.__getitem__, rws.tolist())))


def resolve_keys(store: KeySpace, batch: ColumnarBatch, st: MergeStats,
                 resident: bool = False) -> np.ndarray:
    """batch key position -> local kid (-1 on type conflict); bulk-creates
    missing keys with the batch envelope (max-merge later is identity).
    The ONE implementation of key resolution for both engines:
    `TpuMergeEngine._resolve_keys` delegates here with `resident=True`
    when it holds device mirrors, and host-only callers (engine/cpu.py
    merge_many, the serve/stream coalescers' flushes) use the default."""
    import logging

    n = batch.n_keys
    st.keys_seen += n
    if n == 0:
        return np.zeros(0, dtype=_I64)
    n0 = store.keys.n
    # one native batch call: intern every key; new ids ARE the new rows
    kid_of, n_new = store.key_index.get_or_insert_batch(batch.keys)
    if n_new:
        # a raw op-stream batch may repeat a key: append one row per new
        # id, values from its first occurrence (np.unique's sorted order
        # IS insertion order — interner ids grow with first occurrence)
        created = np.nonzero(kid_of >= n0)[0]
        uniq_ids, first = np.unique(kid_of[created], return_index=True)
        pos = created[first]
        # interner ids must be exactly the next table block — checked
        # BEFORE the append mutates the table (CHECK-THEN-MUTATE: a
        # failure after append_block would strand half-created rows;
        # and a real raise, because python -O strips asserts)
        if len(uniq_ids) != n_new or int(uniq_ids[0]) != n0 or \
                int(uniq_ids[-1]) != n0 + n_new - 1:
            span = f"[{int(uniq_ids[0])}, {int(uniq_ids[-1])}]" \
                if len(uniq_ids) else "[]"
            raise RuntimeError(
                f"key interner issued non-contiguous new ids {span} "
                f"(n={len(uniq_ids)}) for block [{n0}, {n0 + n_new - 1}]")
        store.keys.append_block(
            n_new,
            enc=batch.key_enc[pos], ct=batch.key_ct[pos], mt=0,
            dt=batch.key_dt[pos], expire=0, rv_t=0, rv_node=0, cnt_sum=0)
        store.key_bytes.extend(map(batch.keys.__getitem__, pos.tolist()))
        store.reg_val.extend([None] * n_new)
        st.keys_created += n_new
        if resident:
            # created rows carry batch first-occurrence values on the
            # host but neutral zeros on the device mirror; the batch rows
            # merging in reconstruct them, EXCEPT for conflict-skipped
            # duplicates — clear host values so both sides start neutral
            store.keys.ct[uniq_ids] = 0
            store.keys.dt[uniq_ids] = 0
    # conflict check over ALL positions: duplicate occurrences of a key
    # created above must also match the enc the first occurrence chose
    bad = np.nonzero(store.keys.enc[kid_of] != batch.key_enc)[0]
    if len(bad):
        log = logging.getLogger(__name__)
        for i in bad:
            log.error("type conflict merging key %r: local=%s incoming=%s",
                      batch.keys[i], int(store.keys.enc[kid_of[i]]),
                      int(batch.key_enc[i]))
        st.type_conflicts += len(bad)
        kid_of[bad] = -1
    return kid_of


def merge_host_batches(store: KeySpace, batches: list) -> MergeStats:
    """Resolve + merge a group of op-stream micro-batches entirely on the
    host (no engine object involved).  The fast path for host-only
    engines: one vectorized pass per batch instead of a per-row loop."""
    st = MergeStats()
    for b in batches:
        merge_host_batch(store, b, resolve_keys(store, b, st), st)
    return st


def merge_host_batch(store: KeySpace, batch: ColumnarBatch,
                     kid_of: np.ndarray, st: MergeStats) -> None:
    """Merge one columnar batch into the host store, fully vectorized.
    `kid_of` is the caller's key resolution (the engine's memoized
    `_resolve_keys`).  Duplicate rows per slot are folded by associative
    group reductions, so raw op-stream batches
    (`rows_unique_per_slot=False`) are first-class here."""
    valid = kid_of >= 0
    all_valid = bool(valid.all())
    if batch.n_keys:
        kids = kid_of if all_valid else kid_of[valid]
        if len(kids):
            mat = np.stack([batch.key_ct, batch.key_mt, batch.key_dt,
                            batch.key_expire], axis=-1)
            _merge_env(store, kids, mat if all_valid else mat[valid])

        from ..utils.native_tables import nonnull_mask
        em = (kid_of >= 0) & (batch.key_enc == S.ENC_BYTES) & \
            nonnull_mask(batch.reg_val)
        idx = np.nonzero(em)[0]
        if len(idx):
            _merge_reg(store, kid_of[idx], batch.reg_t[idx],
                       batch.reg_node[idx],
                       list(map(batch.reg_val.__getitem__, idx.tolist())))

    if len(batch.cnt_ki):
        kid_arr = kid_of[batch.cnt_ki]
        keep = np.nonzero(kid_arr >= 0)[0]
        if len(keep):
            st.counter_rows += len(keep)
            sel = slice(None) if len(keep) == len(kid_arr) else keep
            rows = _resolve_cnt_rows(store, kid_arr[sel], batch.cnt_node[sel])
            _apply_cnt_pair(store, rows, batch.cnt_val[sel],
                            batch.cnt_uuid[sel], "val", "uuid", 1)
            bt = batch.cnt_base_t[sel]
            if not (bt == S.NEUTRAL_T).all():
                _apply_cnt_pair(store, rows, batch.cnt_base[sel], bt,
                                "base", "base_t", -1)

    if len(batch.el_ki):
        kid_arr = kid_of[batch.el_ki]
        keep = np.nonzero(kid_arr >= 0)[0]
        if len(keep):
            st.elem_rows += len(keep)
            if len(keep) == len(kid_arr):
                sel = slice(None)
                members = batch.el_member
                vals = batch.el_val
            else:
                sel = keep
                members = list(map(batch.el_member.__getitem__,
                                   keep.tolist()))
                vals = list(map(batch.el_val.__getitem__, keep.tolist()))
            rows = _resolve_el_rows(store, kid_arr[sel], members)
            _merge_el(store, rows, batch.el_add_t[sel],
                      batch.el_add_node[sel], batch.el_del_t[sel], vals)

    if len(batch.tns_ki):
        merge_host_tns(store, batch, kid_of, st)

    for i, key in enumerate(batch.del_keys):
        store.record_key_delete(key, int(batch.del_t[i]))


def merge_host_tns(store: KeySpace, batch: ColumnarBatch,
                   kid_of: np.ndarray, st: MergeStats) -> None:
    """Tensor plane, HOST strategy: the per-row reference loop
    (KeySpace.tensor_merge_row — the ONE slot-merge implementation; the
    op path and the CPU engine run the same calls).  Tensor rows are
    few and payload-heavy, so the per-row Python here IS the measured
    host baseline the resident device path (engine/tpu.py
    _merge_micro_tns) must beat — and the two are differential-tested
    byte-identical."""
    kid_arr = kid_of[batch.tns_ki]
    merge_row = store.tensor_merge_row
    nodes = batch.tns_node
    uuids = batch.tns_uuid
    cnts = batch.tns_cnt
    cfgs = batch.tns_cfg
    payloads = batch.tns_payload
    kept = 0
    for i, kid in enumerate(kid_arr.tolist()):
        if kid < 0:
            continue
        kept += 1
        merge_row(kid, int(nodes[i]), int(uuids[i]), int(cnts[i]),
                  cfgs[i], payloads[i])
    st.tensor_rows += kept
