"""CPU reference MergeEngine: the per-row loop the TPU engine must match.

Semantics per crdt/semantics.py; this is also the measured CPU baseline for
bench.py (the equivalent of the reference's single-key merge path,
src/db.rs:31-43 → src/object.rs:63-83 → per-type merges).
"""

from __future__ import annotations

import logging

from ..crdt import semantics as S
from ..store.keyspace import KeySpace
from .base import ColumnarBatch, MergeStats
from .hostbatch import HOST_MICRO_MAX, HOST_ROW_MIN

log = logging.getLogger(__name__)


class CpuMergeEngine:
    name = "cpu"
    # host-only engine: nothing ever defers, so the streaming surface
    # (engine/base.py MergeEngine) is trivial
    needs_flush = False

    def merge_many(self, store: KeySpace,
                   batches: list) -> MergeStats:
        # op-stream micro-batches (the serve/stream coalescers' flushes)
        # take the vectorized host strategy — bit-identical to the per-row
        # loop below (engine/hostbatch.py docstring; differential-tested in
        # tests/test_host_combine.py and the coalescer suites), dozens of
        # times cheaper at a few hundred rows.  Bulk snapshot groups keep
        # the per-row reference path: this engine IS the measured baseline
        # and the verification oracle for those.
        total_rows = sum(b.n_rows for b in batches)
        if total_rows <= HOST_MICRO_MAX and \
                not all(b.rows_unique_per_slot for b in batches):
            # ...except TINY runs (a read-heavy pipeline's interleaved
            # write clusters, an idle stream flush): below ~2 dozen rows
            # the vectorized pass's numpy fixed costs exceed the whole
            # per-row loop, and the loop IS the reference the vectorized
            # path is differential-pinned against — routing by size can
            # never change bytes, only wall time (measured crossover
            # ~30 rows on the r18 builder box)
            if total_rows > HOST_ROW_MIN:
                from .hostbatch import merge_host_batches
                return merge_host_batches(store, batches)
        st = MergeStats()
        for b in batches:
            st += self.merge(store, b)
        return st

    def flush(self, store: KeySpace) -> None:
        return None

    def merge(self, store: KeySpace, batch: ColumnarBatch) -> MergeStats:
        st = MergeStats()
        n = batch.n_keys
        st.keys_seen = n

        # map batch key position -> local kid (-1 = type conflict, skip)
        kid_of = [-1] * n
        for i in range(n):
            key = batch.keys[i]
            enc = int(batch.key_enc[i])
            kid = store.key_index.lookup(key)
            if kid < 0:
                kid = store.create_key(key, enc, int(batch.key_ct[i]), int(batch.key_dt[i]))
                store.keys.mt[kid] = batch.key_mt[i]
                st.keys_created += 1
            elif store.enc_of(kid) != enc:
                # parity: reference db.rs:31-43 logs and skips on conflict
                log.error("type conflict merging key %r: local=%s incoming=%s",
                          key, store.enc_of(kid), enc)
                st.type_conflicts += 1
                continue
            else:
                ct, mt, dt = store.envelope(kid)
                ct, mt, dt = S.merge_envelope(ct, mt, dt, int(batch.key_ct[i]),
                                              int(batch.key_mt[i]), int(batch.key_dt[i]))
                store.keys.ct[kid], store.keys.mt[kid], store.keys.dt[kid] = ct, mt, dt
            kid_of[i] = kid
            exp = int(batch.key_expire[i])
            if exp > int(store.keys.expire[kid]):
                store.keys.expire[kid] = exp
            if enc == S.ENC_BYTES and batch.reg_val[i] is not None:
                store.register_merge(kid, batch.reg_val[i], int(batch.reg_t[i]),
                                     int(batch.reg_node[i]))

        for r in range(len(batch.cnt_ki)):
            kid = kid_of[int(batch.cnt_ki[r])]
            if kid < 0:
                continue
            store.counter_merge_slot(kid, int(batch.cnt_node[r]),
                                     int(batch.cnt_val[r]), int(batch.cnt_uuid[r]),
                                     int(batch.cnt_base[r]), int(batch.cnt_base_t[r]))
            st.counter_rows += 1

        for r in range(len(batch.el_ki)):
            kid = kid_of[int(batch.el_ki[r])]
            if kid < 0:
                continue
            store.elem_merge(kid, batch.el_member[r], int(batch.el_add_t[r]),
                             int(batch.el_add_node[r]), int(batch.el_del_t[r]),
                             batch.el_val[r])
            st.elem_rows += 1

        for r in range(len(batch.tns_ki)):
            kid = kid_of[int(batch.tns_ki[r])]
            if kid < 0:
                continue
            store.tensor_merge_row(kid, int(batch.tns_node[r]),
                                   int(batch.tns_uuid[r]),
                                   int(batch.tns_cnt[r]),
                                   batch.tns_cfg[r], batch.tns_payload[r])
            st.tensor_rows += 1

        for i, key in enumerate(batch.del_keys):
            store.record_key_delete(key, int(batch.del_t[i]))

        return st
