from .base import ColumnarBatch, MergeEngine, MergeStats, batch_from_keyspace
from .cpu import CpuMergeEngine

__all__ = ["ColumnarBatch", "MergeEngine", "MergeStats", "batch_from_keyspace", "CpuMergeEngine"]


def default_engine():
    """The engine used for bulk merges: batched JAX engine when available,
    CPU reference engine otherwise."""
    try:
        from .tpu import TpuMergeEngine

        return TpuMergeEngine()
    except Exception:  # jax missing or device init failure
        return CpuMergeEngine()
