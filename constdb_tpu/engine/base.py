"""MergeEngine boundary: bulk CRDT merges over columnar batches.

This is the seam the north-star targets (BASELINE.json): snapshot ingest and
replica catch-up produce `ColumnarBatch`es (foreign CRDT state as
struct-of-arrays), and an engine merges them into the local `KeySpace`.
The CPU engine is the semantics reference; the JAX engine (engine/tpu.py)
runs the same rules as batched scatter reductions on device.

The per-key loops this replaces in the reference:
`DB::merge_entry` → `Object::merge` → `Counter::merge` / `Set::merge` /
`Dict::merge` (reference src/db.rs:31-43, src/object.rs:63-83,
src/type_counter.rs:59-91, src/crdt/lwwhash.rs:176-181, 319-323).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Protocol

import numpy as np

from ..store.keyspace import KeySpace

_I64 = np.int64


@dataclass
class ColumnarBatch:
    """Foreign CRDT state in columnar form.

    Key-aligned arrays are indexed by *batch key position* (bki); counter and
    element rows point into the key arrays via `cnt_ki` / `el_ki`.
    """

    # keys
    keys: list = field(default_factory=list)           # bytes per batch key
    key_enc: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int8))
    key_ct: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    key_mt: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    key_dt: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    key_expire: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    # registers (aligned with keys; unused slots hold None/0)
    reg_val: list = field(default_factory=list)
    reg_t: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    reg_node: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    # counter slots: (lifetime total @ uuid) + (delete-observed base @ base_t)
    cnt_ki: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    cnt_node: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    cnt_val: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    cnt_uuid: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    cnt_base: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    cnt_base_t: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    # elements (set members / dict fields)
    el_ki: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    el_member: list = field(default_factory=list)
    el_val: list = field(default_factory=list)
    el_add_t: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    el_add_node: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    el_del_t: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    # tensor contributor slots (crdt/tensor.py two-layer registers):
    # one row per (key, writer node) contribution — LWW stamp + count
    # columns, the packed per-key config riding every row (rows of one
    # key carry identical configs; the first merge fixes it), and the
    # payload as a flat array of the key's dtype (or raw LE bytes on
    # the wire — engines normalize via tensor.payload_array)
    tns_ki: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    tns_node: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    tns_uuid: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    tns_cnt: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    tns_cfg: list = field(default_factory=list)
    tns_payload: list = field(default_factory=list)
    # standalone key-level tombstones (snapshot DELETES section)
    del_keys: list = field(default_factory=list)
    del_t: np.ndarray = field(default_factory=lambda: np.zeros(0, _I64))
    # contract: at most one counter row per (key, node) and one element row
    # per (key, member).  True for snapshot dumps (batch_from_keyspace, the
    # snapshot loader); batches built from raw op streams must leave this
    # False so the engine's dense path (last-write-per-slot placement) is
    # skipped in favor of the duplicate-safe scatter reduction.
    rows_unique_per_slot: bool = False
    # identity tokens (not serialized): chunks sliced from batches that
    # SHARE their key/element plane objects — replica snapshots of one
    # keyspace — carry equal tokens, letting the engine resolve each
    # distinct shape once instead of once per replica (batch_chunks sets
    # them; engine/tpu.py merge_many / _merge_elem_rows memoize on them).
    # Equal tokens guarantee equal content: they embed the ids of the
    # parent objects plus the slice bounds, and `shape_refs` pins those
    # parents alive so the ids cannot be recycled while a chunk exists.
    key_shape: object = None
    el_shape: object = None
    shape_refs: object = field(default=None, repr=False)
    # hint: False = PROVABLY no element values (chunks inherit their
    # parent's one-time scan — any subset of an all-None list is all
    # None).  True/None = values may exist; consumers re-scan their own
    # (smaller) list with has_values().
    el_has_vals: object = None

    @property
    def n_keys(self) -> int:
        return len(self.keys)

    @property
    def n_rows(self) -> int:
        return (len(self.keys) + len(self.cnt_ki) + len(self.el_ki)
                + len(self.tns_ki))


def concat_batches(batches: list) -> ColumnarBatch:
    """Concatenate op-stream batches plane-wise into ONE wide batch
    (row-plane `*_ki` indices shifted past the earlier batches' keys).

    Sound for duplicate-safe consumers only: the result repeats key
    slots and row slots across the inputs, so it must land through the
    scatter-reduction paths (`rows_unique_per_slot` stays False —
    resolve_keys interns repeats, the fold_* reductions pick the same
    associative winners folding once as merging the inputs in order).
    This is what makes a replay MERGE ROUND genuinely wide: one key
    resolution and one vectorized pass per plane per round, instead of
    one per few-hundred-row record (persist/oplog.py _merge_round).

    Row order within each plane preserves input order, so the per-row
    planes (tensors) replay exactly as the sequential merges would."""
    if len(batches) == 1:
        return batches[0]
    out = ColumnarBatch()
    offs = np.cumsum([0] + [b.n_keys for b in batches[:-1]])

    def cat(name):
        return np.concatenate([getattr(b, name) for b in batches])

    def cat_ki(name):
        return np.concatenate([getattr(b, name) + off
                               for b, off in zip(batches, offs)])

    def cat_list(name):
        o = []
        for b in batches:
            o.extend(getattr(b, name))
        return o

    out.keys = cat_list("keys")
    out.key_enc = cat("key_enc")
    out.key_ct = cat("key_ct")
    out.key_mt = cat("key_mt")
    out.key_dt = cat("key_dt")
    out.key_expire = cat("key_expire")
    out.reg_val = cat_list("reg_val")
    out.reg_t = cat("reg_t")
    out.reg_node = cat("reg_node")
    out.cnt_ki = cat_ki("cnt_ki")
    out.cnt_node = cat("cnt_node")
    out.cnt_val = cat("cnt_val")
    out.cnt_uuid = cat("cnt_uuid")
    out.cnt_base = cat("cnt_base")
    out.cnt_base_t = cat("cnt_base_t")
    out.el_ki = cat_ki("el_ki")
    out.el_member = cat_list("el_member")
    out.el_val = cat_list("el_val")
    out.el_add_t = cat("el_add_t")
    out.el_add_node = cat("el_add_node")
    out.el_del_t = cat("el_del_t")
    out.tns_ki = cat_ki("tns_ki")
    out.tns_node = cat("tns_node")
    out.tns_uuid = cat("tns_uuid")
    out.tns_cnt = cat("tns_cnt")
    out.tns_cfg = cat_list("tns_cfg")
    out.tns_payload = cat_list("tns_payload")
    out.del_keys = cat_list("del_keys")
    out.del_t = cat("del_t")
    if all(b.el_has_vals is False for b in batches):
        out.el_has_vals = False
    return out


def has_values(vals: list) -> bool:
    """Single home for the has-element-values predicate (list.count scans
    at C speed; empty bytes count as values, only None is absent — the
    same distinction _pool_add's byte accounting makes)."""
    return len(vals) != vals.count(None)


@dataclass
class MergeStats:
    keys_seen: int = 0
    keys_created: int = 0
    type_conflicts: int = 0
    counter_rows: int = 0
    elem_rows: int = 0
    tensor_rows: int = 0
    # device-transfer accounting for THIS call (engine/tpu.py fills them
    # from its cumulative counters; host-only engines leave zeros).
    # dev_rounds_resident counts micro rounds merged in place against
    # resident device planes — the steady-state residency signal the
    # bench legs and the v5e acceptance criterion read.
    dev_upload_bytes: int = 0
    dev_download_bytes: int = 0
    dev_rounds_resident: int = 0
    # rows a flush actually downloaded during this call (auto-flushes);
    # the engine's cumulative attribute of the same name covers explicit
    # flush() calls too
    flush_rows_downloaded: int = 0

    def __iadd__(self, other: "MergeStats") -> "MergeStats":
        self.keys_seen += other.keys_seen
        self.keys_created += other.keys_created
        self.type_conflicts += other.type_conflicts
        self.counter_rows += other.counter_rows
        self.elem_rows += other.elem_rows
        self.tensor_rows += other.tensor_rows
        self.dev_upload_bytes += other.dev_upload_bytes
        self.dev_download_bytes += other.dev_download_bytes
        self.dev_rounds_resident += other.dev_rounds_resident
        self.flush_rows_downloaded += other.flush_rows_downloaded
        return self


class MergeEngine(Protocol):
    """The streaming merge surface callers (bench, replica link) rely on.

    `merge_many` folds a GROUP of batches in one pass per CRDT family —
    the pipelined engine overlaps host staging with device compute inside
    it.  Engines holding deferred device state set `needs_flush` and write
    it back on `flush` (host-only engines keep both trivial), so a caller
    can drive any engine with the same
    merge_many → … → flush cadence instead of hasattr probing."""

    name: str
    needs_flush: bool

    def merge(self, store: KeySpace, batch: ColumnarBatch) -> MergeStats: ...

    def merge_many(self, store: KeySpace,
                   batches: list) -> MergeStats: ...

    def flush(self, store: KeySpace) -> None: ...


def batch_from_keyspace(ks: KeySpace, include_deletes: bool = True,
                        key_sel: Optional[np.ndarray] = None) -> ColumnarBatch:
    """Dump a keyspace's full logical state as a batch (snapshot body /
    merge-test vehicle).  GC-freed element rows are excluded.

    `key_sel`: restrict the dump to these key rows (int64 kid array) —
    the range-scoped delta export the digest anti-entropy streams for
    divergent buckets (store/digest.py export_bucket_batch).  Counter
    and element rows of unselected keys are dropped and the survivors
    re-pointed at batch-local key positions.  `key_deletes` are NOT
    key-rows and ride unfiltered when `include_deletes` (scoped callers
    filter them by bucket themselves)."""
    b = ColumnarBatch()
    b.rows_unique_per_slot = True  # a state dump has one row per slot
    n = ks.keys.n
    if key_sel is None:
        b.keys = list(ks.key_bytes)
        b.key_enc = ks.keys.enc.copy()
        b.key_ct = ks.keys.ct.copy()
        b.key_mt = ks.keys.mt.copy()
        b.key_dt = ks.keys.dt.copy()
        b.key_expire = ks.keys.expire.copy()
        b.reg_val = list(ks.reg_val)
        b.reg_t = ks.keys.rv_t.copy()
        b.reg_node = ks.keys.rv_node.copy()

        b.cnt_ki = ks.cnt.kid.copy()
        b.cnt_node = ks.cnt.node.copy()
        b.cnt_val = ks.cnt.val.copy()
        b.cnt_uuid = ks.cnt.uuid.copy()
        b.cnt_base = ks.cnt.base.copy()
        b.cnt_base_t = ks.cnt.base_t.copy()

        live = ks.el.kid >= 0
        b.el_ki = ks.el.kid[live].copy()
        b.el_add_t = ks.el.add_t[live].copy()
        b.el_add_node = ks.el.add_node[live].copy()
        b.el_del_t = ks.el.del_t[live].copy()
        rows = np.nonzero(live)[0]
        b.el_member = [ks.el_member[r] for r in rows]
        b.el_val = [ks.el_val[r] for r in rows]
        _tns_dump(ks, b)
        assert n == len(b.keys)
    else:
        sel = np.asarray(key_sel, dtype=_I64)
        idx = sel.tolist()
        b.keys = [ks.key_bytes[i] for i in idx]
        b.key_enc = np.ascontiguousarray(ks.keys.enc[sel])
        b.key_ct = np.ascontiguousarray(ks.keys.ct[sel])
        b.key_mt = np.ascontiguousarray(ks.keys.mt[sel])
        b.key_dt = np.ascontiguousarray(ks.keys.dt[sel])
        b.key_expire = np.ascontiguousarray(ks.keys.expire[sel])
        b.reg_val = [ks.reg_val[i] for i in idx]
        b.reg_t = np.ascontiguousarray(ks.keys.rv_t[sel])
        b.reg_node = np.ascontiguousarray(ks.keys.rv_node[sel])

        posmap = np.full(n, -1, dtype=_I64)
        posmap[sel] = np.arange(len(sel), dtype=_I64)
        if ks.cnt.n:
            cm = np.nonzero(posmap[ks.cnt.kid] >= 0)[0]
            b.cnt_ki = posmap[ks.cnt.kid[cm]]
            b.cnt_node = np.ascontiguousarray(ks.cnt.node[cm])
            b.cnt_val = np.ascontiguousarray(ks.cnt.val[cm])
            b.cnt_uuid = np.ascontiguousarray(ks.cnt.uuid[cm])
            b.cnt_base = np.ascontiguousarray(ks.cnt.base[cm])
            b.cnt_base_t = np.ascontiguousarray(ks.cnt.base_t[cm])
        if ks.el.n:
            ekid = ks.el.kid
            em = np.nonzero((ekid >= 0) & (posmap[ekid] >= 0))[0]
            b.el_ki = posmap[ekid[em]]
            b.el_add_t = np.ascontiguousarray(ks.el.add_t[em])
            b.el_add_node = np.ascontiguousarray(ks.el.add_node[em])
            b.el_del_t = np.ascontiguousarray(ks.el.del_t[em])
            rows = em.tolist()
            b.el_member = [ks.el_member[r] for r in rows]
            b.el_val = [ks.el_val[r] for r in rows]
        if ks.tns.n:
            _tns_dump(ks, b, posmap=posmap)

    if include_deletes and ks.key_deletes:
        b.del_keys = list(ks.key_deletes.keys())
        b.del_t = np.fromiter(ks.key_deletes.values(), dtype=_I64, count=len(ks.key_deletes))
    return b


def _tns_dump(ks: KeySpace, b: ColumnarBatch,
              posmap: Optional[np.ndarray] = None) -> None:
    """Dump the tensor plane into a batch: real contributions only
    (neutral-stamped slots never ship — a fresh store materializes them
    on merge), each row carrying its key's packed config (computed once
    per key).  `posmap`: kid -> batch position for key_sel dumps."""
    from ..crdt import tensor as T
    from ..crdt.semantics import NEUTRAL_T

    n = ks.tns.n
    if not n:
        return
    sel = ks.tns.uuid[:n] != NEUTRAL_T
    if posmap is not None:
        sel &= posmap[ks.tns.kid[:n]] >= 0
    rows = np.nonzero(sel)[0]
    if not len(rows):
        return
    kids = ks.tns.kid[rows]
    b.tns_ki = kids.copy() if posmap is None else posmap[kids]
    b.tns_node = ks.tns.node[rows].copy()
    b.tns_uuid = ks.tns.uuid[rows].copy()
    b.tns_cnt = ks.tns.cnt[rows].copy()
    cfg_of: dict = {}
    cfgs = []
    for kid in kids.tolist():
        c = cfg_of.get(kid)
        if c is None:
            c = cfg_of[kid] = T.pack_config(ks.tns_meta[kid])
        cfgs.append(c)
    b.tns_cfg = cfgs
    b.tns_payload = [ks.tns_payload[r] for r in rows.tolist()]
