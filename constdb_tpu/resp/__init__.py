from .message import (
    Msg, Nil, NoReply, Simple, Err, Bulk, Int, Arr,
    NIL, NO_REPLY, OK, msg_size, mkcmd, as_bytes, as_int, as_uint,
)
from .codec import encode_msg, encode_into, RespParser

__all__ = [
    "Msg", "Nil", "NoReply", "Simple", "Err", "Bulk", "Int", "Arr",
    "NIL", "NO_REPLY", "OK", "msg_size", "mkcmd", "as_bytes", "as_int", "as_uint",
    "encode_msg", "encode_into", "RespParser",
]
