"""Incremental RESP parser and encoder.

Capability parity with the reference's hand-rolled read/write buffers
(reference src/conn/buf_read.rs:114-211 recursive-descent parser with
NeedMoreMsg + compaction; src/conn/buf_write.rs:32-159 encoder).

The parser consumes from an internal bytearray; `feed()` appends raw socket
bytes, `next_msg()` returns one complete message or None.  Partial input never
raises — the cursor only advances past fully parsed messages.  Consumed bytes
are compacted away lazily once they exceed a threshold.
"""

from __future__ import annotations

from typing import Optional

from ..errors import InvalidRequestMsg
from .message import (Arr, Bulk, Err, Int, Msg, NIL, NO_REPLY, Nil,
                      NoReply, Push, Simple)

_CRLF = b"\r\n"
_COMPACT_THRESHOLD = 1 << 16
# interned small-int reply lines (parity: reference src/resp.rs:12-27
# pre-encodes the common counter replies)
_INT_REPLY = [b":%d\r\n" % i for i in range(1024)]

_DEFAULT_MAX_BULK = 512 << 20  # Redis proto-max-bulk-len default
_MAX_BULK_CACHE: list = []


def max_bulk_len() -> int:
    """The parse-time bulk-length ceiling (CONSTDB_PROTO_MAX_BULK,
    Redis-style 512MB default).  A `$`-header past it is a PROTOCOL
    error the moment the header line parses — the parser never buffers
    toward an absurd declared length, so a malicious `$99999999999`
    costs one error reply, not an allocation (overload governance,
    docs/INVARIANTS.md "Degradation laws").  Cached at first use;
    clamped to the wire format's hard 512MB ceiling."""
    if not _MAX_BULK_CACHE:
        from ..conf import env_int
        _MAX_BULK_CACHE.append(
            min(max(1, env_int("CONSTDB_PROTO_MAX_BULK",
                               _DEFAULT_MAX_BULK)), _DEFAULT_MAX_BULK))
    return _MAX_BULK_CACHE[0]


def encode_into(out: bytearray, m: Msg) -> None:
    """Append m's wire encoding to `out` — native fast path when the
    extension is built (interned small-int replies, C-speed bulk arrays),
    bit-identical pure-Python fallback otherwise (and for any shape the
    C encoder declines: subclasses, big ints, non-bytes payloads)."""
    enc = _enc()
    if enc is not None and enc(out, m, Arr, Bulk, Int, Simple, Err, Nil,
                               NoReply):
        return
    _py_encode_into(out, m)


def _py_encode_into(out: bytearray, m: Msg) -> None:
    if isinstance(m, NoReply):
        return
    if isinstance(m, Nil):
        out += b"$-1\r\n"
    elif isinstance(m, Simple):
        out += b"+"
        out += m.val
        out += _CRLF
    elif isinstance(m, Err):
        out += b"-"
        out += m.val
        out += _CRLF
    elif isinstance(m, Int):
        v = m.val
        out += _INT_REPLY[v] if 0 <= v < 1024 else b":%d\r\n" % v
    elif isinstance(m, Bulk):
        out += b"$%d\r\n" % len(m.val)
        out += m.val
        out += _CRLF
    elif isinstance(m, Push):
        # ordered before Arr (Push subclasses it): RESP3 push frames
        # carry the '>' type byte but are otherwise array-shaped.  The
        # native encoder declines subclasses, so this branch is the only
        # encode path for pushes — RESP2 replies never reach it.
        out += b">%d\r\n" % len(m.items)
        for item in m.items:
            if isinstance(item, NoReply):
                raise TypeError("NoReply inside Push would desync the frame")
            encode_into(out, item)
    elif isinstance(m, Arr):
        out += b"*%d\r\n" % len(m.items)
        for item in m.items:
            if isinstance(item, NoReply):
                raise TypeError("NoReply inside Arr would desync the frame")
            encode_into(out, item)
    else:
        raise TypeError(f"cannot encode {m!r}")


def encode_msg(m: Msg) -> bytes:
    out = bytearray()
    encode_into(out, m)
    return bytes(out)


class _NeedMore(Exception):
    pass


_NEED_MORE = _NeedMore()


class RespParser:
    __slots__ = ("_buf", "_pos", "max_depth", "max_bulk", "_q", "_qpos")

    def __init__(self, max_depth: int = 32, max_bulk: Optional[int] = None):
        self._buf = bytearray()
        self._pos = 0
        self.max_depth = max_depth
        self.max_bulk = max_bulk_len() if max_bulk is None else max_bulk
        # already-parsed messages awaiting delivery: the native subclass
        # fast-parses whole pipelines in one C call, and `pushback`
        # re-queues messages a caller drained but does not own (server/io.py
        # hands post-SYNC messages back to the replica link this way)
        self._q: list = []
        self._qpos = 0

    def feed(self, data) -> None:
        self._buf += data

    @property
    def buffered(self) -> int:
        return len(self._buf) - self._pos

    def _compact(self) -> None:
        """Drop consumed bytes once they pass the threshold (single home
        for the policy — next_msg fast/general paths, take_raw, and the
        native subclass all share it)."""
        if self._pos >= _COMPACT_THRESHOLD:
            del self._buf[: self._pos]
            self._pos = 0

    def take_raw(self, n: int) -> bytes:
        """Up to n RAW bytes from the internal buffer.  Snapshot transfer
        interleaves length-delimited raw byte runs with RESP frames on one
        stream (reference src/conn/reader.rs:104-121 `save_to_file`); the
        parser may have buffered past the frame boundary, so the raw run
        must drain from here before reading the socket directly."""
        end = min(self._pos + n, len(self._buf))
        data = bytes(self._buf[self._pos:end])
        self._pos = end
        self._compact()
        return data

    def next_msg(self) -> Optional[Msg]:
        """One complete message, or None if more bytes are needed.
        Raises InvalidRequestMsg on malformed input."""
        q = self._q
        if self._qpos < len(q):
            m = q[self._qpos]
            self._qpos += 1
            if self._qpos >= len(q):
                q.clear()
                self._qpos = 0
            return m
        return self._parse_one()

    def take_queued(self) -> list:
        """Pop every already-parsed message out of the delivery queue
        without touching the byte buffer.  The connection loop's error
        path uses this to salvage the clean prefix a failed drain()
        stashed (see drain) before writing the protocol error."""
        q = self._q
        out = q[self._qpos:] if self._qpos < len(q) else []
        q.clear()
        self._qpos = 0
        return out

    def drain(self) -> list:
        """Every complete message currently buffered, in arrival order
        (the serve path plans a whole pipelined chunk at once —
        server/io.py).  Equivalent to looping next_msg() until None, but
        the native subclass hands the whole run over in one C call.
        Raises InvalidRequestMsg on malformed input; messages parsed
        before the bad frame stay queued for the error path."""
        out = self.take_queued()
        try:
            while True:
                m = self._parse_one()
                if m is None:
                    return out
                out.append(m)
                if self._q:
                    out.extend(self.take_queued())
        except InvalidRequestMsg:
            # stash the clean prefix: the caller's error path can still
            # execute/reply the messages that parsed before the bad frame
            # (take_queued) instead of silently dropping them
            self._q = out
            self._qpos = 0
            raise

    def native_drain(self):
        """One C pass over the buffered pipeline: split AND classify.

        Returns `(ops, payloads)` — parallel lists where ops[i] is a
        serve-plane opcode (server/serve.py _OP_*; 0 = OTHER with a full
        Msg payload) — or None when the native intake stage is
        unavailable or produced nothing.  The scan stops early at any
        frame it will not own (partial, malformed, SYNC upgrade,
        oversized); those bytes stay buffered for drain()/next_msg(),
        which re-parses them with the reference error behavior.  Base
        class: the stage needs the C scanner, so always None."""
        return None

    def pushback(self, msgs: list) -> None:
        """Re-queue already-drained messages at the FRONT of the delivery
        order (they re-emerge from next_msg()/drain() before anything
        still in the byte buffer).  Used when a drained chunk turns out
        to straddle an ownership boundary — e.g. a SYNC upgrade hands the
        connection (and every message after the SYNC) to the replica
        link.  Note take_raw() reads the BYTE buffer and ignores this
        queue; raw snapshot runs never mix with pushed-back messages."""
        if not msgs:
            return
        rest = self.take_queued()
        self._q = list(msgs) + rest
        self._qpos = 0

    def _parse_one(self) -> Optional[Msg]:
        buf = self._buf
        pos = self._pos
        blen = len(buf)
        if pos >= blen:
            return None
        if buf[pos] == 0x2A:  # '*' — fast path: flat array of bulk strings,
            # the shape of every client command (pipelined op throughput
            # lives or dies here); anything else falls back to _parse
            find = buf.find
            e = find(_CRLF, pos + 1)
            if e < 0:
                if blen - pos > 1 << 20:
                    raise InvalidRequestMsg("line too long")
                return None
            try:
                n = int(buf[pos + 1:e])
            except ValueError:
                raise InvalidRequestMsg("invalid array length") from None
            if 0 <= n <= 1 << 20:
                items = []
                p = e + 2
                for _ in range(n):
                    if p >= blen:
                        break
                    c = buf[p]
                    if c == 0x24:  # '$' bulk
                        e = find(_CRLF, p + 1)
                        if e < 0:
                            break
                        try:
                            ln = int(buf[p + 1:e])
                        except ValueError:
                            raise InvalidRequestMsg(
                                "invalid bulk length") from None
                        if ln > self.max_bulk:
                            # same cap as the general path below: a huge
                            # declared length must fail fast, not buffer
                            raise InvalidRequestMsg("bulk string too large")
                        if ln < 0:
                            break  # $-1 Nil inside arrays: general path
                        end = e + 2 + ln + 2
                        if end > blen:
                            break
                        if buf[end - 2:end] != _CRLF:
                            raise InvalidRequestMsg("bulk string missing CRLF")
                        items.append(Bulk(bytes(buf[e + 2:end - 2])))
                        p = end
                    elif c == 0x3A:  # ':' int (replication frames)
                        e = find(_CRLF, p + 1)
                        if e < 0:
                            break
                        try:
                            items.append(Int(int(buf[p + 1:e])))
                        except ValueError:
                            raise InvalidRequestMsg(
                                "invalid integer line") from None
                        p = e + 2
                    else:
                        break  # nested/unusual item: general path
                else:
                    self._pos = p
                    self._compact()
                    return Arr(items)
                # partial or non-flat frame: fall through to _parse below
        start = pos
        try:
            m = self._parse(0)
        except _NeedMore:
            self._pos = start
            return None
        self._compact()
        return m

    # --- internals ---

    def _line(self) -> bytes:
        idx = self._buf.find(_CRLF, self._pos)
        if idx < 0:
            # guard: a line that never terminates is malformed, not "partial"
            if len(self._buf) - self._pos > 1 << 20:
                raise InvalidRequestMsg("line too long")
            raise _NEED_MORE
        line = bytes(self._buf[self._pos:idx])
        self._pos = idx + 2
        return line

    def _int_line(self) -> int:
        line = self._line()
        try:
            return int(line)
        except ValueError:
            raise InvalidRequestMsg(f"invalid integer line {line[:32]!r}") from None

    def _parse(self, depth: int) -> Msg:
        if depth > self.max_depth:
            raise InvalidRequestMsg("nesting too deep")
        if self._pos >= len(self._buf):
            raise _NEED_MORE
        t = self._buf[self._pos]
        self._pos += 1
        if t == 0x2B:  # '+'
            return Simple(self._line())
        if t == 0x2D:  # '-'
            return Err(self._line())
        if t == 0x3A:  # ':'
            return Int(self._int_line())
        if t == 0x24:  # '$'
            n = self._int_line()
            if n < 0:
                if n != -1:  # only $-1 is Nil; other negatives are malformed
                    raise InvalidRequestMsg("negative bulk length")
                return NIL
            if n > self.max_bulk:
                raise InvalidRequestMsg("bulk string too large")
            end = self._pos + n + 2
            if end > len(self._buf):
                raise _NEED_MORE
            val = bytes(self._buf[self._pos:self._pos + n])
            if self._buf[self._pos + n:end] != _CRLF:
                raise InvalidRequestMsg("bulk string missing CRLF")
            self._pos = end
            return Bulk(val)
        if t == 0x2A:  # '*'
            n = self._int_line()
            if n < 0:
                if n != -1:
                    raise InvalidRequestMsg("negative array length")
                return NIL
            if n > 1 << 20:
                raise InvalidRequestMsg("array too large")
            return Arr([self._parse(depth + 1) for _ in range(n)])
        if t == 0x3E:  # '>' — RESP3 push frame (client-side parse of
            # invalidation broadcasts; a push is never nil-length).  The
            # native scanners defer unknown type bytes here, so both
            # parsers share this one branch.
            n = self._int_line()
            if n < 0:
                raise InvalidRequestMsg("negative push length")
            if n > 1 << 20:
                raise InvalidRequestMsg("push frame too large")
            return Push([self._parse(depth + 1) for _ in range(n)])
        raise InvalidRequestMsg(f"unexpected type byte {bytes([t])!r}")


class NativeRespParser(RespParser):
    """RespParser with the flat-command fast path in C.

    `native/resp.cpp resp_parse` scans the shared buffer and returns
    fully-constructed Arr/Bulk/Int messages (built at C speed via
    tp_alloc + slot set); anything it cannot fast-parse — nested arrays,
    replies, `$-1`/`*0` — is handed, one message at a time, to the
    inherited pure-Python parser, so the output is bit-identical either
    way.  The op path is parse-bound (OPBENCH.md); this is our answer to
    the reference's N-parse-threads design (reference src/lib.rs:138-142)
    under the single-writer loop.
    """

    __slots__ = ()

    def native_drain(self):
        """The native intake stage (native/intake.cpp intake_scan): one C
        call consumes every leading well-formed flat command frame and
        returns opcodes + pre-flattened payloads for the plannable set.
        Declines (None) when the extension predates intake_scan, when
        pushed-back messages are queued (they must re-emerge first, in
        order), or when the scan consumed nothing."""
        scan = _intake()
        if scan is None or self._qpos < len(self._q):
            return None
        ops, payloads, new_pos = scan(
            self._buf, self._pos, Arr, Bulk, Int, Simple, Err, NIL,
            self.max_bulk)
        if not ops:
            return None
        self._pos = new_pos
        self._compact()
        return ops, payloads

    def _parse_one(self) -> Optional[Msg]:
        ext = _ext()
        if ext is None:
            return super()._parse_one()
        try:
            # max_bulk rides into the C scanner so an absurd $-header is
            # rejected at HEADER-parse time (the scanner defers it to the
            # pure parser, which raises) — never buffered toward.  A
            # prebuilt cst_ext.so predating the parameter rejects the
            # call shape; enforcement then falls to the pure parser,
            # which is only load-bearing below the 512MB hard ceiling
            # the old scanner already enforces.
            try:
                msgs, new_pos, fallback = ext.resp_parse(
                    self._buf, self._pos, Arr, Bulk, Int, Simple, Err,
                    NIL, 1024, self.max_bulk)
            except TypeError:
                if self.max_bulk < _DEFAULT_MAX_BULK:
                    return super()._parse_one()
                msgs, new_pos, fallback = ext.resp_parse(
                    self._buf, self._pos, Arr, Bulk, Int, Simple, Err,
                    NIL)
        except ValueError as e:
            raise InvalidRequestMsg(str(e)) from None
        self._pos = new_pos
        self._compact()
        if msgs:
            if len(msgs) > 1:
                # only called with the delivery queue empty (next_msg /
                # drain pop it first), so the overflow can take it over
                self._q = msgs
                self._qpos = 1
            return msgs[0]
        if fallback:
            return super()._parse_one()
        return None


_EXT_CACHE: list = []
_ENC_CACHE: list = []
_INTAKE_CACHE: list = []


def _ext():
    if not _EXT_CACHE:
        from ..utils.native_tables import load_ext
        mod = load_ext()
        _EXT_CACHE.append(mod if mod is not None and
                          hasattr(mod, "resp_parse") else None)
    return _EXT_CACHE[0]


def _enc():
    """The native encoder entry point, or None.  Gated SEPARATELY from the
    parser: a prebuilt cst_ext.so from before the encoder existed must
    degrade to the pure-Python path, not AttributeError on every reply."""
    if not _ENC_CACHE:
        from ..utils.native_tables import load_ext
        _ENC_CACHE.append(getattr(load_ext(), "resp_encode", None))
    return _ENC_CACHE[0]


def _intake():
    """The native intake entry point, or None.  Gated separately from
    resp_parse (same reasoning as _enc: a prebuilt cst_ext.so from before
    the intake stage existed must degrade, not AttributeError)."""
    if not _INTAKE_CACHE:
        from ..utils.native_tables import load_ext
        _INTAKE_CACHE.append(getattr(load_ext(), "intake_scan", None))
    return _INTAKE_CACHE[0]


def make_parser() -> RespParser:
    """The fastest available parser: native fast path when the extension
    is built, pure Python otherwise (identical message objects)."""
    return NativeRespParser() if _ext() is not None else RespParser()
