"""RESP message model.

Capability parity with the reference's `Message` enum and helpers
(reference src/resp.rs:35-43 enum, 100-129 size accounting, 133-145 mkcmd!).

Messages are small immutable objects:
  Simple(b)  -> +b\r\n          Err(b) -> -b\r\n        Int(i) -> :i\r\n
  Bulk(b)    -> $len\r\n b \r\n  Arr([..]) -> *len\r\n ...
  NIL        -> $-1\r\n          NO_REPLY -> nothing on the wire
"""

from __future__ import annotations

from typing import Iterable, Union

from ..errors import InvalidRequestMsg
from ..utils.bytesutil import bytes2i64, bytes2u64, i64_to_bytes


class Msg:
    __slots__ = ()


class Nil(Msg):
    __slots__ = ()

    def __repr__(self) -> str:
        return "Nil"

    def __eq__(self, other) -> bool:
        return isinstance(other, Nil)

    def __hash__(self) -> int:
        return hash("Nil")


class NoReply(Msg):
    """Maps to the reference's Message::None: nothing is written back."""

    __slots__ = ()

    def __repr__(self) -> str:
        return "NoReply"

    def __eq__(self, other) -> bool:
        return isinstance(other, NoReply)

    def __hash__(self) -> int:
        return hash("NoReply")


class _BytesMsg(Msg):
    __slots__ = ("val",)

    def __init__(self, val: Union[bytes, str]):
        self.val = val.encode() if isinstance(val, str) else bytes(val)

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.val!r})"

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other.val == self.val

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.val))


class Simple(_BytesMsg):
    __slots__ = ()


class Err(_BytesMsg):
    __slots__ = ()


class Bulk(_BytesMsg):
    __slots__ = ()


class Int(Msg):
    __slots__ = ("val",)

    def __init__(self, val: int):
        self.val = int(val)

    def __repr__(self) -> str:
        return f"Int({self.val})"

    def __eq__(self, other) -> bool:
        return type(other) is Int and other.val == self.val

    def __hash__(self) -> int:
        return hash(("Int", self.val))


class Arr(Msg):
    __slots__ = ("items",)

    def __init__(self, items: Iterable[Msg]):
        self.items = list(items)

    def __repr__(self) -> str:
        return f"Arr({self.items!r})"

    def __eq__(self, other) -> bool:
        return type(other) is Arr and other.items == self.items

    def __hash__(self) -> int:
        return hash(("Arr", tuple(self.items)))


class Push(Arr):
    """RESP3 push frame: >len\r\n ... — an out-of-band server-initiated
    message (invalidation broadcasts, server/tracking.py).  Subclasses
    Arr so every item-walking consumer works unchanged, but compares as
    its own type: a Push is NOT equal to an Arr with the same items
    (the wire type byte differs)."""

    __slots__ = ()

    def __repr__(self) -> str:
        return f"Push({self.items!r})"

    def __eq__(self, other) -> bool:
        return type(other) is Push and other.items == self.items

    def __hash__(self) -> int:
        return hash(("Push", tuple(self.items)))


NIL = Nil()
NO_REPLY = NoReply()
OK = Simple(b"OK")


def msg_size(m: Msg) -> int:
    """Payload size accounting for the repl-log byte cap (parity:
    reference src/resp.rs:100-110 `Message::size`)."""
    if isinstance(m, (Simple, Err, Bulk)):
        return len(m.val)
    if isinstance(m, Int):
        return 8
    if isinstance(m, Arr):
        return sum(msg_size(x) for x in m.items)
    return 0


def mkcmd(*parts) -> Arr:
    """Build a command Arr of Bulk strings from mixed str/bytes/int parts
    (parity: reference mkcmd! macro, src/resp.rs:133-145)."""
    out = []
    for p in parts:
        if isinstance(p, bytes):
            out.append(Bulk(p))
        elif isinstance(p, str):
            out.append(Bulk(p.encode()))
        elif isinstance(p, int):
            out.append(Bulk(i64_to_bytes(p)))
        elif isinstance(p, Msg):
            out.append(p)
        else:
            raise TypeError(f"mkcmd: unsupported part {p!r}")
    return Arr(out)


# --- argument coercion (parity: reference NextArg trait, src/cmd.rs:348-397) ---

def as_bytes(m: Msg) -> bytes:
    # exact-type fast path first: Bulk is ~every argument on the wire,
    # and these coercions sit on the per-frame replication hot path.
    # Plain bytes pass through: the native AOF scanner's raw mode hands
    # bulk-replay frames their arguments unwrapped (persist/oplog.py).
    if type(m) is Bulk or isinstance(m, (Simple, Err, Bulk)):
        return m.val
    if type(m) is bytes:
        return m
    if isinstance(m, Int):
        return i64_to_bytes(m.val)
    raise InvalidRequestMsg("should be non-array type")


def as_int(m: Msg) -> int:
    if type(m) is Int or isinstance(m, Int):
        return m.val
    if type(m) is bytes:
        v = bytes2i64(m)
        if v is None:
            raise InvalidRequestMsg("string should be an integer")
        return v
    if isinstance(m, (Simple, Bulk)):
        v = bytes2i64(m.val)
        if v is None:
            raise InvalidRequestMsg("string should be an integer")
        return v
    raise InvalidRequestMsg("argument should be Integer or String")


def as_uint(m: Msg) -> int:
    if isinstance(m, Int):
        if m.val < 0:
            raise InvalidRequestMsg("argument should be an unsigned integer")
        return m.val
    if type(m) is bytes:
        v = bytes2u64(m)
        if v is None:
            raise InvalidRequestMsg("string should be an unsigned integer")
        return v
    if isinstance(m, (Simple, Bulk)):
        v = bytes2u64(m.val)
        if v is None:
            raise InvalidRequestMsg("string should be an unsigned integer")
        return v
    raise InvalidRequestMsg("argument should be Integer or String")
