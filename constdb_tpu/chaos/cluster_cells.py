"""Cluster-mode chaos cells: slot migration under faults.

A cluster cell is a different animal from the replication matrix cells
(scenario.py): TWO nodes in TWO replication groups — deliberately no
repl link between them (that full-mesh stream is what cluster mode
removes) — splitting the 16384-slot keyspace, with a redirect-following
client driving writes and the migration channel dialed through the
fault plane's connector, so partitions hit it like any repl link.

Cells (wired into scenario.matrix_cells / smoke_cells via Cell.cluster):

  migrate-partition  a slot migration is killed mid-protocol by a full
                     partition (connections killed), the mesh keeps
                     serving, and the RETRIED migration must complete
                     and converge — a half-shipped slot never flips
  ownership-flap     a slot migrates A -> B -> A (two epoch bumps);
                     every write before/between/after must land exactly
                     once in the final owner's state
  no-resurrection    a key and a set member are deleted WHILE their
                     slots are mid-migration; the deletes must hold on
                     the new owner (the GC pin keeps the tombstones
                     alive across the handoff)

Oracle: each group's canonical export, filtered to its OWNED slots,
must equal the journal-replay reference exactly (scenario.py's
certified-MRDT argument, per slot group), and every node's per-slot
digest for its owned slots must match the reference's.  Failure
messages carry `[chaos cluster:<cell> seed=N]` — the replay handle.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..cluster import slot_of
from ..resp.message import Err, Msg
from .cluster import ChaosCluster, Client, NodeSpec
from .oracle import OpJournal
from .plane import FaultPlane

CLUSTER_CELLS = ("migrate-partition", "ownership-flap", "no-resurrection")


class RedirectClient:
    """Follows MOVED/ASK redirects, one live connection per address."""

    def __init__(self) -> None:
        self.conns: dict[str, Client] = {}
        self.redirects = 0

    async def _conn(self, addr: str) -> Client:
        c = self.conns.get(addr)
        if c is None:
            c = await Client().connect(addr)
            self.conns[addr] = c
        return c

    async def cmd(self, addr: str, *parts) -> Msg:
        for _hop in range(6):
            try:
                r = await (await self._conn(addr)).cmd(*parts)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                self.conns.pop(addr, None)
                raise
            if isinstance(r, Err) and \
                    r.val.startswith((b"MOVED ", b"ASK ")):
                addr = r.val.split()[2].decode()
                self.redirects += 1
                continue
            return r
        raise AssertionError(f"redirect loop at {addr}: {parts[:2]}")

    async def close(self) -> None:
        for c in self.conns.values():
            await c.close()
        self.conns.clear()


def _specs() -> list[NodeSpec]:
    """Two single-node groups splitting the slot space evenly."""
    return [NodeSpec(engine="cpu",
                     extra={"cluster": True, "slot_groups": 2,
                            "cluster_group": g})
            for g in range(2)]


async def _seed_addrs(cluster: ChaosCluster) -> None:
    """Each node learns the OTHER group's address (one MEET-style
    seeding per node; adopt() merges addresses from then on)."""
    for i, other in ((0, 1), (1, 0)):
        c = await Client().connect(cluster.apps[i].advertised_addr)
        try:
            await c.cmd("cluster", "setaddr", other,
                        cluster.apps[other].advertised_addr)
        finally:
            await c.close()


def _owned_keys(prefix: str, gid: int, n: int, *, suffix: bytes = b"",
                avoid: Optional[set] = None) -> list[bytes]:
    """`n` distinct keys whose FULL name (prefix+i+suffix) hashes to a
    slot the even 2-group split assigns to `gid` (group 0 owns slots
    [0, 8192)), skipping slots in `avoid`."""
    out, j = [], 0
    while len(out) < n:
        k = f"{prefix}{j}".encode() + suffix
        s = slot_of(k)
        if (s < 8192) == (gid == 0) and (avoid is None or s not in avoid):
            out.append(k)
            if avoid is not None:
                avoid.add(s)
        j += 1
    return out


async def _burst(rc: RedirectClient, cluster: ChaosCluster, keys, serial,
                 n: int) -> int:
    """`n` mixed writes over `keys`, all entered at node 0 (redirects
    find the owner); returns the advanced serial."""
    addr = cluster.apps[0].advertised_addr
    for i in range(n):
        k = keys[i % len(keys)]
        serial += 1
        if i % 3 == 0:
            r = await rc.cmd(addr, b"sadd", k + b":s",
                             b"m%d" % (serial % 16))
        elif i % 3 == 1:
            r = await rc.cmd(addr, b"hset", k + b":h",
                             b"f%d" % (serial % 4), b"v%d" % serial)
        else:
            r = await rc.cmd(addr, b"set", k, b"v%d" % serial)
        assert not isinstance(r, Err), (k, r)
    return serial


async def _migrate(cluster: ChaosCluster, src: int, slot: int,
                   target_addr: str, timeout: float = 10.0) -> bool:
    """Drive `CLUSTER MIGRATE` over the admin plane and wait for the
    flip (or the attempt's clean death).  True iff ownership flipped."""
    c = await Client().connect(cluster.apps[src].advertised_addr)
    try:
        r = await c.cmd("cluster", "migrate", slot, slot + 1, target_addr)
        assert not isinstance(r, Err), r
    finally:
        await c.close()
    cl = cluster.apps[src].node.cluster
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if not cl.owns(slot):
            return True
        if not cl.migrating and not cl._tasks:
            return not cl.owns(slot)  # attempt died cleanly
        await asyncio.sleep(0.02)
    return not cl.owns(slot)


async def _drain_gc(cluster: ChaosCluster, tag: str,
                    timeout: float = 10.0) -> None:
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        pending = 0
        for app in cluster.apps:
            app.node.gc()
            pending += len(app.node.ks.garbage)
        if not pending:
            return
        if loop.time() > deadline:
            raise AssertionError(
                f"{tag} {pending} tombstones never collected after the "
                f"migrations settled — a stale GC pin survived a handoff")
        await asyncio.sleep(0.05)


async def _certify(tag: str, cluster: ChaosCluster,
                   journal: OpJournal) -> dict:
    """The cluster oracle (module docstring): per-owned-slot canonical
    equality against the journal reference + per-slot digest agreement.
    One replay builds both the reference canonical and its digests."""
    from ..cluster.slots import SLOT_FANOUT, SLOT_LEAVES, bucket_of_slot
    from ..server.node import Node
    from ..store.digest import state_digest_matrix

    await _drain_gc(cluster, tag)
    ref = Node(node_id=(1 << 30) + 9, alias="cluster-oracle")
    for (origin, uuid), (name, args) in sorted(
            journal.ops.items(), key=lambda kv: (kv[0][1], kv[0][0])):
        if name in (b"meet", b"forget"):
            continue
        ref.apply_replicated(name, args, origin, uuid)
    for _ in range(64):
        ref.gc()
        if not ref.ks.garbage:
            break
    ref_canon = ref.canonical()

    tables = [a.node.cluster.table for a in cluster.apps]
    assert all(t.serialize() == tables[0].serialize() for t in tables), \
        f"{tag} slot tables diverged after the run: " \
        f"epochs {[t.epoch for t in tables]}"
    canons = [await cluster.canonical_of(i)
              for i in range(len(cluster.apps))]
    gids = [a.node.cluster.my_gid for a in cluster.apps]
    for key, ent in ref_canon.items():
        gid = tables[0].owner_of(slot_of(key))
        got = canons[gids.index(gid)].get(key)
        assert got == ent, \
            f"{tag} key {key!r} (slot {slot_of(key)}, group {gid}) " \
            f"diverges from the journal reference: {got} != {ent}"
    # no phantom state: an owner must not hold OWNED-slot keys the
    # reference lacks (a source's stale copy of a MOVED slot is fine —
    # it is no longer the owner — but invented owned state is a bug)
    for i, canon in enumerate(canons):
        for key in canon:
            if tables[0].owner_of(slot_of(key)) == gids[i]:
                assert key in ref_canon, \
                    f"{tag} node {i} holds owned key {key!r} the " \
                    f"journal reference does not"
    # per-slot digest agreement on owned slots, against the reference —
    # the same 64x256 geometry under which slot == digest bucket
    ref.ensure_flushed()
    ref_mat = state_digest_matrix(
        ref.ks, SLOT_FANOUT, SLOT_LEAVES).reshape(-1)
    for i, app in enumerate(cluster.apps):
        app.node.ensure_flushed()
        mat = state_digest_matrix(
            app.node.ks, SLOT_FANOUT, SLOT_LEAVES).reshape(-1)
        bad = [s for s in range(len(ref_mat))
               if tables[0].owner_of(s) == gids[i]
               and int(mat[bucket_of_slot(s)])
               != int(ref_mat[bucket_of_slot(s)])]
        assert not bad, \
            f"{tag} node {i} per-slot digest disagrees with the " \
            f"reference on owned slots {bad[:5]}" \
            + (f" (+{len(bad) - 5})" if len(bad) > 5 else "")
    return {"journal_ops": len(journal.ops), "ref_keys": len(ref_canon)}


async def _run_cell_async(name: str, seed: int, ops: int = 45) -> dict:
    import random
    import tempfile

    assert name in CLUSTER_CELLS, name
    rng = random.Random(seed ^ 0xC1A57E12)
    with tempfile.TemporaryDirectory(prefix="constdb-chaos-cl-") as work:
        plane = FaultPlane(seed)
        journal = OpJournal()
        cluster = ChaosCluster(work, seed, _specs(), plane=plane,
                               journal=journal)
        await cluster.start()
        rc = RedirectClient()
        tag = f"[chaos cluster:{name} seed={seed}]"
        try:
            await _seed_addrs(cluster)
            addr0 = cluster.apps[0].advertised_addr
            addr1 = cluster.apps[1].advertised_addr
            node0, node1 = cluster.apps[0].node, cluster.apps[1].node
            # background keys on both sides of the split, slot-disjoint
            # from the migration subjects so a cell's migrations move
            # exactly the keys it targets
            taken: set = set()
            subjects = _owned_keys("mig", 0, 2, avoid=taken)
            setkey = _owned_keys("mig", 0, 1, suffix=b":s", avoid=taken)[0]
            keys = _owned_keys("ck", 0, 6, avoid=taken) \
                + _owned_keys("ck", 1, 6, avoid=taken)
            serial = await _burst(rc, cluster, keys + subjects, 0, ops)

            if name == "migrate-partition":
                slot = slot_of(subjects[0])
                # slow the migration channel so the kill lands MID-
                # protocol, then cut the edge both ways
                plane.set_faults(0, 1, delay=(0.01, 0.05))
                flip = asyncio.create_task(
                    _migrate(cluster, 0, slot, addr1, timeout=6.0))
                await asyncio.sleep(0.03 + rng.random() * 0.05)
                plane.partition(0, 1, sym=True, kill=True)
                # the mesh keeps serving through the partition (clients
                # are not partitioned from either group — only the
                # inter-group migration channel is)
                serial = await _burst(rc, cluster, keys, serial, ops)
                first = await flip
                plane.heal()
                plane.clear_faults()
                if not first:
                    assert await _migrate(cluster, 0, slot, addr1), \
                        f"{tag} retried migration never completed"
                assert not node0.cluster.owns(slot) \
                    and node1.cluster.owns(slot), f"{tag} no flip"
                serial = await _burst(rc, cluster, keys + subjects,
                                      serial, ops)

            elif name == "ownership-flap":
                slot = slot_of(subjects[0])
                e0 = node0.cluster.epoch
                assert await _migrate(cluster, 0, slot, addr1), \
                    f"{tag} A->B migration failed"
                serial = await _burst(rc, cluster, keys + subjects,
                                      serial, ops)
                assert await _migrate(cluster, 1, slot, addr0), \
                    f"{tag} B->A migration failed"
                assert node0.cluster.owns(slot), \
                    f"{tag} flap did not return the slot to A"
                assert node0.cluster.epoch >= e0 + 2, \
                    f"{tag} flap bumped epoch {e0} -> " \
                    f"{node0.cluster.epoch}, want >= +2"
                serial = await _burst(rc, cluster, keys + subjects,
                                      serial, ops)

            else:  # no-resurrection
                dead = subjects[0]
                r = await rc.cmd(addr0, b"sadd", setkey,
                                 b"doomed", b"keeper")
                assert not isinstance(r, Err), r
                plane.set_faults(0, 1, delay=(0.005, 0.02))
                # delete the string WHILE its slot migrates (direct,
                # ASK-redirected, or just-flipped — all must hold; the
                # GC pin keeps the tombstone exportable)
                flip = asyncio.create_task(_migrate(
                    cluster, 0, slot_of(dead), addr1, timeout=8.0))
                await asyncio.sleep(0.01 + rng.random() * 0.03)
                r = await rc.cmd(addr0, b"del", dead)
                assert not isinstance(r, Err), (dead, r)
                assert await flip, \
                    f"{tag} string migration never completed"
                # and the set member while ITS slot migrates
                flip = asyncio.create_task(_migrate(
                    cluster, 0, slot_of(setkey), addr1, timeout=8.0))
                await asyncio.sleep(0.01 + rng.random() * 0.03)
                r = await rc.cmd(addr0, b"srem", setkey, b"doomed")
                assert not isinstance(r, Err), (setkey, r)
                assert await flip, f"{tag} set migration never completed"
                plane.clear_faults()
                serial = await _burst(rc, cluster, keys, serial, ops)
                canon = await cluster.canonical_of(1)
                ent = canon.get(dead)
                assert ent is None or ent[1] < ent[3], \
                    f"{tag} deleted key {dead!r} resurrected on the " \
                    f"new owner: {ent}"
                s = canon.get(setkey)
                assert s is not None, f"{tag} migrated set vanished"
                live = {m for m, _at, _an, dlt, _v in s[5] if dlt == 0}
                assert b"doomed" not in live and b"keeper" in live, \
                    f"{tag} removed member resurrected (or survivor " \
                    f"lost) across the move: {sorted(live)}"

            assert rc.redirects > 0, \
                f"{tag} the workload never exercised a redirect"
            stats = await _certify(tag, cluster, journal)
            stats["redirects"] = rc.redirects
            stats["epoch"] = node0.cluster.epoch
            stats["migrations"] = (node0.cluster.migrations_out
                                   + node1.cluster.migrations_out)
            stats["serial"] = serial
            return stats
        except AssertionError:
            raise
        except Exception as e:
            raise AssertionError(f"{tag} cell crashed: {e!r}") from e
        finally:
            await rc.close()
            await cluster.close()


def run_cluster_cell(name: str, seed: int, ops: int = 45) -> dict:
    """Sync entry (scenario.run_scenario dispatches here for cells with
    Cell.cluster set)."""
    return asyncio.run(_run_cell_async(name, seed, ops))


__all__ = ["CLUSTER_CELLS", "RedirectClient", "run_cluster_cell"]
