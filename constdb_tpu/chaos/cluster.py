"""Chaos-cluster lifecycle: nodes under the fault plane.

Grows the old tests/cluster_util.py + tests/test_chaos.py helpers into
first-class scenario primitives: per-cell node configs (engine,
capability knobs, serve shards), deterministic per-node HLC clocks with
scripted jitter, crash/restart (cold via the real snapshot/boot paths,
warm via a server rebuild over the surviving Node), and plane-aware
state reads (a shard-per-core node's canonical/digest come from its
workers).  Every node dials its peers through the plane's connector, so
the whole mesh's transport is fault-injectable.
"""

from __future__ import annotations

import asyncio
import os
import random
from dataclasses import dataclass, field
from typing import Optional

from ..persist.snapshot import NodeMeta, dump_keyspace, write_snapshot_file
from ..resp.codec import RespParser, encode_msg
from ..resp.message import Arr, Bulk, Msg
from ..server.io import ServerApp, start_node
from ..server.node import Node
from ..utils.hlc import now_ms
from .plane import FaultPlane

# fast-cadence server knobs for in-process meshes (the old
# cluster_util.FAST, plus the backoff bounds chaos runs need: retries
# must stay sub-second so a healed partition re-forms the mesh inside a
# convergence window, and the handshake must time out faster than a
# scenario step)
FAST = dict(heartbeat=0.15, reconnect_delay=0.2, reconnect_max=1.0,
            gc_interval=0.2, handshake_timeout=3.0)


class ChaosClock:
    """Deterministic per-node HLC wall source with scripted jitter.

    The fixed-clock hook from the serve-coalescer tests (Node(clock=…)),
    grown for chaos: each call advances a private millisecond counter by
    a small seeded step (so two nodes' clocks drift apart on their own),
    and `jump()` applies scripted skew — forward leaps and BACKWARD
    steps both, since HLC monotonicity under clock regression is exactly
    the property worth certifying.  Pure function of (seed, node, call
    count, jumps): replays exactly.
    """

    def __init__(self, seed: int, node_idx: int,
                 start_ms: Optional[int] = None) -> None:
        self._ms = now_ms() if start_ms is None else start_ms
        self._skew = 0
        self._rng = random.Random((seed << 8) ^ (node_idx * 2654435761))

    def __call__(self) -> int:
        self._ms += self._rng.choice((0, 1, 1, 2))
        return self._ms + self._skew

    def jump(self, delta_ms: int) -> None:
        self._skew += delta_ms


@dataclass
class NodeSpec:
    """One node's capability-cell configuration."""

    engine: str = "cpu"            # cpu | xla | xla-resident
    wire_batch: Optional[int] = None   # 1 = per-frame wire (cap withheld)
    delta_sync: Optional[bool] = None  # False = full snapshots only
    wire_compress: Optional[bool] = None  # False = plain streams/dumps
    #                                       (CAP_COMPRESS withheld)
    apply_batch: Optional[int] = None
    serve_batch: Optional[int] = None
    serve_shards: int = 1
    repl_log_cap: int = 1_024_000
    # durable op log (persist/oplog.py): the fsync policy name enables
    # AOF for this node ("always" | "everysec" | "no"); None = off.
    # The cluster pins each node's aof dir to its index so kill9/cold
    # restarts recover from the node's OWN log, no harness-side dump.
    aof: Optional[str] = None
    extra: dict = field(default_factory=dict)

    def build_engine(self):
        if self.engine == "cpu":
            return None  # Node defaults to CpuMergeEngine
        from ..engine.tpu import TpuMergeEngine
        if self.engine == "xla":
            return TpuMergeEngine(resident=True, steady=False)
        if self.engine == "xla-resident":
            return TpuMergeEngine(resident=True, steady=True, warmup=0)
        raise ValueError(f"unknown engine spec {self.engine!r}")

    def app_kwargs(self) -> dict:
        kw = dict(FAST)
        kw.update(self.extra)
        if self.wire_batch is not None:
            kw["wire_batch"] = self.wire_batch
        if self.delta_sync is not None:
            kw["delta_sync"] = self.delta_sync
        if self.wire_compress is not None:
            kw["wire_compress"] = self.wire_compress
        if self.apply_batch is not None:
            kw["apply_batch"] = self.apply_batch
        if self.serve_batch is not None:
            kw["serve_batch"] = self.serve_batch
        if self.serve_shards > 1:
            kw["serve_shards"] = self.serve_shards
        if self.aof is not None:
            kw["aof"] = True
            kw["aof_fsync"] = self.aof
        return kw


class Client:
    """Minimal RESP client (the reference's constdb-cli/test transport)."""

    def __init__(self) -> None:
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.parser = RespParser()

    async def connect(self, addr: str) -> "Client":
        host, port = addr.rsplit(":", 1)
        self.reader, self.writer = await asyncio.open_connection(host,
                                                                 int(port))
        return self

    async def cmd(self, *parts) -> Msg:
        items = [Bulk(p if isinstance(p, bytes) else str(p).encode())
                 for p in parts]
        self.writer.write(encode_msg(Arr(items)))
        await self.writer.drain()
        while True:
            msg = self.parser.next_msg()
            if msg is not None:
                return msg
            data = await asyncio.wait_for(self.reader.read(1 << 16), 10.0)
            if not data:
                raise ConnectionError("EOF")
            self.parser.feed(data)

    async def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except (ConnectionError, OSError):
                pass


class ChaosCluster:
    """N nodes wired through one FaultPlane (see module docstring)."""

    def __init__(self, work_dir: str, seed: int, specs: list[NodeSpec],
                 plane: Optional[FaultPlane] = None,
                 journal=None) -> None:
        self.work_dir = str(work_dir)
        self.seed = seed
        self.specs = specs
        self.plane = plane if plane is not None else FaultPlane(seed)
        self.journal = journal
        self.apps: list[Optional[ServerApp]] = [None] * len(specs)
        self.clocks = [ChaosClock(seed, i) for i in range(len(specs))]
        self._ports: dict[int, int] = {}  # listen port -> node index
        # bumped per restart: the oracle monitor keys watermark baselines
        # by (node, incarnation) — a cold restart legally rewinds them
        self.incarnations = [0] * len(specs)
        # fault-accounting counters banked from nodes a cold restart
        # discarded (NodeStats dies with the process; the oracle's
        # accounting laws cover the whole run)
        self.retired_stats: dict[str, int] = {}

    def stat_total(self, name: str) -> int:
        """Sum of a NodeStats counter (or stats.extra key) over every
        live node PLUS everything banked from cold-restarted ones."""
        total = self.retired_stats.get(name, 0)
        for app in self.apps:
            if app is None:
                continue
            st = app.node.stats
            total += getattr(st, name, 0) or st.extra.get(name, 0)
        return total

    def _bank_stats(self, node: Node) -> None:
        st = node.stats
        for name in ("repl_wire_demotions", "repl_reconnects",
                     "repl_full_syncs", "repl_delta_syncs"):
            self.retired_stats[name] = \
                self.retired_stats.get(name, 0) + getattr(st, name)
        for name in ("fullsync_reset_refused", "repl_delta_demotions"):
            self.retired_stats[name] = \
                self.retired_stats.get(name, 0) + st.extra.get(name, 0)

    # ----------------------------------------------------------- lifecycle

    def _resolve(self, port: int) -> Optional[int]:
        return self._ports.get(port)

    def _wire(self, i: int, app: ServerApp) -> None:
        """Install the plane connector + oracle hooks on a (re)started
        node."""
        app.peer_connector = self.plane.connector(i, self._resolve)
        self._ports[app.port] = i
        self.apps[i] = app
        if self.journal is not None:
            self.journal.hook_node(app.node)

    async def start_one(self, i: int, node: Optional[Node] = None,
                        snapshot_path: str = "") -> ServerApp:
        spec = self.specs[i]
        if node is None:
            node = Node(node_id=i + 1, alias=f"n{i + 1}",
                        engine=spec.build_engine(),
                        repl_log_cap=spec.repl_log_cap,
                        clock=self.clocks[i])
        port = self.apps[i].port if self.apps[i] is not None else 0
        kw = spec.app_kwargs()
        if spec.aof is not None:
            # stable per-node dir: a restarted node recovers from its
            # OWN durable log, the way a real process would
            kw["aof_dir"] = os.path.join(self.work_dir, f"aof.n{i}")
        app = await start_node(node, host="127.0.0.1", port=port,
                               work_dir=self.work_dir,
                               snapshot_path=snapshot_path,
                               **kw)
        self._wire(i, app)
        return app

    async def start(self) -> "ChaosCluster":
        for i in range(len(self.specs)):
            await self.start_one(i)
        return self

    async def meet_all(self) -> None:
        c = await Client().connect(self.apps[0].advertised_addr)
        try:
            for other in self.apps[1:]:
                await c.cmd("meet", other.advertised_addr)
        finally:
            await c.close()

    async def close(self) -> None:
        await self.plane.close()
        for app in self.apps:
            if app is not None:
                await app.close()
                eng = app.node.engine
                if hasattr(eng, "close"):
                    eng.close()

    # ------------------------------------------------------------- crashes

    async def restart_cold(self, i: int) -> ServerApp:
        """Crash + cold boot: dump state, kill the process state, build
        a FRESH Node restored from the snapshot on the same port — the
        real io.py boot-restore path (start_node), including the merged
        repl-log watermark fences.  The undo log, reconnect ladders, and
        every in-memory watermark die with the process, exactly as a
        real crash loses them.

        AOF variant: an AOF-enabled node takes NO harness-side dump —
        the clean shutdown group-commits its own log and recovery comes
        entirely from the node's own snapshot + oplog tail (the
        durability path under certification)."""
        app = self.apps[i]
        old = app.node
        if self.specs[i].aof is not None:
            await app.close()
            if hasattr(old.engine, "close"):
                old.engine.close()
            self._bank_stats(old)
            self.incarnations[i] += 1
            return await self.start_one(i)
        snap = os.path.join(self.work_dir, f"chaos.{old.node_id}.snapshot")
        # watermarks (meta + records) BEFORE the state export — the
        # consistency-cut rule every dump site follows (persist/
        # share.py): a record captured after the export claims pull
        # coverage the exported state lacks, and the boot restore's
        # watermark adoption then skips that window's redelivery
        # forever (this very harness found that ordering bug live)
        meta = NodeMeta(node_id=old.node_id, alias=old.alias,
                        repl_last_uuid=old.repl_log.landed_last_uuid
                        if hasattr(old.repl_log, "landed_last_uuid")
                        else old.repl_log.last_uuid)
        records = old.replicas.records()
        if old.serve_plane is not None:
            captures = await old.serve_plane.export_batches()
            write_snapshot_file(snap, meta, records, captures)
        else:
            old.ensure_flushed()
            dump_keyspace(snap, old.ks, meta, records)
        await app.close()
        if hasattr(old.engine, "close"):
            old.engine.close()
        self._bank_stats(old)
        self.incarnations[i] += 1
        return await self.start_one(i, snapshot_path=snap)

    async def kill9(self, i: int, torn: bool = False,
                    rng: Optional[random.Random] = None) -> ServerApp:
        """`kill -9` (+ optional power loss) and cold restart from the
        node's OWN durable op log — no harness-side dump, no graceful
        group commit:

          * process death: bytes the OpLog had buffered in memory die
            with it; bytes already written survive in the page cache
            (exactly a SIGKILL's semantics) — the op log is frozen
            AS-IS before the teardown's close path could flush it.
          * `torn=True` additionally models power loss: each segment is
            truncated at a SEEDED offset inside its un-fsynced suffix —
            possibly mid-record, the torn-tail case recovery must
            repair loudly.

        After recovery the journal obligation is pruned of the node's
        never-durable ops: by the emit-only-durable law they were never
        advertised to any peer, so they cease to exist mesh-wide
        (oracle.prune_origin); fsync-acknowledged writes are below the
        durable fence and MUST therefore still converge byte-identically
        — the zero-acked-loss certification."""
        app = self.apps[i]
        old = app.node
        lg = old.oplog
        assert lg is not None, "kill9 targets AOF-enabled nodes"
        paths = [lg.seg_path(lg.dir, lg.generation, s)
                 for s in range(lg.n_segments)]
        # freeze the log exactly as the dying process leaves it: close()
        # must NOT run its final drain + group commit — and a real
        # SIGKILL stops EVERYTHING at that same instant, so no in-flight
        # serve chunk may land, ack, or journal after the freeze (a
        # graceful close would keep quiescing worker chunks whose
        # mirror the frozen log silently drops: journaled-but-never-
        # logged ops that no fence can account for).  Connections and
        # the worker pool die first, then the teardown runs.
        lg._closed = True
        for t in list(app._conn_tasks):
            t.cancel()
        if app.serve_plane is not None:
            await app.serve_plane.close()
        await app.close()
        # the durable point is read AFTER close: an in-flight group
        # commit can SETTLE during the teardown awaits (releasing the
        # emission floor — a stopping push loop may legally emit those
        # just-durable ops), so a snapshot taken before close could
        # mark emitted-and-durable bytes as torn-able and the
        # truncation would forge exactly the emitted-but-lost
        # divergence the emit-only-durable law forbids (found by the
        # everysec cell flaking under load)
        synced = list(lg.synced_sizes)
        if hasattr(old.engine, "close"):
            old.engine.close()
        self._bank_stats(old)
        if torn:
            r = rng if rng is not None else \
                random.Random((self.seed << 4) ^ (0x70A9 + i))
            for s, path in enumerate(paths):
                if not os.path.exists(path):
                    continue
                size = os.path.getsize(path)
                lo = min(synced[s] if s < len(synced) else size, size)
                if size > lo:
                    cut = r.randrange(lo, size)
                    with open(path, "r+b") as f:
                        f.truncate(cut)
        self.incarnations[i] += 1
        app2 = await self.start_one(i)
        if self.journal is not None:
            fence = app2.node.stats.extra.get("aof_recovered_fence", 0)
            pruned = self.journal.prune_origin(app2.node.node_id, fence)
            if pruned:
                self.retired_stats["journal_pruned"] = \
                    self.retired_stats.get("journal_pruned", 0) + pruned
        return app2

    async def checkpoint_crash(self, i: int, stage: str) -> ServerApp:
        """Crash INSIDE an incremental checkpoint: arm the op log's
        injected fault at `stage` ("switch" = new generation opened,
        "snapshot" = base snapshot written, "meta" = meta committed but
        old generations not yet deleted), drive a rewrite into it, then
        kill -9 and cold-restart from whatever interleaving the fault
        left on disk.  Every stage must replay idempotently: the
        surviving generations re-merge to the same state (the
        checkpoint-cut consistency law — the oracle certifies
        convergence right after)."""
        app = self.apps[i]
        lg = app.node.oplog
        assert lg is not None, "checkpoint_crash targets AOF nodes"
        lg._ckpt_fault = stage
        await lg.rewrite(app)  # raises inside; caught + flagged dirty
        assert lg._ckpt_fault == "", \
            f"checkpoint fault {stage!r} did not fire"
        return await self.kill9(i)

    async def restart_warm(self, i: int) -> ServerApp:
        """Process hiccup: the Node object (state, undo log, repl_log)
        survives, every connection does not."""
        app = self.apps[i]
        node = app.node
        port = app.port
        await app.close()
        self.incarnations[i] += 1
        kw = self.specs[i].app_kwargs()
        if self.specs[i].aof is not None:
            kw["aof_dir"] = os.path.join(self.work_dir, f"aof.n{i}")
        app2 = ServerApp(node, host="127.0.0.1", port=port,
                         work_dir=self.work_dir, **kw)
        if self.specs[i].aof is not None:
            # the Node (and its state) survives a warm restart, but the
            # old app's close() closed its op log — re-open it, no
            # replay needed (persist/oplog.py rearm)
            from ..persist.oplog import rearm
            rearm(app2)
        await app2.start()
        self._wire(i, app2)
        return app2

    def clock_jump(self, i: int, delta_ms: int) -> None:
        self.clocks[i].jump(delta_ms)

    # ---------------------------------------------------------- state reads

    async def canonical_of(self, i: int) -> dict:
        app = self.apps[i]
        plane = app.node.serve_plane
        if plane is not None:
            return await plane.canonical()
        return app.node.canonical()

    async def digest_of(self, i: int, fanout: int = 16,
                        leaves: int = 4):
        app = self.apps[i]
        plane = app.node.serve_plane
        if plane is not None:
            return await plane.state_digest(fanout, leaves)
        from ..store.digest import state_digest_matrix
        app.node.ensure_flushed()
        return state_digest_matrix(app.node.ks, fanout, leaves)

    async def converge(self, timeout: float = 30.0,
                       poll: float = 0.1) -> dict:
        """Poll until every node's canonical CRDT state is identical;
        returns the converged canonical.  On timeout, the differing keys
        are named — with the cluster seed, that is the whole repro."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            canons = [await self.canonical_of(i)
                      for i in range(len(self.apps))]
            if all(c == canons[0] for c in canons[1:]):
                return canons[0]
            if loop.time() > deadline:
                diff = set()
                for c in canons[1:]:
                    for k in set(c) | set(canons[0]):
                        if c.get(k) != canons[0].get(k):
                            diff.add(k)
                raise AssertionError(
                    f"[chaos seed={self.seed}] no convergence after "
                    f"{timeout}s; {len(diff)} keys differ, e.g. "
                    f"{sorted(diff)[:5]}")
            await asyncio.sleep(poll)

    async def full_mesh(self, timeout: float = 20.0) -> None:
        """Wait until every node has a connected link to every other."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        want = {a.advertised_addr for a in self.apps}
        while True:
            ok = True
            for app in self.apps:
                peers = {m.addr for m in app.node.replicas.live_peers()
                         if m.link is not None and m.link.connected}
                if want - {app.advertised_addr} - peers:
                    ok = False
                    break
            if ok:
                return
            if loop.time() > deadline:
                raise AssertionError(
                    f"[chaos seed={self.seed}] mesh did not fully connect")
            await asyncio.sleep(0.05)
