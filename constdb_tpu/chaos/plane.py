"""Deterministic fault plane over the inter-node transport.

Every replica connection in the mesh is DIALED by some node's link
(an inbound SYNC adopts the stream the dialer created — server/io.py),
so wrapping the dial seam (`ServerApp.peer_connector`) puts both
directions of every inter-node byte through this plane.  The wrapped
stream is split into protocol UNITS — one RESP frame each, with a
FULLSYNC/DELTASYNC header fused to its whole raw payload window so a
reorder can never tear a raw byte range apart — and each directed edge
(src_node -> dst_node) applies its current fault rules per unit:

  * blocked      — partition: new dials on the edge are refused, and
                   traffic hitting a blocked direction drops WITH its
                   connection (transport fate-sharing — see _schedule)
  * delay        — deliver after a seeded pause (FIFO preserved)
  * reorder      — swap adjacent deliverable units with probability p
  * duplicate    — deliver the unit twice (dup-skip discipline food)
  * truncate     — one-shot: deliver a PREFIX of the next unit, then
                   hard-kill the connection (mid-frame cut)
  * corrupt_wire — one-shot: flip a byte inside the next REPLBATCH
                   payload (the codec's crc must demote LOUDLY, never
                   apply garbage)

Handshake `sync` frames and raw-window units are exempt from reorder/
duplication (reordering a handshake is not a network behavior TCP can
produce — within one connection TCP only delays, dies, or delivers in
order; the frame-level faults model what the MESH can produce across
teardown/redial/overlap races, plus the adversarial dup/reorder the
CRDT layer claims to absorb).  Every decision is drawn from a per-edge
`random.Random` seeded from the plane seed, so a scenario's fault
schedule is a pure function of (seed, traffic shape) and failures
replay from the printed seed.

The plane also counts what it actually injected (`stats`) — the oracle
checks INFO demotion/refusal/reconnect gauges against these.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional

from ..errors import CstError
from ..resp.codec import encode_msg, make_parser
from ..resp.message import Arr, Bulk, as_bytes, as_int

_RAW_KINDS = (b"fullsync", b"deltasync")
# never reordered/duplicated/corrupted: connection setup and raw windows
_EXEMPT_KINDS = (b"sync",)
# additionally never REORDERED (duplication stays fair game): a REPLACK
# drained-beacon delivered AHEAD of the stream frame it followed would
# fast-forward the receiver's watermark over an undelivered op — a fault
# TCP cannot produce (in-order-or-die within a connection), and one the
# beacon's soundness argument explicitly assumes away
# (docs/INVARIANTS.md "Transport assumptions").  Swapping two stream
# frames IS modeled: the gap check detects it and the link pays a
# teardown + resync, which is the recovery being certified.
_ORDERED_KINDS = (b"replack",)


class _Unit:
    """One schedulable transport unit (see module docstring)."""

    __slots__ = ("kind", "payload", "msg", "atomic")

    def __init__(self, kind: Optional[bytes], payload: bytes,
                 msg=None, atomic: bool = False):
        self.kind = kind
        self.payload = payload
        self.msg = msg
        self.atomic = atomic

    @property
    def exempt(self) -> bool:
        return self.atomic or self.kind in _EXEMPT_KINDS

    @property
    def reorderable(self) -> bool:
        return not self.exempt and self.kind not in _ORDERED_KINDS


class _Splitter:
    """Byte stream -> units.  Frames re-encode byte-identically (every
    wire frame is produced by encode_msg, which this reuses); a raw
    payload window is buffered until complete and fused to its header."""

    def __init__(self) -> None:
        self._parser = make_parser()
        self._raw_need = 0
        self._raw_head = b""
        self._raw_kind = b""
        self._raw_buf = bytearray()

    def feed(self, data: bytes) -> list[_Unit]:
        self._parser.feed(data)
        units: list[_Unit] = []
        while True:
            if self._raw_need:
                got = self._parser.take_raw(self._raw_need)
                if not got:
                    break
                self._raw_buf += got
                self._raw_need -= len(got)
                if self._raw_need:
                    break
                units.append(_Unit(self._raw_kind,
                                   self._raw_head + bytes(self._raw_buf),
                                   atomic=True))
                self._raw_head = b""
                self._raw_buf = bytearray()
                continue
            msg = self._parser.next_msg()
            if msg is None:
                break
            payload = encode_msg(msg)
            kind = None
            items = msg.items if isinstance(msg, Arr) else None
            if items and isinstance(items[0], Bulk):
                kind = items[0].val.lower()
            if kind in _RAW_KINDS and len(items) > 1:
                size = as_int(items[1])
                if size > 0:
                    self._raw_need = size
                    self._raw_head = payload
                    self._raw_kind = kind
                    continue
            units.append(_Unit(kind, payload, msg))
        return units


class EdgeRules:
    """Mutable fault configuration of one directed edge."""

    __slots__ = ("blocked", "delay", "reorder", "dup",
                 "truncate_next", "corrupt_next", "stall")

    def __init__(self) -> None:
        self.blocked = False
        self.delay: Optional[tuple[float, float]] = None
        self.reorder = 0.0
        self.dup = 0.0
        self.truncate_next = False
        self.corrupt_next = False
        # stalled reader: delivery on this direction PARKS (the pumps
        # stop moving bytes — crucially the inbound pump stops READING
        # the real socket, so TCP backpressure reaches the sender) until
        # unstalled.  Transport-sound: a peer that stops draining its
        # receive buffer is exactly this, and TCP neither drops nor
        # reorders while it lasts — the resource fault the replication
        # window (CONSTDB_REPL_WINDOW) exists to govern.
        self.stall = False

    def clear(self) -> None:
        self.delay = None
        self.reorder = 0.0
        self.dup = 0.0
        self.stall = False


class _Edge:
    __slots__ = ("rules", "rng")

    def __init__(self, seed: int, src: int, dst: int) -> None:
        self.rules = EdgeRules()
        # a per-edge stream so one edge's traffic volume cannot shift
        # another edge's decision sequence
        self.rng = random.Random((seed << 16) ^ (src << 8) ^ dst)


class FaultPlane:
    def __init__(self, seed: int) -> None:
        self.seed = seed
        self._edges: dict[tuple[int, int], _Edge] = {}
        self._conns: list[_ChaosConn] = []
        self.stats: dict[str, int] = {}

    # ------------------------------------------------------------- controls

    def edge(self, src: int, dst: int) -> _Edge:
        e = self._edges.get((src, dst))
        if e is None:
            e = self._edges[(src, dst)] = _Edge(self.seed, src, dst)
        return e

    def count(self, what: str, n: int = 1) -> None:
        self.stats[what] = self.stats.get(what, 0) + n

    def set_faults(self, a: int, b: int, delay=None, reorder: float = 0.0,
                   dup: float = 0.0, sym: bool = True) -> None:
        for src, dst in ((a, b), (b, a)) if sym else ((a, b),):
            r = self.edge(src, dst).rules
            r.delay = delay
            r.reorder = reorder
            r.dup = dup

    def clear_faults(self) -> None:
        for e in self._edges.values():
            e.rules.clear()

    def partition(self, a: int, b: int, sym: bool = True,
                  kill: bool = True) -> None:
        """Stop delivery on a->b (and b->a when `sym`).  `kill` tears
        the edge's live connections down immediately; with kill=False
        they die lazily, on the first frame that hits the blocked
        direction (see _schedule — either way a partitioned connection
        DIES rather than silently dropping, preserving the transport's
        fate-sharing contract).  New dials on the edge are refused
        until `heal`."""
        self.count("partitions")
        for src, dst in ((a, b), (b, a)) if sym else ((a, b),):
            self.edge(src, dst).rules.blocked = True
        if kill:
            self.kill_connections(a, b)

    def heal(self, a: Optional[int] = None, b: Optional[int] = None) -> None:
        for (src, dst), e in self._edges.items():
            if a is None or (src in (a, b) and dst in (a, b)):
                e.rules.blocked = False

    def kill_connections(self, a: Optional[int] = None,
                         b: Optional[int] = None) -> int:
        """Hard-kill live connections on the (a, b) edge — or all of
        them (None).  Mid-stream: whatever was in flight is gone."""
        n = 0
        for c in list(self._conns):
            if c.closed:
                continue
            if a is None or (c.src in (a, b) and c.dst in (a, b)):
                c.kill()
                n += 1
        if n:
            self.count("conn_kills", n)
        return n

    def stall(self, src: int, dst: int) -> None:
        """Stalled reader on src->dst: delivery parks (and the inbound
        pump stops reading the carrying socket, so the sender feels real
        TCP backpressure) until `unstall`.  The connection stays ALIVE —
        this is the stalled-but-connected peer the replication window
        governs, not a partition."""
        self.count("stalls")
        self.edge(src, dst).rules.stall = True

    def unstall(self, src: int, dst: int) -> None:
        self.edge(src, dst).rules.stall = False

    def truncate_next(self, src: int, dst: int) -> None:
        """One-shot mid-frame cut on src->dst: the next unit delivers a
        prefix, then the connection dies."""
        self.edge(src, dst).rules.truncate_next = True

    def corrupt_next_wire(self, src: int, dst: int) -> None:
        """One-shot byte flip inside the next REPLBATCH payload on
        src->dst (crc-guarded demotion food)."""
        self.edge(src, dst).rules.corrupt_next = True

    def live_connections(self, a: int, b: int) -> int:
        return sum(1 for c in self._conns
                   if not c.closed and c.src in (a, b) and c.dst in (a, b))

    async def close(self) -> None:
        for c in list(self._conns):
            c.kill()
        self._conns.clear()

    # ------------------------------------------------------------ connector

    def connector(self, src: int, resolve):
        """The `ServerApp.peer_connector` for node `src`.  `resolve` maps
        a dialed port to the destination node index (the cluster's port
        registry); unknown ports dial straight through (a peer outside
        the harness)."""
        async def dial(host: str, port: int):
            dst = resolve(port)
            if dst is None:
                return await asyncio.open_connection(host, port)
            if self.edge(src, dst).rules.blocked or \
                    self.edge(dst, src).rules.blocked:
                self.count("dials_refused")
                raise ConnectionRefusedError(
                    f"chaos: edge {src}<->{dst} partitioned")
            reader, writer = await asyncio.open_connection(host, port)
            conn = _ChaosConn(self, src, dst, reader, writer)
            self._conns.append(conn)
            self._conns = [c for c in self._conns if not c.closed]
            return conn.reader, conn.writer
        return dial


# ---------------------------------------------------------------- transport


class _ChaosReader:
    """StreamReader stand-in fed by the inbound pump."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._eof = False
        self._wake = asyncio.Event()

    def _feed(self, data: bytes) -> None:
        self._buf += data
        self._wake.set()

    def _feed_eof(self) -> None:
        self._eof = True
        self._wake.set()

    async def read(self, n: int) -> bytes:
        while not self._buf and not self._eof:
            self._wake.clear()
            await self._wake.wait()
        if self._buf:
            out = bytes(self._buf[:n])
            del self._buf[:n]
            return out
        return b""


class _ChaosWriter:
    """StreamWriter stand-in: write() hands bytes to the outbound pump
    synchronously (fault decisions happen in write order — the
    deterministic part); delivery happens on the pump task."""

    def __init__(self, conn: "_ChaosConn") -> None:
        self._conn = conn

    def write(self, data: bytes) -> None:
        self._conn.feed_out(bytes(data))

    async def drain(self) -> None:
        if self._conn.closed:
            raise ConnectionResetError("chaos connection killed")
        await self._conn.real_writer.drain()

    def close(self) -> None:
        self._conn.close_out()

    def is_closing(self) -> bool:
        return self._conn.closed

    async def wait_closed(self) -> None:
        try:
            await self._conn.real_writer.wait_closed()
        except (ConnectionError, OSError):
            pass


class _ChaosConn:
    """One dialed inter-node connection under the plane: two directed
    pumps (src->dst rides the wrapped writer, dst->src rides a task
    reading the real socket), each splitting its byte stream into units
    and applying its edge's fault rules."""

    def __init__(self, plane: FaultPlane, src: int, dst: int,
                 reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter) -> None:
        self.plane = plane
        self.src = src
        self.dst = dst
        self.real_reader = reader
        self.real_writer = writer
        self.closed = False
        self.reader = _ChaosReader()
        self.writer = _ChaosWriter(self)
        self._out_split = _Splitter()
        self._in_split = _Splitter()
        self._outq: asyncio.Queue = asyncio.Queue()
        self._out_task = asyncio.create_task(self._out_pump())
        self._in_task = asyncio.create_task(self._in_pump())

    # -------------------------------------------------------------- faults

    def _schedule(self, direction: tuple[int, int],
                  units: list[_Unit]) -> list:
        """Apply the edge's rules to a batch of units, in order.
        Returns delivery ops: ("data", bytes, delay) / ("kill",)."""
        plane = self.plane
        edge = plane.edge(*direction)
        r = edge.rules
        rng = edge.rng
        ops: list = []
        deliver: list[_Unit] = []
        # the corrupt one-shot and reorder are mutually exclusive on an
        # edge while the one-shot is ARMED or firing: a reorder-induced
        # gap teardown — in this batch or one still in the delivery
        # pipeline — kills the connection before the corrupted REPLBATCH
        # is decoded, silently swallowing the injection and spuriously
        # failing the oracle's demotions==corruptions accounting law.
        # Reorder is exercised plentifully whenever no corruption is
        # pending (the certify schedule runs its reorder window first).
        reorder_ok = not r.corrupt_next
        for u in units:
            if r.blocked:
                # transport-sound partition: traffic on a blocked
                # direction is dropped AND kills the carrying connection
                # (the retransmit-timeout analog).  TCP can delay, die,
                # or deliver in order — it can NEVER silently drop a
                # frame and then deliver later ones on the same
                # connection; modeling that would "refute" the REPLACK
                # drained-beacon, whose soundness argument assumes
                # connection fate-sharing (docs/INVARIANTS.md).
                plane.count("frames_dropped")
                ops.append(("kill",))
                return ops
            if r.truncate_next:
                r.truncate_next = False
                plane.count("truncations")
                cut = max(1, len(u.payload) // 2)
                ops.append(("data", u.payload[:cut], 0.0))
                ops.append(("kill",))
                # everything after the cut is gone with the connection
                return ops
            if r.corrupt_next and u.kind == b"replbatch" and u.msg is not None:
                r.corrupt_next = False
                plane.count("wire_corruptions")
                u = _corrupt_replbatch(u)
            if not u.exempt and r.dup and rng.random() < r.dup:
                plane.count("frames_duplicated")
                deliver.append(u)
            deliver.append(u)
        if r.reorder and reorder_ok and len(deliver) > 1:
            i = 0
            while i + 1 < len(deliver):
                if deliver[i].reorderable and deliver[i + 1].reorderable \
                        and _swappable(deliver[i], deliver[i + 1]) \
                        and rng.random() < r.reorder:
                    deliver[i], deliver[i + 1] = deliver[i + 1], deliver[i]
                    self.plane.count("frames_reordered")
                    i += 2
                else:
                    i += 1
        for u in deliver:
            delay = rng.uniform(*r.delay) if r.delay else 0.0
            if delay:
                plane.count("frames_delayed")
            ops.append(("data", u.payload, delay))
        return ops

    # --------------------------------------------------------------- pumps

    def feed_out(self, data: bytes) -> None:
        if self.closed:
            return
        for op in self._schedule((self.src, self.dst),
                                 self._out_split.feed(data)):
            self._outq.put_nowait(op)

    def close_out(self) -> None:
        if not self.closed:
            self._outq.put_nowait(("eof",))

    async def _stall_gate(self, direction: tuple[int, int]) -> None:
        """Park while the direction's stalled-reader fault is armed
        (EdgeRules.stall) — polling, no rng draws, so the plane's
        seeded decision streams are untouched."""
        rules = self.plane.edge(*direction).rules
        while rules.stall and not self.closed:
            await asyncio.sleep(0.02)

    async def _out_pump(self) -> None:
        try:
            while True:
                op = await self._outq.get()
                if op[0] == "kill":
                    self.kill()
                    return
                if op[0] == "eof":
                    self.real_writer.close()
                    return
                _, data, delay = op
                if delay:
                    await asyncio.sleep(delay)
                await self._stall_gate((self.src, self.dst))
                self.real_writer.write(data)
                await self.real_writer.drain()
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass

    async def _in_pump(self) -> None:
        try:
            while True:
                # the stall gate sits BEFORE the socket read: a stalled
                # reader stops draining its receive buffer, so the
                # sender's kernel/userspace buffers fill and its
                # replication window (not a timeout) is what reacts
                await self._stall_gate((self.dst, self.src))
                data = await self.real_reader.read(1 << 16)
                if not data:
                    break
                for op in self._schedule((self.dst, self.src),
                                         self._in_split.feed(data)):
                    if op[0] == "kill":
                        self.kill()
                        return
                    _, payload, delay = op
                    if delay:
                        await asyncio.sleep(delay)
                    self.reader._feed(payload)
        except (ConnectionError, OSError):
            pass
        except asyncio.CancelledError:
            return
        self.reader._feed_eof()

    def kill(self) -> None:
        """Hard-kill: both endpoints see the connection die NOW."""
        if self.closed:
            return
        self.closed = True
        tr = self.real_writer.transport
        if tr is not None:
            tr.abort()
        self.reader._feed_eof()
        for t in (self._out_task, self._in_task):
            if t is not None and not t.done() and \
                    t is not asyncio.current_task():
                t.cancel()


_STREAM_KINDS = (b"replicate", b"replbatch")


def _swappable(a: _Unit, b: _Unit) -> bool:
    """May units a, b swap without forging an UNDETECTABLE skip?

    The fault model injects only faults the protocol claims to detect
    and recover from.  Two stream frames whose prev chain LINKS them
    (b.prev == a.uuid) are swappable: the receiver's gap check fires on
    the out-of-order frame and the link pays a teardown + resync — the
    recovery being certified.  Two stream frames that are NOT chained
    (adjacent frames from different segments of a sharded pusher's
    merged log) carry no continuity contract a receiver could check —
    swapping them forges a silent dup-skip of the later frame, a fault
    no in-order-or-die transport can produce (docs/INVARIANTS.md
    "Transport assumptions"; found live by this harness: a sharded
    cell's certify run lost exactly one cross-segment frame).  Frames
    outside the replication stream (digest negotiation, partsync) have
    no ordering contract and swap freely."""
    if a.kind not in _STREAM_KINDS or b.kind not in _STREAM_KINDS:
        return True
    if a.msg is None or b.msg is None:
        return False
    try:
        return as_int(b.msg.items[2]) == as_int(a.msg.items[3])
    except (CstError, IndexError):
        return False


def _corrupt_replbatch(u: _Unit) -> _Unit:
    """Flip one byte in the middle of a REPLBATCH payload (items[5]) and
    re-encode — structurally valid RESP, semantically corrupt payload."""
    items = list(u.msg.items)
    payload = bytearray(as_bytes(items[5]))
    if not payload:
        return u
    payload[len(payload) // 2] ^= 0xFF
    items[5] = Bulk(bytes(payload))
    msg = Arr(items)
    return _Unit(u.kind, encode_msg(msg), msg)
