"""Resource-fault certification: overload governance under chaos.

The transport cells (scenario.py) certify convergence under *delivery*
faults; these cells certify the overload-governance layer (server/
overload.py, server/io.py outbuf cap, replica/link.py repl window) under
*resource* faults — and, critically, that its degradation preserves
convergence.  Three scripted scenarios, each a pure function of its
seed:

  firehose        a memory-capped node under a pipelined write firehose
                  sheds client data writes with EXACT `-OOM …` error
                  replies — never partially applied, logged, or
                  replicated — while deletes and reads stay admitted,
                  REPLICATION INTAKE keeps landing the peer's stream,
                  the accounting gauges track the injected pressure, and
                  the whole mesh still converges byte-identically to the
                  CPU-engine reference over the non-shed delivered set
                  (the shed-at-the-edge soundness law,
                  docs/INVARIANTS.md "Degradation laws").
  stalled_client  a client that stops reading is disconnected LOUDLY at
                  CONSTDB_CLIENT_OUTBUF_MAX (counted in
                  client_outbuf_disconnects) without perturbing other
                  connections' reply streams — connection-fatal, never
                  state-corrupting.
  stalled_peer    a stalled-but-connected replica trips the
                  CONSTDB_REPL_WINDOW pause (repl_window_pauses), the
                  ring evicts past the paused cursor, and recovery rides
                  the already-certified resync path (delta or full) to
                  byte-identical convergence once the peer drains.

`run_resource_scenario(seed)` runs all three and returns their stats;
any failure names `[chaos-resource seed=N]` — the replay handle.
scripts/ci.sh runs seed 7 as its overload smoke stage.
"""

from __future__ import annotations

import asyncio
import tempfile

from ..resp.codec import encode_msg, make_parser
from ..resp.message import Arr, Bulk, Err, Int
from ..server.overload import OOM_ERR
from .cluster import ChaosCluster, Client, NodeSpec
from .oracle import InvariantMonitor, OpJournal, certify_state
from .plane import FaultPlane


async def _pipeline(addr: str, frames: list[bytes],
                    chunk: int = 256) -> list:
    """Pipelined request/response driver: send `frames` in chunks of
    `chunk`, read every reply, return the reply list in order."""
    c = await Client().connect(addr)
    replies: list = []
    try:
        for lo in range(0, len(frames), chunk):
            part = frames[lo:lo + chunk]
            c.writer.write(b"".join(part))
            await c.writer.drain()
            got = 0
            while got < len(part):
                msg = c.parser.next_msg()
                if msg is not None:
                    replies.append(msg)
                    got += 1
                    continue
                data = await asyncio.wait_for(c.reader.read(1 << 16), 10.0)
                if not data:
                    raise ConnectionError("EOF mid-pipeline")
                c.parser.feed(data)
    finally:
        await c.close()
    return replies


def _set_frames(prefix: bytes, n: int, val_len: int,
                spread: int = 64) -> list[bytes]:
    return [encode_msg(Arr([Bulk(b"set"),
                            Bulk(b"%s%d" % (prefix, i % spread)),
                            Bulk(b"v%07d" % i + b"x" * val_len)]))
            for i in range(n)]


# ------------------------------------------------------------- firehose


async def _firehose(seed: int, work: str) -> dict:
    cap = 256_000  # bytes; the workload's footprint is several x this
    specs = [NodeSpec(engine="cpu",
                      extra={"maxmemory": cap, "maxmemory_soft_pct": 75.0}),
             NodeSpec(engine="cpu")]
    plane = FaultPlane(seed)
    journal = OpJournal()
    cluster = ChaosCluster(work, seed, specs, plane=plane, journal=journal)
    await cluster.start()
    monitor = InvariantMonitor(cluster, journal).start()
    tag = f"[chaos-resource seed={seed}] firehose:"
    try:
        await cluster.meet_all()
        await cluster.full_mesh()
        capped = cluster.apps[0]
        gov = capped.node.governor
        gov.check_every = 1  # exact watermark edges for the oracle
        addr0 = capped.advertised_addr
        addr1 = cluster.apps[1].advertised_addr

        # below the watermark everything lands
        pre = await _pipeline(addr0, _set_frames(b"pre:", 64, 64))
        assert not any(isinstance(r, Err) for r in pre), \
            f"{tag} writes shed below the soft watermark"

        # the firehose: enough SET bytes to blow far past the cap
        replies = await _pipeline(addr0, _set_frames(b"fh:", 4096, 512))
        oks = sum(1 for r in replies if not isinstance(r, Err))
        oom = [r for r in replies if isinstance(r, Err)]
        assert oom, f"{tag} cap {cap} never shed a single write"
        assert oks, f"{tag} every write shed — soft watermark at zero?"
        for r in oom:
            assert r.val == OOM_ERR, \
                f"{tag} shed reply is not the exact OOM error: {r.val!r}"
        used = gov.used_memory()
        assert used >= gov.soft_bytes, \
            f"{tag} shedding with used_memory {used} below soft " \
            f"{gov.soft_bytes}"

        # exempt traffic stays admitted while saturated
        probes = await _pipeline(addr0, [
            encode_msg(Arr([Bulk(b"set"), Bulk(b"fh:0"), Bulk(b"nope")])),
            encode_msg(Arr([Bulk(b"get"), Bulk(b"fh:0")])),
            encode_msg(Arr([Bulk(b"del"), Bulk(b"pre:0")])),
            encode_msg(Arr([Bulk(b"info"), Bulk(b"memory")])),
        ])
        assert isinstance(probes[0], Err) and probes[0].val == OOM_ERR, \
            f"{tag} saturated node admitted a data write"
        assert not isinstance(probes[1], Err), f"{tag} read shed"
        assert probes[2] == Int(1), \
            f"{tag} DEL shed under OOM (it frees memory): {probes[2]}"
        assert not isinstance(probes[3], Err), f"{tag} admin shed"
        info = bytes(probes[3].val)
        assert b"overload_state:" in info and b"used_memory:" in info, \
            f"{tag} INFO memory section lost its overload gauges"
        assert b"overload_state:ok" not in info, \
            f"{tag} INFO reports state ok while the node sheds"

        # accounting law: every shed produced exactly one error reply
        shed_stat = capped.node.stats.oom_shed_writes
        observed = len(oom) + 1  # + the probe SET above
        assert shed_stat == observed, \
            f"{tag} oom_shed_writes={shed_stat} but clients observed " \
            f"{observed} OOM replies"

        # replication intake is NEVER shed: the peer's writes must land
        # on the saturated node (convergence is the proof)
        peer = await _pipeline(addr1, _set_frames(b"peer:", 256, 256))
        assert not any(isinstance(r, Err) for r in peer), \
            f"{tag} uncapped peer shed writes"
        ref = await certify_state(cluster, journal, timeout=30.0)
        for i in range(64):
            key = b"peer:%d" % i
            assert key in ref, f"{tag} reference lost peer key {key!r}"
        monitor.check()
        return {"shed": shed_stat, "landed": oks,
                "used_memory": used, "maxmemory": cap,
                "hard_reclaims": capped.node.stats.oom_hard_reclaims,
                "canonical_keys": len(ref)}
    finally:
        monitor.stop()
        await cluster.close()


# ------------------------------------------------------- stalled client


async def _stalled_client(seed: int, work: str) -> dict:
    cap = 1 << 16
    specs = [NodeSpec(engine="cpu", extra={"client_outbuf_max": cap})]
    cluster = ChaosCluster(work, seed, specs, plane=FaultPlane(seed))
    await cluster.start()
    tag = f"[chaos-resource seed={seed}] stalled_client:"
    try:
        app = cluster.apps[0]
        addr = app.advertised_addr
        # seed a value big enough that a pipelined GET burst dwarfs the
        # cap (32KB x 64 replies = 2MB >> 64KB)
        seeded = await _pipeline(addr, [encode_msg(Arr(
            [Bulk(b"set"), Bulk(b"big"), Bulk(b"x" * (32 << 10))]))])
        assert not isinstance(seeded[0], Err), f"{tag} seed write failed"

        stalled = await Client().connect(addr)
        try:
            # 1024 x 32KB = 32MB of replies: far past anything loopback
            # kernel buffers can absorb, so the transport's un-drained
            # buffer must cross the 64KB cap
            burst = b"".join(encode_msg(Arr([Bulk(b"get"), Bulk(b"big")]))
                             for _ in range(1024))
            stalled.writer.write(burst)
            await stalled.writer.drain()
            # ... and never read.  The server must cut the connection at
            # the cap; reading now must hit EOF/reset, not data forever.
            deadline = asyncio.get_running_loop().time() + 10.0
            while app.node.stats.client_outbuf_disconnects == 0:
                assert asyncio.get_running_loop().time() < deadline, \
                    f"{tag} server never disconnected the stalled reader"
                await asyncio.sleep(0.02)
        finally:
            await stalled.close()
        assert app.node.stats.client_outbuf_disconnects == 1, \
            f"{tag} disconnect miscounted: " \
            f"{app.node.stats.client_outbuf_disconnects}"

        # other connections' reply streams are untouched
        fine = await _pipeline(addr, _set_frames(b"ok:", 128, 32) + [
            encode_msg(Arr([Bulk(b"get"), Bulk(b"ok:1")]))])
        assert not any(isinstance(r, Err) for r in fine), \
            f"{tag} a healthy connection caught errors"
        return {"outbuf_disconnects":
                app.node.stats.client_outbuf_disconnects}
    finally:
        await cluster.close()


# --------------------------------------------------------- stalled peer


async def _stalled_peer(seed: int, work: str) -> dict:
    specs = [NodeSpec(engine="cpu", repl_log_cap=24_000,
                      extra={"repl_window": 2048}),
             NodeSpec(engine="cpu")]
    plane = FaultPlane(seed)
    journal = OpJournal()
    cluster = ChaosCluster(work, seed, specs, plane=plane, journal=journal)
    await cluster.start()
    monitor = InvariantMonitor(cluster, journal).start()
    tag = f"[chaos-resource seed={seed}] stalled_peer:"
    try:
        await cluster.meet_all()
        await cluster.full_mesh()
        addr0 = cluster.apps[0].advertised_addr
        node0 = cluster.apps[0].node
        # the peer stops reading node 0's stream — connection stays up
        plane.stall(0, 1)
        replies = await _pipeline(addr0, _set_frames(b"st:", 1200, 64))
        assert not any(isinstance(r, Err) for r in replies), \
            f"{tag} writes failed on the pushing node"
        deadline = asyncio.get_running_loop().time() + 15.0
        while node0.stats.repl_window_pauses == 0:
            assert asyncio.get_running_loop().time() < deadline, \
                f"{tag} repl window never paused " \
                f"(CONSTDB_REPL_WINDOW=2048, ~90KB backlogged)"
            await asyncio.sleep(0.05)
        # the paused cursor must fall off the byte-capped ring, so the
        # recovery below exercises the certified resync path
        deadline = asyncio.get_running_loop().time() + 15.0
        while node0.repl_log.evicted_up_to == 0:
            assert asyncio.get_running_loop().time() < deadline, \
                f"{tag} ring never evicted under the paused drain"
            await asyncio.sleep(0.05)
        resyncs0 = (node0.stats.repl_full_syncs
                    + node0.stats.repl_delta_syncs)
        plane.unstall(0, 1)
        ref = await certify_state(cluster, journal, timeout=45.0)
        resyncs = (node0.stats.repl_full_syncs
                   + node0.stats.repl_delta_syncs)
        assert resyncs > resyncs0, \
            f"{tag} eviction past the paused cursor recovered without " \
            f"a delta/full resync ({resyncs0} -> {resyncs})"
        monitor.check()
        return {"window_pauses": node0.stats.repl_window_pauses,
                "resyncs": resyncs, "canonical_keys": len(ref)}
    finally:
        monitor.stop()
        await cluster.close()


# ---------------------------------------------------------------- runner


async def _run_all(seed: int) -> dict:
    out: dict = {}
    for name, fn in (("firehose", _firehose),
                     ("stalled_client", _stalled_client),
                     ("stalled_peer", _stalled_peer)):
        with tempfile.TemporaryDirectory(
                prefix=f"constdb-chaos-res-{name}-") as work:
            out[name] = await fn(seed, work)
    return out


def run_resource_scenario(seed: int) -> dict:
    """Run the three resource-fault certification scenarios (module
    doc); returns per-scenario stats.  Failures carry
    `[chaos-resource seed=N]` — the replay handle."""
    try:
        return asyncio.run(_run_all(seed))
    except AssertionError:
        raise
    except Exception as e:
        raise AssertionError(
            f"[chaos-resource seed={seed}] scenario crashed: {e!r}") from e


__all__ = ["run_resource_scenario"]
