"""CLI: run chaos certification scenarios.

  python -m constdb_tpu.chaos                    # smoke cells, seed 7
  python -m constdb_tpu.chaos --all              # full capability matrix
  python -m constdb_tpu.chaos --seed 42 --cells wire1-delta1-shards1-cpu
  python -m constdb_tpu.chaos --soak --seed 99   # randomized soak

Every line prints the replay seed; a failing cell's AssertionError
carries `[chaos seed=N cell=…]` — rerun with that seed to replay the
exact schedule.  scripts/ci.sh runs the smoke set as its chaos stage.
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m constdb_tpu.chaos",
        description="convergence-under-chaos certification harness")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--cells", default="",
                    help="comma-separated cell names (see --list)")
    ap.add_argument("--all", action="store_true",
                    help="run the full capability matrix")
    ap.add_argument("--soak", action="store_true",
                    help="randomized soak on the default cell")
    ap.add_argument("--resource", action="store_true",
                    help="resource-fault cells: memory-capped firehose, "
                         "stalled client, stalled peer (chaos/resource.py)")
    ap.add_argument("--ops", type=int, default=30,
                    help="ops per scripted burst")
    ap.add_argument("--list", action="store_true",
                    help="list matrix cell names and exit")
    ns = ap.parse_args(argv)

    from .scenario import (certify_scenario, matrix_cells, run_scenario,
                           smoke_cells, soak_scenario)

    if ns.list:
        for c in matrix_cells():
            print(c.name)
        return 0

    if ns.resource:
        from .resource import run_resource_scenario
        print(f"chaos resource cells: seed={ns.seed}")
        t0 = time.monotonic()
        stats = run_resource_scenario(ns.seed)
        print(f"chaos resource cells PASSED in "
              f"{time.monotonic() - t0:.1f}s: {stats}")
        return 0

    if ns.soak:
        sc = soak_scenario(ns.seed)
        print(f"chaos soak: seed={ns.seed} steps={len(sc.steps)}")
        t0 = time.monotonic()
        stats = run_scenario(sc)
        print(f"chaos soak PASSED in {time.monotonic() - t0:.1f}s: "
              f"{stats}")
        return 0

    if ns.all:
        cells = matrix_cells()
    elif ns.cells:
        by_name = {c.name: c for c in matrix_cells()}
        try:
            cells = [by_name[n] for n in ns.cells.split(",")]
        except KeyError as e:
            print(f"unknown cell {e.args[0]!r}; --list shows the matrix",
                  file=sys.stderr)
            return 2
    else:
        cells = smoke_cells()

    failed = 0
    for cell in cells:
        sc = certify_scenario(ns.seed, cell, ops=ns.ops)
        t0 = time.monotonic()
        try:
            stats = run_scenario(sc)
        except AssertionError as e:
            failed += 1
            print(f"FAIL {cell.name} seed={ns.seed}: {e}")
            continue
        print(f"PASS {cell.name} seed={ns.seed} "
              f"({time.monotonic() - t0:.1f}s): "
              f"ops={stats.get('journal_ops')} "
              f"reconnects={stats.get('reconnects')} "
              f"plane={stats.get('plane')}")
    if failed:
        print(f"{failed}/{len(cells)} cells FAILED (replay: --seed "
              f"{ns.seed} --cells <name>)", file=sys.stderr)
        return 1
    print(f"chaos certification: {len(cells)}/{len(cells)} cells green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
