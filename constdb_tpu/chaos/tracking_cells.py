"""Tracking chaos cells: the near-cache invalidation laws under faults.

A tracking cell drives a REAL NearCacheClient (client/near_cache.py)
against a fault-injected mesh: a hot-key storm fills the near-cache
while writers mutate the same keys — locally, through the peer's
replication stream, across partitions, over a killed tracked
connection, and across a cluster slot migration.  The oracle is the
zero-stale law: once the mesh quiesces, EVERY entry the near-cache
would serve must equal the serving node's own answer — a stale cached
read is a failure, not a race.

Cells (wired into scenario.matrix_cells / smoke_cells via
Cell.tracking):

  track-repl-writes   every storm write enters at the PEER: the tracked
                      node's invalidations come exclusively from the
                      replication intake seam
  track-partition     the repl link is cut (connections killed)
                      mid-storm while the peer keeps writing; the heal
                      resync must invalidate everything it lands
  track-conn-kill     the tracked connection is killed server-side
                      while an invalidation push sits in the coalescing
                      window — the frame is LOST; the reconnect-flush
                      law must restore correctness
  track-slot-migration  a slot holding tracked keys migrates away; the
                      adopt-time slots_lost hook must invalidate them
                      (writes now land on the new owner — the one-shot
                      promise could never otherwise be kept)

Failure messages carry `[chaos tracking:<cell> seed=N]` — the replay
handle, like every chaos cell.
"""

from __future__ import annotations

import asyncio

from ..client import NearCacheClient
from ..cluster import slot_of
from ..resp.message import Bulk, Err
from .cluster import ChaosCluster, Client, NodeSpec
from .plane import FaultPlane

TRACKING_CELLS = ("track-repl-writes", "track-partition",
                  "track-conn-kill", "track-slot-migration")

_HOT = [b"hot%d" % i for i in range(8)]


async def _storm(rng, nc: NearCacheClient, writers: list, n: int,
                 serial: int, write_pct: float = 0.1,
                 keys: list = _HOT) -> int:
    """A 90:10 hot-key storm: the tracked client reads hot keys
    (filling its near-cache); writes go through `writers` (plain
    untracked clients — the peers whose mutations owe pushes)."""
    for _ in range(n):
        k = keys[rng.randrange(len(keys))]
        if rng.random() < write_pct:
            serial += 1
            w = writers[rng.randrange(len(writers))]
            r = await w.cmd(b"set", k, b"v%d" % serial)
            assert not isinstance(r, Err), (k, r)
        else:
            r = await nc.get(k)
            assert not isinstance(r, Err), (k, r)
    return serial


async def _quiesce(cluster: ChaosCluster, timeout: float = 20.0) -> None:
    """Replication convergence + a beat for the push coalescing windows
    and the client reader task to drain."""
    await cluster.converge(timeout=timeout)
    await asyncio.sleep(0.1)


async def _assert_zero_stale(tag: str, nc: NearCacheClient,
                             direct: Client, timeout: float = 5.0) -> None:
    """The oracle: every entry the near-cache holds equals the serving
    node's own current answer.  Bounded polling absorbs in-flight push
    frames; entries still stale at the deadline are the failure."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while True:
        stale = []
        for k, cached in list(nc.cache.items()):
            truth = await direct.cmd(b"get", k)
            if truth != cached:
                stale.append((k, cached, truth))
        if not stale:
            break
        if loop.time() > deadline:
            raise AssertionError(
                f"{tag} near-cache would serve stale entries after "
                f"quiescence: {stale[:3]}"
                + (f" (+{len(stale) - 3})" if len(stale) > 3 else ""))
        await asyncio.sleep(0.05)
    # and the read path agrees end to end (hits and misses alike)
    for k in _HOT:
        got = await nc.get(k)
        truth = await direct.cmd(b"get", k)
        assert got == truth, \
            f"{tag} tracked read of {k!r} diverges: {got} != {truth}"


async def _repl_pair(work: str, seed: int, plane) -> ChaosCluster:
    cluster = ChaosCluster(work, seed, [NodeSpec(), NodeSpec()],
                           plane=plane)
    await cluster.start()
    await cluster.meet_all()
    await cluster.converge(timeout=20.0)
    return cluster


async def _run_repl_cell(name: str, seed: int, ops: int, rng) -> dict:
    import tempfile

    tag = f"[chaos tracking:{name} seed={seed}]"
    with tempfile.TemporaryDirectory(prefix="constdb-chaos-trk-") as work:
        plane = FaultPlane(seed)
        cluster = await _repl_pair(work, seed, plane)
        node0 = cluster.apps[0].node
        nc = await NearCacheClient(
            cluster.apps[0].advertised_addr).connect()
        local = await Client().connect(cluster.apps[0].advertised_addr)
        peer = await Client().connect(cluster.apps[1].advertised_addr)
        try:
            if name == "track-repl-writes":
                # writes ONLY through the peer: every invalidation at
                # node 0 is born at the replication intake seam
                serial = await _storm(rng, nc, [peer], ops * 4, 0,
                                      write_pct=0.2)
                await _quiesce(cluster)
                await _assert_zero_stale(tag, nc, local)
                assert nc.invalidations > 0, \
                    f"{tag} no push ever invalidated a replicated write"

            elif name == "track-partition":
                serial = await _storm(rng, nc, [local, peer], ops * 2, 0)
                plane.partition(0, 1, sym=True, kill=True)
                # the peer keeps writing into the partition; the
                # tracked client keeps reading node 0's (consistent,
                # merely old) state — near-cache vs node 0 stays exact
                serial = await _storm(rng, nc, [peer], ops * 2, serial,
                                      write_pct=0.3)
                await _assert_zero_stale(tag, nc, local)
                plane.heal()
                # the heal resync lands the peer's writes; the intake
                # taps must invalidate every affected tracked key
                serial = await _storm(rng, nc, [local, peer], ops,
                                      serial)
                await _quiesce(cluster)
                await _assert_zero_stale(tag, nc, local)
                assert nc.invalidations > 0, tag

            else:  # track-conn-kill
                serial = await _storm(rng, nc, [local, peer], ops * 2, 0)
                # park an invalidation in the coalescing window, then
                # kill the tracked connection server-side BEFORE the
                # window flushes: the push frame is lost with the
                # socket
                reg = node0.tracking
                reg.latency_s = 0.5
                victim = _HOT[rng.randrange(len(_HOT))]
                assert await nc.get(victim) is not None
                r = await local.cmd(b"set", victim, b"lost-push")
                assert not isinstance(r, Err), r
                killed = 0
                for conn in list(cluster.apps[0].client_conns.values()):
                    if conn.tracking:
                        conn.writer.transport.abort()
                        killed += 1
                assert killed == 1, f"{tag} tracked conn not found"
                deadline = asyncio.get_running_loop().time() + 5.0
                while nc._connected:
                    assert asyncio.get_running_loop().time() < deadline, \
                        f"{tag} client never noticed the kill"
                    await asyncio.sleep(0.01)
                assert not nc.cache and nc.flushes >= 1, \
                    f"{tag} reconnect-flush law broken: cache survived " \
                    f"the disconnect"
                reg.latency_s = 0.002
                await nc.connect()
                got = await nc.get(victim)
                assert got == Bulk(b"lost-push"), \
                    f"{tag} read after reconnect returned {got}, not " \
                    f"the write whose push was lost"
                serial = await _storm(rng, nc, [local, peer], ops,
                                      serial)
                await _quiesce(cluster)
                await _assert_zero_stale(tag, nc, local)

            stats = {"serial": serial, "nc_hits": nc.hits,
                     "nc_misses": nc.misses,
                     "nc_invalidations": nc.invalidations,
                     "nc_flushes": nc.flushes,
                     "pushes": node0.stats.tracking_pushes,
                     "invalidations_sent":
                         node0.stats.tracking_invalidations_sent}
            assert nc.hits > 0, f"{tag} the storm never hit the near-cache"
            assert node0.stats.tracking_demotions == 0, \
                f"{tag} unexpected outbuf demotion"
            return stats
        except AssertionError:
            raise
        except Exception as e:
            raise AssertionError(f"{tag} cell crashed: {e!r}") from e
        finally:
            await nc.close()
            await local.close()
            await peer.close()
            await cluster.close()


async def _run_migration_cell(seed: int, ops: int, rng) -> dict:
    import tempfile

    from .cluster_cells import (RedirectClient, _migrate, _owned_keys,
                                _seed_addrs, _specs)

    tag = f"[chaos tracking:track-slot-migration seed={seed}]"
    with tempfile.TemporaryDirectory(prefix="constdb-chaos-trk-") as work:
        plane = FaultPlane(seed)
        cluster = ChaosCluster(work, seed, _specs(), plane=plane)
        await cluster.start()
        rc = RedirectClient()
        nc = None
        try:
            await _seed_addrs(cluster)
            addr0 = cluster.apps[0].advertised_addr
            addr1 = cluster.apps[1].advertised_addr
            node0 = cluster.apps[0].node
            nc = await NearCacheClient(addr0).connect()
            # tracked keys owned by group 0; `moving` migrates away,
            # `staying` shares its fate only if its slot moved too (it
            # must NOT — the hook is per-slot, not flush-all)
            taken: set = set()
            moving = _owned_keys("trkmig", 0, 1, avoid=taken)[0]
            staying = _owned_keys("trkstay", 0, 1, avoid=taken)[0]
            # storm keys: group-0-owned, slot-disjoint from the subjects
            # (an unowned or just-moved key would answer MOVED)
            hot = _owned_keys("trkhot", 0, 6, avoid=taken)
            for k in hot:
                r = await rc.cmd(addr0, b"set", k, b"hv")
                assert not isinstance(r, Err), (k, r)
            for k in (moving, staying):
                r = await rc.cmd(addr0, b"set", k, b"before")
                assert not isinstance(r, Err), (k, r)
            assert await nc.get(moving) == Bulk(b"before")
            assert await nc.get(staying) == Bulk(b"before")
            drops0 = nc.invalidations + nc.flushes
            # storm on unrelated keys while the slot migrates away.
            # Two server-side paths may drop the moved entry — the
            # CLUSTER MIGRATE admin command's CTRL flush-all, and the
            # adopt-time slots_lost per-key push (pinned in isolation
            # by tests/test_tracking.py) — the LAW is that one of them
            # always does before a stale serve is possible
            mig = asyncio.create_task(
                _migrate(cluster, 0, slot_of(moving), addr1))
            serial = await _storm(rng, nc, [], ops, 0, write_pct=0.0,
                                  keys=hot)
            assert await mig, f"{tag} migration never completed"
            deadline = asyncio.get_running_loop().time() + 5.0
            while moving in nc.cache:
                assert asyncio.get_running_loop().time() < deadline, \
                    f"{tag} tracked key survived the slot handoff"
                await asyncio.sleep(0.01)
            assert nc.invalidations + nc.flushes > drops0, tag
            # the new owner takes a write this node will NEVER see — a
            # surviving near-cache entry would be permanently stale
            r = await rc.cmd(addr0, b"set", moving, b"after")
            assert not isinstance(r, Err), r
            got = await nc.get(moving)
            assert isinstance(got, Err) and got.val.startswith(b"MOVED"), \
                f"{tag} tracked read of the migrated key returned " \
                f"{got!r} instead of a MOVED redirect"
            # the new owner serves the key (which value wins is LWW
            # under the chaos clocks' skew — not this cell's law)
            r = await rc.cmd(addr0, b"get", moving)
            assert isinstance(r, Bulk), \
                f"{tag} migrated key unreadable on the new owner: {r!r}"
            assert await nc.get(staying) == Bulk(b"before")
            return {"serial": serial, "nc_hits": nc.hits,
                    "nc_invalidations": nc.invalidations,
                    "redirects": rc.redirects,
                    "epoch": node0.cluster.epoch}
        except AssertionError:
            raise
        except Exception as e:
            raise AssertionError(f"{tag} cell crashed: {e!r}") from e
        finally:
            if nc is not None:
                await nc.close()
            await rc.close()
            await cluster.close()


async def _run_cell_async(name: str, seed: int, ops: int = 30) -> dict:
    import random

    assert name in TRACKING_CELLS, name
    rng = random.Random(seed ^ 0x7AC4EDB5)
    if name == "track-slot-migration":
        return await _run_migration_cell(seed, ops, rng)
    return await _run_repl_cell(name, seed, ops, rng)


def run_tracking_cell(name: str, seed: int, ops: int = 30) -> dict:
    """Sync entry (scenario.run_scenario dispatches here for cells with
    Cell.tracking set)."""
    return asyncio.run(_run_cell_async(name, seed, ops))


__all__ = ["TRACKING_CELLS", "run_tracking_cell"]
