"""Scenario DSL + the certification schedules.

A `Scenario` is (seed, capability cell, step list).  Steps are plain
data — `("ops", n)`, `("partition", a, b, sym)`, `("crash", i, style)`,
`("clock_jump", i, ms)`, … — so a schedule prints, diffs, and replays;
every random choice (op mix, targets, fault decisions, backoff jitter)
derives from the seed, so a failing run's printed seed IS its repro.

`certify_scenario` is the acceptance schedule the ISSUE names: one
scripted run combining partitions (full and asymmetric), frame
reorder/duplication/delay, a mid-frame truncation kill, connection
kills, cold+warm process crashes, clock jitter (forward and backward),
a targeted REPLBATCH corruption, and one mixed-version peer — ending in
the full invariant oracle (convergence to the CPU reference, digest
agreement, watermark monotonicity, no-resurrection, GC drain, fault
accounting).  `matrix_cells` enumerates the capability sweep it must
pass on: wire batch x delta sync x serve shards x resident engine.

`soak_scenario` generates a randomized schedule from its seed for the
slow soak; any failure reports `[chaos seed=N]` and
`run_scenario(soak_scenario(N))` replays that exact schedule.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Optional

from ..resp.message import Arr, Int
from .cluster import ChaosCluster, Client, NodeSpec
from .oracle import (InvariantMonitor, OpJournal, certify_state,
                     check_fault_accounting)
from .plane import FaultPlane


@dataclass
class Cell:
    """One capability-matrix cell: which negotiated fast paths are ON
    for the non-legacy nodes."""

    wire: bool = True       # REPLBATCH columnar wire (CAP_BATCH_STREAM)
    delta: bool = True      # digest-driven delta resync (CAP_DELTA_SYNC)
    compress: bool = True   # negotiated wire/bulk compression
    #                         (CAP_COMPRESS — round 17)
    shards: int = 1         # serve workers per node (1 = single loop)
    engine: str = "cpu"     # cpu | xla | xla-resident
    aof: Optional[str] = None  # durable op log fsync policy (round 18):
    #                            "always" | "everysec" | "no"; None =
    #                            off.  AOF cells grow kill9_mid_write +
    #                            torn_write steps — cold restarts that
    #                            recover from the node's OWN log.
    ckpt: bool = False      # crash-mid-checkpoint steps (round 20):
    #                         fault-inject each rewrite interleaving
    #                         (generation switch / snapshot / meta
    #                         commit), kill -9, certify the replay
    cluster: str = ""       # cluster-mode cell name (round 21): when
    #                         set, the cell runs a hash-slot migration
    #                         scenario (cluster_cells.CLUSTER_CELLS)
    #                         instead of the replication matrix — two
    #                         slot groups, no inter-group repl links,
    #                         the other knobs above do not apply
    tracking: str = ""      # tracking cell name (round 22): when set,
    #                         the cell drives a real NearCacheClient
    #                         through a fault-injected storm
    #                         (tracking_cells.TRACKING_CELLS) and
    #                         certifies the zero-stale law instead of
    #                         running the replication matrix

    @property
    def name(self) -> str:
        return (f"wire{int(self.wire)}-delta{int(self.delta)}"
                f"-comp{int(self.compress)}"
                f"-shards{self.shards}-{self.engine}"
                + (f"-aof-{self.aof}" if self.aof else "")
                + ("-ckpt" if self.ckpt else "")
                + (f"-cluster-{self.cluster}" if self.cluster else "")
                + (f"-{self.tracking}" if self.tracking else ""))

    def specs(self, n: int = 3, mixed_idx: Optional[int] = None
              ) -> list[NodeSpec]:
        """Node configs for this cell.  `mixed_idx` plays the
        mixed-version peer: wire batching, delta sync, and compression
        OFF, so its handshakes advertise none of the capabilities and
        every stream it touches must negotiate down correctly.
        Compression cells lower the payload floor so the scripted
        bursts' REPLBATCH frames actually compress — the corrupt
        one-shot then hits a COMPRESSED payload, certifying the
        compression-demotion law, not just the batch codec's."""
        out = []
        for i in range(n):
            if i == mixed_idx:
                out.append(NodeSpec(engine="cpu", wire_batch=1,
                                    delta_sync=False,
                                    wire_compress=False,
                                    aof=self.aof))
            else:
                out.append(NodeSpec(
                    engine=self.engine,
                    wire_batch=None if self.wire else 1,
                    delta_sync=None if self.delta else False,
                    wire_compress=None if self.compress else False,
                    serve_shards=self.shards,
                    aof=self.aof,
                    extra={"wire_compress_min": 64}
                    if self.compress else {}))
        return out


def matrix_cells() -> list[Cell]:
    """The full capability sweep.  Sharded cells collapse the wire
    dimension (a shard-per-core receiver never advertises
    CAP_BATCH_STREAM, and in an all-sharded mesh nobody does) and pin
    the worker engine (serve workers run the cpu spec); compression
    (round 17) defaults ON across the sweep — every wire cell's
    corrupt-REPLBATCH shot then hits a compressed payload — with
    dedicated compress-OFF cells on the cpu engine pinning the plain
    negotiation both with and without the batch wire."""
    cells = []
    for engine in ("cpu", "xla", "xla-resident"):
        for wire in (True, False):
            for delta in (True, False):
                cells.append(Cell(wire=wire, delta=delta, shards=1,
                                  engine=engine))
    cells.append(Cell(wire=True, delta=True, compress=False,
                      engine="cpu"))
    cells.append(Cell(wire=False, delta=False, compress=False,
                      engine="cpu"))
    for delta in (True, False):
        cells.append(Cell(wire=False, delta=delta, shards=2,
                          engine="cpu"))
    # durability cells (round 18): every AOF cell adds kill9_mid_write
    # + torn_write cold restarts recovering from the node's own log.
    # `always` carries the zero-acked-loss law; `everysec` certifies
    # the weaker contract (durable-prefix recovery + re-convergence);
    # one sharded cell drives the per-shard segment merge.
    cells.append(Cell(aof="always"))
    cells.append(Cell(aof="everysec"))
    cells.append(Cell(wire=False, delta=False, compress=False,
                      aof="always"))
    cells.append(Cell(wire=False, shards=2, aof="always"))
    # crash-mid-checkpoint (round 20): the incremental-checkpoint cut
    # must be idempotent at every fault interleaving
    cells.append(Cell(aof="always", ckpt=True))
    # cluster mode (round 21): slot migration under partition, the
    # ownership flap, and deletes landing mid-move (cluster_cells.py)
    from .cluster_cells import CLUSTER_CELLS
    cells.extend(Cell(cluster=c) for c in CLUSTER_CELLS)
    # client-assisted caching (round 22): the near-cache invalidation
    # laws under replication, partitions, connection kills, and slot
    # migration (tracking_cells.py)
    from .tracking_cells import TRACKING_CELLS
    cells.extend(Cell(tracking=t) for t in TRACKING_CELLS)
    return cells


def smoke_cells() -> list[Cell]:
    """One representative cell per negotiated fast path (the CI chaos
    smoke): everything-on (compression included — its corrupt shot hits
    a compressed REPLBATCH), everything-off (pure legacy paths, plain
    bytes end to end), the resident engine, and the sharded serving
    plane."""
    return [Cell(), Cell(wire=False, delta=False, compress=False),
            Cell(engine="xla-resident"), Cell(shards=2, wire=False),
            Cell(aof="always", ckpt=True), Cell(aof="everysec"),
            Cell(cluster="migrate-partition"),
            Cell(tracking="track-partition")]


@dataclass
class Scenario:
    seed: int
    cell: Cell = field(default_factory=Cell)
    steps: list = field(default_factory=list)
    n_nodes: int = 3
    mixed_idx: Optional[int] = 2   # which node plays the legacy peer
    ops_per_burst: int = 30
    converge_timeout: float = 45.0

    @property
    def name(self) -> str:
        return f"seed={self.seed} cell={self.cell.name}"


def certify_scenario(seed: int, cell: Optional[Cell] = None,
                     ops: int = 30) -> Scenario:
    """The acceptance schedule (see module docstring).  Node 2 is the
    mixed-version peer; faults target the 0<->1 edge (both fast-path
    nodes) and the mesh around node 2."""
    cell = cell if cell is not None else Cell()
    steps = [
        ("ops", ops),
        # frame-level chaos on the fast-path edge: delay + reorder + dup
        ("faults", 0, 1, dict(delay=(0.0005, 0.004), reorder=0.25,
                              dup=0.25)),
        ("ops", ops * 2),
        # cached reads racing the faulted replication stream: planned +
        # cached replies must match the per-command reference exactly
        ("cached_reads", 0),
        ("clear_faults",),
    ]
    if cell.wire and cell.shards == 1:
        # a corrupt REPLBATCH payload must demote LOUDLY.  Injected on a
        # CALM edge (after clear_faults) and VERIFIED with bounded
        # retries ("corrupt_burst"): a consumed one-shot can still be
        # legitimately discarded WITH a dying connection (transport
        # fate-sharing — e.g. the double-dial adopt overlap closes the
        # stream the corrupted frame was written to), in which case the
        # clean redelivery is correct behavior and no demotion exists to
        # count.  The law being certified is decode-fails-loudly
        # whenever a corrupt payload REACHES a live parser — so the
        # step re-arms and re-bursts until one does (the burst runs on
        # node 0 ONLY, so its serve path logs a consecutive encodable
        # run and the 0->1 push loop group-encodes a REPLBATCH for the
        # one-shot to hit; the certify step asserts a demotion really
        # landed).
        steps += [("corrupt_burst", 0, 1, 24), ("ops", ops // 2)]
    steps += [
        # no-resurrection probe setup: the member exists mesh-wide
        # BEFORE the partition...
        ("probe_setup",),
        ("partition", 0, 2, dict(sym=False, kill=False)),  # asymmetric
        ("ops", ops),
        ("heal",),
        # ...then node 2 is FULLY isolated (both edges, connections
        # killed), the member is retired on the majority side, and node
        # 2 keeps writing — after the heal the removal must win
        # everywhere and the member must never resurrect
        ("partition", 0, 2, dict(sym=True, kill=True)),
        ("partition", 1, 2, dict(sym=True, kill=True)),
        ("probe_retire",),
        ("ops", ops),
        ("heal",),
        # mid-stream violence on a live edge
        ("truncate", 0, 1),
        ("ops", ops // 2),
        ("kill_conns", 0, 1),
        ("ops", ops // 2),
        # process deaths: cold loses everything in memory, warm loses
        # only connections
        ("crash", 1, "cold"),
        ("ops", ops),
        ("crash", 0, "warm"),
        ("ops", ops // 2),
        # clock jitter: a leap ahead, writes, a step BACK, writes
        ("clock_jump", 2, 30_000),
        ("ops", ops // 2),
        ("clock_jump", 2, -20_000),
        ("ops", ops // 2),
        # the read plane again after crashes + clock jitter (node 1 was
        # cold-restarted above — its cache refilled from recovered state)
        ("cached_reads", 1),
    ]
    if cell.aof:
        # durability primitives (round 18): kill -9 mid-firehose and a
        # torn-tail power loss, each followed by a cold restart that
        # recovers from the node's OWN op log (no harness-side dump).
        # The oracle then certifies that every fsync-acknowledged write
        # survived and the mesh re-converged byte-identically — the
        # never-durable suffix is pruned from the journal obligation
        # under the emit-only-durable law (cluster.kill9).
        steps += [
            ("kill9_mid_write", 0),
            ("ops", ops),
            ("torn_write", 1),
            ("ops", ops),
        ]
        if cell.ckpt:
            # crash-mid-checkpoint (round 20): each fault interleaving
            # of the rewrite's commit sequence leaves a different disk
            # state (new gen open / base written / meta committed with
            # the old generations still on disk) — all must cold-replay
            # to the same bytes
            for stage in ("switch", "snapshot", "meta"):
                steps += [("ckpt_crash", 0, stage), ("ops", ops // 2)]
    steps += [("certify",)]
    return Scenario(seed=seed, cell=cell, steps=steps,
                    ops_per_burst=ops)


def soak_scenario(seed: int, rounds: int = 12, ops: int = 80) -> Scenario:
    """Randomized soak: `rounds` bursts with seeded fault events drawn
    between them, always ending in the full oracle.  The schedule is a
    pure function of `seed` — rebuild with the printed seed to replay."""
    rng = random.Random(seed ^ 0x5EEDFA17)
    steps: list = [("ops", ops)]
    partitioned = False
    for _ in range(rounds):
        roll = rng.random()
        if roll < 0.18 and not partitioned:
            a, b = rng.sample(range(3), 2)
            steps.append(("partition", a, b,
                          dict(sym=rng.random() < 0.7,
                               kill=rng.random() < 0.7)))
            partitioned = True
        elif roll < 0.30 and partitioned:
            steps.append(("heal",))
            partitioned = False
        elif roll < 0.45:
            a, b = rng.sample(range(3), 2)
            steps.append(("faults", a, b,
                          dict(delay=(0.0002, 0.003),
                               reorder=rng.choice((0.0, 0.2, 0.4)),
                               dup=rng.choice((0.0, 0.2, 0.4)))))
        elif roll < 0.55:
            steps.append(("clear_faults",))
        elif roll < 0.65:
            a, b = rng.sample(range(3), 2)
            steps.append(("kill_conns", a, b))
        elif roll < 0.72:
            a, b = rng.sample(range(3), 2)
            steps.append(("truncate", a, b))
        elif roll < 0.85:
            steps.append(("crash", rng.randrange(3),
                          rng.choice(("cold", "warm"))))
        else:
            steps.append(("clock_jump", rng.randrange(3),
                          rng.choice((-15_000, 10_000, 45_000))))
        steps.append(("ops", ops))
    if partitioned:
        steps.append(("heal",))
    steps += [("ops", ops), ("certify",)]
    return Scenario(seed=seed, steps=steps, ops_per_burst=ops,
                    converge_timeout=90.0)


# ---------------------------------------------------------------- workload


class _Workload:
    """Seeded op generator with the bookkeeping the oracle probes need.

    The mix sticks to rewrites that are pure pointwise merges (the
    journal-replay reference is then exact under ANY delivery order):
    counter steps + CNTUNDO, register set/del, set add/remove, hash set.
    Deleted register keys are per-node-exclusive and never rewritten, so
    "retired stays dead" is a mesh invariant, not a race."""

    def __init__(self, seed: int, n_nodes: int) -> None:
        self.rng = random.Random(seed ^ 0xC4A05)
        self.n = n_nodes
        self.serial = 0
        self.retired_regs: list[bytes] = []
        # per-node keys with at least one undoable local counter op
        self.undoable: list[dict[str, int]] = [dict()
                                               for _ in range(n_nodes)]

    def clear_undo(self, i: int) -> None:
        self.undoable[i].clear()  # a cold restart loses the undo log

    async def pipelined_writes(self, cluster: ChaosCluster, i: int,
                               n: int) -> None:
        """One pipelined chunk of `n` writes on node `i`: the serve
        coalescer logs them as one run, so the push loops drain a
        CONSECUTIVE encodable run — the shape REPLBATCH group-encoding
        (and the corrupt_wire one-shot) needs; a request-response burst
        trickles single entries that ship per-frame."""
        from ..resp.codec import encode_msg
        from ..resp.message import Arr, Bulk
        c = await Client().connect(cluster.apps[i].advertised_addr)
        try:
            buf = bytearray()
            for j in range(n):
                self.serial += 1
                buf += encode_msg(Arr([
                    Bulk(b"set"), Bulk(b"wire%d" % (j % 8)),
                    Bulk(b"v%d" % self.serial)]))
            c.writer.write(bytes(buf))
            await c.writer.drain()
            got = 0
            while got < n:  # all n replies = the whole chunk landed
                if c.parser.next_msg() is not None:
                    got += 1
                    continue
                data = await asyncio.wait_for(c.reader.read(1 << 16),
                                              10.0)
                if not data:
                    raise ConnectionError("EOF mid-pipeline")
                c.parser.feed(data)
        finally:
            await c.close()

    def cached_read_check(self, cluster: ChaosCluster, i: int) -> None:
        """The read-plane smoke under chaos: one coalesced read chunk
        (planned batch + versioned reply cache, server/serve.py) vs the
        per-command reference on the SAME node with no await between
        the passes — both observe identical state, so any byte
        difference is a stale cached serve, a FAILURE, not a race.
        Runs twice so the second pass actually hits entries the first
        one filled (entries surviving earlier replication intake are
        exactly what the invalidation laws must have dropped).  Sharded
        nodes skip (their data lives in the workers; the sharded read
        differential is pinned in tests/test_read_path.py)."""
        node = cluster.apps[i].node
        if node.serve_plane is not None:
            return
        from ..resp.codec import encode_into
        from ..resp.message import Arr, Bulk, NoReply
        from ..server.serve import ServeCoalescer
        msgs = [Arr([Bulk(b"get"), Bulk(b"wire%d" % j)])
                for j in range(8)]
        msgs += [Arr([Bulk(b"smembers"), Bulk(b"probe:s")]),
                 Arr([Bulk(b"scnt"), Bulk(b"probe:s")]),
                 Arr([Bulk(b"sismember"), Bulk(b"probe:s"),
                      Bulk(b"probe-member")])]
        coal = ServeCoalescer(node)
        for _ in range(2):
            out = bytearray()
            coal.run_chunk(list(msgs), out)
            ref = bytearray()
            for m in msgs:
                r = node.execute(m)
                if not isinstance(r, NoReply):
                    encode_into(ref, r)
            if bytes(out) != bytes(ref):
                raise AssertionError(
                    f"node {i}: cached/planned read replies diverged "
                    f"from the per-command reference (stale serve)")

    async def burst(self, cluster: ChaosCluster, n_ops: int,
                    only: Optional[set] = None) -> None:
        rng = self.rng
        live = [i for i in range(len(cluster.apps))
                if cluster.apps[i] is not None
                and (only is None or i in only)]
        clients = {}
        try:
            for i in live:
                clients[i] = await Client().connect(
                    cluster.apps[i].advertised_addr)
            for _ in range(n_ops):
                i = rng.choice(live)
                c = clients[i]
                self.serial += 1
                die = rng.random()
                if die < 0.30:
                    k = f"cnt{rng.randrange(6)}"
                    r = await c.cmd(rng.choice(("incr", "decr")), k,
                                    rng.randrange(1, 4))
                    assert isinstance(r, Int), r
                    self.undoable[i][k] = self.undoable[i].get(k, 0) + 1
                elif die < 0.40 and self.undoable[i]:
                    k = rng.choice(sorted(self.undoable[i]))
                    r = await c.cmd("cntundo", k)
                    # an Err here is a real bug: the tracker only names
                    # keys with a recorded, not-yet-undone local op
                    assert isinstance(r, Int), (k, r)
                    left = self.undoable[i][k] - 1
                    if left:
                        self.undoable[i][k] = left
                    else:
                        del self.undoable[i][k]
                elif die < 0.60:
                    await c.cmd("set", f"reg{rng.randrange(8)}",
                                f"v{self.serial}")
                elif die < 0.75:
                    await c.cmd("sadd", f"set{rng.randrange(6)}",
                                f"m{self.serial % 40}")
                elif die < 0.85:
                    k = f"set{rng.randrange(6)}"
                    # pick drawn UNCONDITIONALLY: the rng stream must not
                    # depend on the reply, or a replay whose timing
                    # shifts one membership view would desync the whole
                    # remaining schedule from its seed
                    pick = rng.random()
                    got = await c.cmd("smembers", k)
                    if isinstance(got, Arr) and got.items:
                        ms = sorted(b.val for b in got.items)
                        await c.cmd("srem", k, ms[int(pick * len(ms))])
                elif die < 0.95:
                    await c.cmd("hset", f"h{rng.randrange(4)}",
                                f"f{rng.randrange(6)}", f"v{self.serial}")
                else:
                    # retire a per-node-exclusive register: set + del on
                    # the same node, never touched again
                    k = f"dead:{i}:{self.serial}".encode()
                    await c.cmd("set", k, "doomed")
                    r = await c.cmd("del", k)
                    assert r == Int(1), (k, r)
                    self.retired_regs.append(k)
        finally:
            for c in clients.values():
                await c.close()


# ------------------------------------------------------------------ runner


async def _corrupt_burst(sc: Scenario, cluster: ChaosCluster, plane,
                         wl: "_Workload", src: int, dst: int,
                         n: int, retries: int = 6) -> None:
    """Arm the REPLBATCH corruption one-shot on src->dst and drive a
    pipelined burst until a demotion is OBSERVED (bounded retries).  A
    consumed injection whose carrying connection died before delivery
    (fate-sharing — e.g. the double-dial adopt overlap) is re-armed and
    re-tried; an injection that reaches a live parser must demote
    within the wait window or the scenario fails loudly."""
    loop = asyncio.get_running_loop()
    demos0 = cluster.stat_total("repl_wire_demotions")
    for _attempt in range(retries):
        plane.corrupt_next_wire(src, dst)
        await wl.pipelined_writes(cluster, src, n)
        deadline = loop.time() + 3.0
        while loop.time() < deadline:
            if cluster.stat_total("repl_wire_demotions") > demos0:
                return
            await asyncio.sleep(0.05)
        # not observed: either the one-shot is still ARMED (no
        # REPLBATCH passed — e.g. the link was mid-resync) or it was
        # consumed and discarded with a dying connection.  Disarm
        # before re-arming so the retry holds exactly one pending shot.
        plane.edge(src, dst).rules.corrupt_next = False
    raise AssertionError(
        f"[chaos {sc.name}] no wire demotion after {retries} corrupt "
        f"bursts — a corrupt payload that reached a live parser was "
        f"swallowed silently")


async def _kill9_mid_write(cluster: ChaosCluster, wl: "_Workload",
                           i: int, torn: bool) -> None:
    """kill -9 (optionally with a torn-tail power loss) while a
    pipelined firehose is mid-flight on node `i`, then cold-restart
    from the node's own op log.  The firehose's unacked suffix dies
    with the connection — exactly the window the durability laws are
    about (cluster.kill9 prunes the never-durable part of the journal
    obligation)."""
    task = asyncio.create_task(wl.pipelined_writes(cluster, i, 96))
    # seeded-but-unconditional draw: the rng stream must not depend on
    # scheduling (scenario replays stay a pure function of the seed)
    await asyncio.sleep(0.004 + wl.rng.random() * 0.02)
    await cluster.kill9(i, torn=torn)
    try:
        await task
    except (ConnectionError, OSError, asyncio.TimeoutError,
            asyncio.IncompleteReadError):
        pass


async def _run_scenario_async(sc: Scenario) -> dict:
    import tempfile

    with tempfile.TemporaryDirectory(prefix="constdb-chaos-") as work:
        plane = FaultPlane(sc.seed)
        journal = OpJournal()
        cluster = ChaosCluster(work, sc.seed,
                               sc.cell.specs(sc.n_nodes, sc.mixed_idx),
                               plane=plane, journal=journal)
        await cluster.start()
        monitor = InvariantMonitor(cluster, journal).start()
        wl = _Workload(sc.seed, sc.n_nodes)
        probe_member = b"probe-member"
        stats: dict = {}
        try:
            await cluster.meet_all()
            await cluster.converge(timeout=20.0)
            for step in sc.steps:
                kind = step[0]
                if kind == "ops":
                    await wl.burst(cluster, step[1])
                elif kind == "ops_on":
                    await wl.burst(cluster, step[2], only={step[1]})
                elif kind == "wire_burst":
                    await wl.pipelined_writes(cluster, step[1], step[2])
                elif kind == "cached_reads":
                    wl.cached_read_check(cluster, step[1])
                elif kind == "corrupt_burst":
                    await _corrupt_burst(sc, cluster, plane, wl,
                                         step[1], step[2], step[3])
                elif kind == "faults":
                    plane.set_faults(step[1], step[2], **step[3])
                elif kind == "clear_faults":
                    plane.clear_faults()
                elif kind == "partition":
                    plane.partition(step[1], step[2], **step[3])
                elif kind == "heal":
                    plane.heal()
                elif kind == "kill_conns":
                    plane.kill_connections(step[1], step[2])
                elif kind == "truncate":
                    plane.truncate_next(step[1], step[2])
                elif kind == "corrupt_wire":
                    plane.corrupt_next_wire(step[1], step[2])
                elif kind == "crash":
                    i = step[1]
                    if step[2] == "cold" or \
                            cluster.apps[i].node.serve_plane is not None:
                        await cluster.restart_cold(i)
                        wl.clear_undo(i)
                    else:
                        await cluster.restart_warm(i)
                elif kind in ("kill9_mid_write", "torn_write"):
                    i = step[1]
                    await _kill9_mid_write(cluster, wl, i,
                                           torn=kind == "torn_write")
                    wl.clear_undo(i)
                elif kind == "ckpt_crash":
                    i = step[1]
                    await cluster.checkpoint_crash(i, step[2])
                    # the restarted process lost its in-memory undo
                    # window (rewrite()'s opening group commit still
                    # makes every acked op durable before the kill)
                    wl.clear_undo(i)
                elif kind == "clock_jump":
                    cluster.clock_jump(step[1], step[2])
                elif kind == "probe_setup":
                    c = await Client().connect(
                        cluster.apps[0].advertised_addr)
                    await c.cmd("sadd", "probe:s", probe_member)
                    await c.close()
                    await cluster.converge(timeout=sc.converge_timeout)
                elif kind == "probe_retire":
                    # retired on node 0 — node 2 is partitioned away and
                    # still holds the member until the heal
                    c = await Client().connect(
                        cluster.apps[0].advertised_addr)
                    await c.cmd("srem", "probe:s", probe_member)
                    await c.close()
                elif kind == "certify":
                    plane.clear_faults()
                    plane.heal()
                    if any(s[0] in ("corrupt_wire", "corrupt_burst")
                           for s in sc.steps):
                        # at least one injection must have HIT a real
                        # REPLBATCH (the targeted bursts guarantee
                        # traffic; retries may consume several)
                        assert plane.stats.get("wire_corruptions", 0) \
                            >= 1, \
                            f"[chaos {sc.name}] wire corruption armed " \
                            f"but never hit a REPLBATCH frame"
                    canon = await certify_state(
                        cluster, journal, timeout=sc.converge_timeout)
                    _check_probes(sc, cluster, wl, canon, probe_member)
                    monitor.check()
                    check_fault_accounting(cluster, plane)
                    stats["canonical_keys"] = len(canon)
                else:
                    raise ValueError(f"unknown scenario step {kind!r}")
            stats["journal_ops"] = len(journal.ops)
            stats["plane"] = dict(plane.stats)
            stats["reconnects"] = sum(
                a.node.stats.repl_reconnects for a in cluster.apps)
            # whole-run gauges the smoke cells assert on: demotions
            # (banked across cold restarts) and the native intake
            # counters — a cell that claims to exercise the C intake
            # stage must show it actually owned client chunks
            stats["wire_demotions"] = \
                cluster.stat_total("repl_wire_demotions")
            stats["native_intake_chunks"] = \
                cluster.stat_total("native_intake_chunks")
            return stats
        except AssertionError:
            raise
        except Exception as e:
            # every failure names the replay seed, whatever its type
            raise AssertionError(
                f"[chaos {sc.name}] scenario crashed: {e!r}") from e
        finally:
            monitor.stop()
            await cluster.close()


def _check_probes(sc: Scenario, cluster, wl: _Workload, canon: dict,
                  probe_member: bytes) -> None:
    """No-resurrection laws over the converged canonical export.  A
    canonical() entry is (enc, ct, mt, dt, expire, content); element
    content rows are (member, add_t, add_node, del_t, val).

    Durability interplay (AOF cells): a kill9/torn crash legally
    ERASES acked-but-never-fsynced ops under `everysec` — the oracle
    prunes them from the journal obligation (emit-only-durable) and
    the mesh converges WITHOUT them.  A retired key whose DELETE op no
    longer exists in the journal is therefore legitimately live again
    (the delete never durably happened); the law being probed —
    nothing resurrects a delete that still EXISTS — only applies while
    the journal holds it.  `certify_state` (which already ran) pins
    the canonical to the pruned journal either way."""
    def journal_has(name: bytes, key: bytes) -> bool:
        j = cluster.journal
        if j is None:
            return True
        return any(n == name and a and getattr(a[0], "val", None) == key
                   for (_o, _u), (n, a) in j.ops.items())

    for key in wl.retired_regs:
        ent = canon.get(key)
        if ent is not None and not ent[1] < ent[3] and \
                not journal_has(b"delbytes", key):
            continue  # the delete was crash-erased before any fsync
        assert ent is None or ent[1] < ent[3], \
            f"[chaos {sc.name}] retired key {key!r} resurrected: {ent}"
    s = canon.get(b"probe:s")
    if s is not None:
        members = {m for m, _at, _an, dlt, _v in s[5] if dlt == 0}
        assert probe_member not in members or \
            not journal_has(b"srem", b"probe:s"), \
            f"[chaos {sc.name}] removed member resurrected after " \
            f"partition heal: {sorted(members)}"


def run_scenario(sc: Scenario) -> dict:
    """Run one scenario to completion (sync wrapper; prints nothing —
    every failure message carries `[chaos seed=N …]`)."""
    if sc.cell.cluster:
        from .cluster_cells import run_cluster_cell
        return run_cluster_cell(sc.cell.cluster, sc.seed,
                                ops=sc.ops_per_burst)
    if sc.cell.tracking:
        from .tracking_cells import run_tracking_cell
        return run_tracking_cell(sc.cell.tracking, sc.seed,
                                 ops=sc.ops_per_burst)
    return asyncio.run(_run_scenario_async(sc))


# re-exported for the CLI and tests
__all__ = ["Cell", "Scenario", "certify_scenario", "soak_scenario",
           "matrix_cells", "smoke_cells", "run_scenario"]
