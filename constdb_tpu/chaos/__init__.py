"""Convergence-under-chaos certification harness (ROADMAP item 5).

Every hot path in this build has a faster variant negotiated by
capability bits — coalesced apply, serve coalescing/sharding, delta
resync, resident device planes, the REPLBATCH wire — and each ships with
a differential suite pinning byte-identity on CLEAN runs.  This package
is the production-readiness gate on top: it certifies that all of those
paths still CONVERGE under partitions, frame reordering, duplicated
delivery, mid-stream and mid-frame connection kills, process crashes
(cold and warm), clock jitter, and mixed-version peers — at once, on
every capability-matrix cell.

Shape of the harness:

  * `plane.FaultPlane` — a seeded fault plane wrapping EVERY inter-node
    transport (ServerApp.peer_connector: replica links are always the
    dialing side of their stream, so wrapping dials covers the mesh).
    It splits each direction into protocol frames (raw FULLSYNC/
    DELTASYNC payload windows stay atomic with their headers) and
    applies scripted or seeded faults per directed edge: partitions
    (full/asymmetric), delay, reorder, duplication, mid-frame
    truncation + kill, targeted REPLBATCH payload corruption.
  * `cluster.ChaosCluster` — node lifecycle: per-cell engine/capability
    configs, deterministic per-node HLC clocks with scripted jitter
    (`ChaosClock`), and the crash primitives (`restart_cold` /
    `restart_warm`) the old tests/test_chaos.py helpers grew into.
  * `oracle` — the invariant oracle: an op JOURNAL tapping every node's
    origin stream (ReplLog.on_append) feeds a CPU-engine reference
    export every node must match byte-identically; a continuous MONITOR
    pins per-link watermark/beacon monotonicity while faults are live;
    post-convergence checks pin digest-matrix agreement, no-resurrection
    of retired keys/members, GC drain, and fault accounting (INFO
    demotion/refusal/reconnect counters vs the faults actually
    injected).
  * `scenario` — the Scenario DSL: seed + node specs + a scripted
    fault/op schedule.  A scenario's decision stream (ops, targets,
    fault choices, backoff jitter) is a pure function of its seed, so
    any failure replays from the printed seed; `certify_scenario` is
    the acceptance schedule (partition + reorder + duplicate +
    mid-stream kill + clock jitter + one mixed-version peer) and
    `matrix_cells` enumerates the capability sweep it must pass on.

  * `resource` — the RESOURCE-fault cells (round 16): a memory-capped
    node under a firehose (shed-at-the-edge with exact -OOM replies,
    replication intake admitted, convergence preserved), a
    stalled-reader client cut at the outbuf cap, and a stalled-reader
    peer recovering through the repl-window pause -> ring eviction ->
    certified resync path.  The fault plane grew a transport-sound
    `stall` primitive for these (a peer that stops reading is a fault
    TCP produces daily).

CLI: `python -m constdb_tpu.chaos [--seed N] [--cells a,b,...] [--all]
[--resource]` (scripts/ci.sh runs the fixed-seed representative cells
as its chaos smoke stage and the resource cells in its overload stage).
"""

from .plane import FaultPlane
from .cluster import ChaosClock, ChaosCluster, NodeSpec
from .oracle import InvariantMonitor, OpJournal
from .resource import run_resource_scenario
from .scenario import (Cell, Scenario, certify_scenario, matrix_cells,
                       run_scenario, smoke_cells, soak_scenario)

__all__ = ["FaultPlane", "ChaosClock", "ChaosCluster", "NodeSpec",
           "InvariantMonitor", "OpJournal", "Cell", "Scenario",
           "certify_scenario", "matrix_cells", "run_scenario",
           "run_resource_scenario", "smoke_cells", "soak_scenario"]
