"""The chaos invariant oracle: journal, reference export, continuous
monitor, and post-convergence laws.

The oracle is grounded in Certified Mergeable Replicated Data Types
(PAPERS.md, arXiv 2203.14518): instead of asserting ad-hoc end states,
it replays each family's MERGE LAWS as executable properties over the
real system under faults —

  convergence      every node's canonical export equals the CPU-engine
                   reference built by replaying the journaled origin
                   streams (any delivery order of commuting rewrites is
                   a valid merge order, so the uuid-sorted replay IS the
                   certified reference)
  monotonicity     per-link watermarks (uuid_he_sent) and REPLACK/beacon
                   progress (uuid_i_acked, uuid_he_acked) never regress
                   within a node incarnation — checked CONTINUOUSLY
                   while faults are live, not just at quiesce
  digest agreement post-convergence, every node's anti-entropy digest
                   matrix is identical (the delta-resync layer and the
                   store agree on what "same state" means)
  no resurrection  keys/members retired before a partition stay dead
                   after it heals (scenario.py drives the probes)
  loud accounting  INFO demotion/refusal/reconnect gauges match the
                   faults the plane actually injected — a silently
                   swallowed fault is itself a failure
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..server.node import Node


class OpJournal:
    """Tap on every node's ORIGIN stream (ReplLog.on_append): the exact
    (origin, uuid, rewrite) set the mesh is obligated to converge on.
    The ring itself evicts, so only a tap taken at append time can
    reconstruct the obligation after a long run."""

    def __init__(self) -> None:
        # (origin_node_id, uuid) -> (name, args); uuids can collide
        # ACROSS origins (two nodes minting in the same millisecond)
        self.ops: dict[tuple[int, int], tuple] = {}

    def hook_node(self, node: Node) -> None:
        """(Re-)install the tap on `node`'s repl log — every segment of
        a sharded node's MergedReplLog, the single ring otherwise.
        Idempotent; the monitor re-installs each poll so a log swapped
        by reset_for_full_resync is re-tapped within one tick."""
        rl = node.repl_log
        logs = rl.segments if hasattr(rl, "segments") else [rl]
        for lg in logs:
            lg.on_append = \
                lambda uuid, name, args, _n=node: self._record(
                    _n.node_id, uuid, name, args)

    def _record(self, origin: int, uuid: int, name: bytes,
                args: list) -> None:
        self.ops.setdefault((origin, uuid), (name, args))

    def prune_origin(self, origin: int, above: int) -> int:
        """Drop `origin`'s journaled ops with uuid > `above` — the
        kill9/torn-write accounting: ops a crashed node appended but
        never made durable were, by the emit-only-durable law
        (persist/oplog.py), never advertised to any peer either, so
        they cease to exist mesh-wide and leave the convergence
        obligation.  `above` is the crashed node's recovered local
        watermark; anything it DID recover (or ever emitted) is at or
        below it and stays in the obligation.  Returns the count."""
        dead = [k for k in self.ops if k[0] == origin and k[1] > above]
        for k in dead:
            del self.ops[k]
        return len(dead)

    def reference_canonical(self, collected: bool = False) -> dict:
        """The certified reference: a fresh CPU-engine node applying
        every journaled rewrite through the REAL per-key apply path, in
        (uuid, origin) order.  The scenario workload is restricted to
        rewrites that are pure pointwise merges (set/cntset/sadd/srem/
        hset/hdel/delbytes/delcnt/…), for which every delivery order —
        including this one — is a merge order, so the reference is the
        unique fixpoint all replicas must hit.  `collected=True`
        additionally drains the reference's own GC to its
        everything-applied horizon — the state a quiesced, fully-acked
        mesh must land on."""
        ref = Node(node_id=(1 << 30) + 7, alias="oracle")
        for (origin, uuid), (name, args) in sorted(self.ops.items(),
                                                   key=lambda kv:
                                                   (kv[0][1], kv[0][0])):
            if name in (b"meet", b"forget"):
                # membership is mesh infrastructure, not keyspace state
                # — and replaying it would give the reference live peers
                # with zero watermarks, pinning its GC horizon at 0
                continue
            ref.apply_replicated(name, args, origin, uuid)
        if collected:
            for _ in range(64):
                ref.gc()
                if not ref.ks.garbage:
                    break
        return ref.canonical()


class InvariantMonitor:
    """Continuous watermark/beacon monotonicity over a live cluster.

    Samples every live node's per-peer watermarks on a short period and
    records any REGRESSION as a violation.  Baselines key on (node,
    incarnation, reset epoch, peer): a cold restart legally rewinds a
    node to its snapshot's watermarks and a state wipe legally zeroes
    them — within one incarnation, going backward is a lost-op bug of
    exactly the kind the round-5 chaos suite once caught in the push
    cursor."""

    def __init__(self, cluster, journal: Optional[OpJournal] = None,
                 period: float = 0.05) -> None:
        self.cluster = cluster
        self.journal = journal
        self.period = period
        self.violations: list[str] = []
        self._seen: dict[tuple, dict] = {}
        self._task: Optional[asyncio.Task] = None

    # one poll is cheap (attribute reads), so the monitor runs at fault
    # cadence without perturbing the system under test

    def poll_once(self) -> None:
        cluster = self.cluster
        for i, app in enumerate(cluster.apps):
            if app is None or app._closing:
                continue
            node = app.node
            inc = cluster.incarnations[i]
            for addr, m in list(node.replicas.peers.items()):
                key = (i, inc, node.reset_epoch, addr)
                cur = {"he_sent": m.uuid_he_sent,
                       "i_acked": m.uuid_i_acked,
                       "he_acked": m.uuid_he_acked}
                prev = self._seen.get(key)
                if prev is not None:
                    for name, v in cur.items():
                        if v < prev[name]:
                            self.violations.append(
                                f"node {i} peer {addr}: {name} regressed "
                                f"{prev[name]} -> {v} (incarnation {inc},"
                                f" epoch {node.reset_epoch})")
                self._seen[key] = cur
            if self.journal is not None:
                self.journal.hook_node(node)

    async def _run(self) -> None:
        while True:
            self.poll_once()
            await asyncio.sleep(self.period)

    def start(self) -> "InvariantMonitor":
        self._task = asyncio.create_task(self._run())
        return self

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    def check(self) -> None:
        self.poll_once()
        if self.violations:
            raise AssertionError(
                f"[chaos seed={self.cluster.seed}] watermark/beacon "
                f"monotonicity violated: {self.violations[:5]}"
                + (f" (+{len(self.violations) - 5} more)"
                   if len(self.violations) > 5 else ""))


async def certify_state(cluster, journal: OpJournal,
                        timeout: float = 30.0) -> dict:
    """The quiesce-time oracle, as one fixpoint: every node must reach
    the CPU-engine reference's canonical export BYTE-identically, every
    pair of digest matrices must agree, and every garbage heap must
    DRAIN (with the mesh quiesced and every stream acked, the GC
    horizon passes every tombstone — collection must really run, not
    merely defer).  GC progress is intentionally part of the fixpoint:
    replicas legally collect at different times, so digests/canonicals
    are only comparable once collection has quiesced on both sides of
    each comparison — including the reference, which collects its own
    tombstones to the same everything-acked horizon."""
    import numpy as np

    await cluster.converge(timeout=timeout)
    ref = journal.reference_canonical(collected=True)
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    why = "?"
    while True:
        pending = 0
        for app in cluster.apps:
            plane = app.node.serve_plane
            if plane is not None:
                await plane.gc(app.node.gc_horizon())
            else:
                app.node.gc()
                pending += len(app.node.ks.garbage)
        if pending:
            why = f"{pending} tombstones still pending collection"
        else:
            canons = [await cluster.canonical_of(i)
                      for i in range(len(cluster.apps))]
            bad = [i for i, c in enumerate(canons) if c != ref]
            if bad:
                diff = {k for k in set(canons[bad[0]]) | set(ref)
                        if canons[bad[0]].get(k) != ref.get(k)}
                why = (f"nodes {bad} diverge from the CPU-engine "
                       f"reference: {len(diff)} keys, e.g. "
                       f"{sorted(diff)[:5]}")
            else:
                mats = [await cluster.digest_of(i)
                        for i in range(len(cluster.apps))]
                bad = [i for i, m in enumerate(mats)
                       if not np.array_equal(mats[0], m)]
                if not bad:
                    return ref
                why = f"digest matrices disagree: node 0 vs nodes {bad}"
        if loop.time() > deadline:
            raise AssertionError(
                f"[chaos seed={cluster.seed}] certification never "
                f"reached its fixpoint: {why}")
        await asyncio.sleep(0.2)


def check_fault_accounting(cluster, plane) -> None:
    """Loud-accounting law: what the plane injected must show up in the
    nodes' own gauges — and what it did NOT inject must not.  Counters
    span the whole run: a cold restart banks its node's stats into the
    cluster before discarding them (ChaosCluster.stat_total)."""
    seed = cluster.seed
    demotions = cluster.stat_total("repl_wire_demotions")
    corruptions = plane.stats.get("wire_corruptions", 0)
    if corruptions == 0:
        assert demotions == 0, \
            f"[chaos seed={seed}] {demotions} wire demotions with no " \
            f"injected corruption — the codec is rejecting clean payloads"
    else:
        assert 1 <= demotions <= corruptions, \
            f"[chaos seed={seed}] injected {corruptions} wire " \
            f"corruptions but counted {demotions} demotions — a corrupt " \
            f"payload was swallowed silently"
    kills = plane.stats.get("conn_kills", 0) + \
        plane.stats.get("truncations", 0)
    if kills:
        assert cluster.stat_total("repl_reconnects") >= 1, \
            f"[chaos seed={seed}] {kills} injected connection kills but " \
            f"zero reconnects — links are not recovering"
    refused = cluster.stat_total("fullsync_reset_refused")
    assert refused == 0, \
        f"[chaos seed={seed}] {refused} fullsync-reset refusals in a " \
        f"mesh that never excludes peers from the GC horizon"
