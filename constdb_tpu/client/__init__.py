"""Client-side tier: the tracked near-cache (client/near_cache.py).

Server counterpart: server/tracking.py (RESP3 invalidation pushes).
"""

from .near_cache import NearCacheClient

__all__ = ["NearCacheClient"]
