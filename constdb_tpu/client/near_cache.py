"""Tracked RESP3 client with a near-cache tier.

The server half (server/tracking.py) forwards the reply cache's
invalidation stream to subscribed connections as RESP3 push frames;
this client turns that stream into a local read tier: a GET whose key
is quiet since the last read is answered from process memory with ZERO
server round-trips.

Trust discipline (docs/INVARIANTS.md "Tracking laws"):

  * **connection-scoped trust** — a cached entry is only trustworthy
    while the connection that filled it is live: the server's one-shot
    invalidation promise is per-connection state that dies with the
    socket.  ANY disconnect (error, EOF, server abort, reconnect)
    therefore flushes the whole near-cache BEFORE the first read after
    it — the reconnect-flush law.  The flush happens at disconnect
    DETECTION (both in the reader task and on the command path), so a
    half-dead connection can never serve a stale entry in between.
  * **invalidate-before-visible, client half** — push frames are
    consumed by a dedicated reader task the moment they arrive, and a
    near-cache hit yields to the event loop first (`sleep(0)`), so an
    invalidation that has reached this process is always applied before
    a hit is served.  (The wire itself is ordered: the server queues
    the push before the mutation's effects are observable.)
  * **own writes** — a write issued through this client drops its key
    locally at send time; the server's push (which the registry owes
    this very connection) would arrive only after the reply.

The transport mirrors chaos/cluster.py Client — one connection, one
in-flight command (callers serialize through an internal lock), pure
RespParser (it decodes `>N` push frames natively; resp/codec.py).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..resp.codec import RespParser, encode_msg
from ..resp.message import Arr, Bulk, Err, Msg, Nil, Push, as_int

# commands whose FIRST argument names a key this client may mutate —
# issued through cmd(), they drop the key from the near-cache locally
# (the server's own push covers every other writer)
_WRITE_CMDS = frozenset((b"set", b"del", b"incr", b"incrby", b"decr",
                         b"decrby", b"sadd", b"srem", b"hset", b"hdel",
                         b"lpush", b"rpush", b"lpop", b"rpop", b"expire",
                         b"persist"))


class NearCacheClient:
    """One tracked RESP3 connection + its near-cache tier."""

    def __init__(self, addr: str, bcast: bool = False,
                 prefixes: tuple = (), max_entries: int = 65536) -> None:
        self.addr = addr
        self.bcast = bcast
        self.prefixes = tuple(prefixes)
        self.max_entries = max_entries
        self.cache: dict[bytes, Msg] = {}
        # client-side telemetry (the bench oracle + chaos cells read
        # these)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0   # keys dropped by push frames
        self.flushes = 0         # whole-cache drops (push-nil/disconnect)
        self.client_id = 0
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._replies: asyncio.Queue = asyncio.Queue()
        self._reader_task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()
        self._connected = False
        # fill-race guard: an invalidation (or flush) that lands while
        # a GET is in flight POISONS the fill — caching the reply after
        # its invalidation was already consumed would strand a stale
        # entry forever (the server's one-shot promise is spent)
        self._pending_key: Optional[bytes] = None
        self._poisoned = False

    # ------------------------------------------------------------ lifecycle

    async def connect(self) -> "NearCacheClient":
        """Dial + HELLO 3 + CLIENT TRACKING on.  Always flushes the
        near-cache first: whatever connection previously filled it is
        gone, and with it the server's invalidation promise."""
        self._flush("reconnect")
        host, port = self.addr.rsplit(":", 1)
        self.reader, self.writer = await asyncio.open_connection(
            host, int(port))
        self._replies = asyncio.Queue()
        self._connected = True
        self._reader_task = asyncio.get_running_loop().create_task(
            self._read_loop())
        hello = await self._roundtrip(b"hello", b"3")
        if isinstance(hello, Err):
            await self.close()
            raise ConnectionError(f"HELLO 3 refused: {hello.val!r}")
        items = hello.items if isinstance(hello, Arr) else []
        for i in range(0, len(items) - 1, 2):
            if isinstance(items[i], Bulk) and items[i].val == b"id":
                self.client_id = as_int(items[i + 1])
        sub = [b"client", b"tracking", b"on"]
        if self.bcast:
            sub.append(b"bcast")
            for p in self.prefixes:
                sub += [b"prefix", p]
        reply = await self._roundtrip(*sub)
        if isinstance(reply, Err):
            await self.close()
            raise ConnectionError(
                f"CLIENT TRACKING refused: {reply.val!r}")
        return self

    async def close(self) -> None:
        self._connected = False
        if self._reader_task is not None:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except (asyncio.CancelledError, Exception):
                pass
            self._reader_task = None
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self.writer = None
        self.reader = None

    # ------------------------------------------------------------ data path

    async def get(self, key: bytes) -> Msg:
        """GET through the near-cache: a tracked hit costs zero server
        round-trips.  The `sleep(0)` yield lets the reader task apply
        any already-arrived invalidation push before the hit is
        trusted."""
        if not self._connected:
            raise ConnectionError("not connected")
        await asyncio.sleep(0)
        hit = self.cache.get(key)
        if hit is not None:
            self.hits += 1
            return hit
        self.misses += 1
        async with self._lock:
            self._pending_key, self._poisoned = key, False
            try:
                reply = await self._send_and_wait(
                    [Bulk(b"get"), Bulk(key)])
            finally:
                poisoned = self._poisoned
                self._pending_key, self._poisoned = None, False
            if not poisoned and not isinstance(reply, Err):
                if len(self.cache) >= self.max_entries:
                    # bounded tier: drop the oldest entry (insertion
                    # order) — correctness never depends on residency
                    self.cache.pop(next(iter(self.cache)))
                self.cache[key] = reply
            return reply

    async def cmd(self, *parts) -> Msg:
        """Generic passthrough.  A write command's key drops from the
        near-cache at send time (see module doc, "own writes")."""
        if not self._connected:
            raise ConnectionError("not connected")
        items = [Bulk(p if isinstance(p, bytes) else str(p).encode())
                 for p in parts]
        if len(items) > 1 and items[0].val.lower() in _WRITE_CMDS:
            self.cache.pop(items[1].val, None)
        async with self._lock:
            return await self._send_and_wait(items)

    async def set(self, key: bytes, val: bytes) -> Msg:
        return await self.cmd(b"set", key, val)

    # ------------------------------------------------------------- plumbing

    async def _roundtrip(self, *parts) -> Msg:
        async with self._lock:
            return await self._send_and_wait(
                [Bulk(p if isinstance(p, bytes) else str(p).encode())
                 for p in parts])

    async def _send_and_wait(self, items: list) -> Msg:
        try:
            self.writer.write(encode_msg(Arr(items)))
            await self.writer.drain()
        except (ConnectionError, OSError) as e:
            self._on_disconnect()
            raise ConnectionError(str(e)) from e
        reply = await self._replies.get()
        if reply is None:
            # the reader task died: the connection is gone (it already
            # flushed the cache) — surface it on the command path
            raise ConnectionError("connection lost")
        return reply

    async def _read_loop(self) -> None:
        """Dedicated frame pump: push frames apply IMMEDIATELY (the
        client half of invalidate-before-visible); everything else is a
        reply for the command in flight."""
        parser = RespParser()
        try:
            while True:
                data = await self.reader.read(1 << 16)
                if not data:
                    break
                parser.feed(data)
                while (msg := parser.next_msg()) is not None:
                    if isinstance(msg, Push):
                        self._on_push(msg)
                    else:
                        self._replies.put_nowait(msg)
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass
        finally:
            self._on_disconnect()

    def _on_push(self, msg: Push) -> None:
        items = msg.items
        if not items or not isinstance(items[0], Bulk) or \
                items[0].val != b"invalidate":
            return  # unknown push kind: ignore (forward-compatible)
        payload = items[1] if len(items) > 1 else None
        if isinstance(payload, Arr):
            for k in payload.items:
                if isinstance(k, Bulk):
                    if self.cache.pop(k.val, None) is not None:
                        self.invalidations += 1
                    if k.val == self._pending_key:
                        self._poisoned = True
        elif isinstance(payload, Nil) or payload is None:
            self._flush("push-nil")

    def _on_disconnect(self) -> None:
        if self._connected:
            self._connected = False
            self._flush("disconnect")
            self._replies.put_nowait(None)  # wake a waiting command

    def _flush(self, _why: str) -> None:
        if self._pending_key is not None:
            self._poisoned = True
        if self.cache:
            self.flushes += 1
            self.cache.clear()
