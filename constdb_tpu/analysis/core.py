"""Invariant lint engine: AST rule framework + baseline machinery.

Every major defect the advisor rounds surfaced was a *discipline*
violation, not a logic error — `_pool_add` mutated pool state before its
ceiling check, a blocking call stalled the asyncio loop, a stray env read
bypassed `conf.py`.  Those disciplines lived only in comments and
postmortems; this package checks them mechanically, before runtime (the
"convergence checked before runtime" stance of Certified MRDTs,
arxiv 2203.14518 — see PAPERS.md).

Moving parts:
  * `Finding` — one violation: rule, severity, file:line, the enclosing
    function's qualname, a stable `token`, a message and a fix hint.
    `key` (rule:path:qualname:token — NO line number) is the identity
    baselining uses, so pre-existing findings survive unrelated edits.
  * `Rule` — subclass per invariant (see rules.py).  `applies(ctx)`
    scopes by path parts (e.g. ASYNC-BLOCK only looks under `server/` +
    `replica/`), which is also how the seeded-violation corpus under
    tests/analysis_corpus/ mirrors the package layout.
  * `FileContext` — parsed source shared by every rule: the AST, an
    indexed function list (qualnames + async ancestry), per-line
    `# lint: ignore[RULE]` sets, and helpers (`own_nodes`, `dotted`).
  * Baseline — `baseline.json` records pre-existing finding keys with
    counts and per-key notes; `--baseline` mode fails only on GROWTH
    (a new key, or more findings than the recorded count for a key).

Escape hatch: append `# lint: ignore[RULE-NAME]` (comma-separate for
several rules, `*` for all) on the offending line.  Use it for findings
that are correct-by-design AND documented on the spot — everything else
belongs in the baseline with a tracking note, or fixed.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

SEVERITIES = ("note", "warning", "error")

_IGNORE_RE = re.compile(r"#\s*lint:\s*ignore\[([A-Za-z0-9_*,\- ]+)\]")


@dataclass
class Finding:
    rule: str
    severity: str
    path: str           # posix relpath from the scan root
    line: int
    qualname: str       # enclosing function/class dotted name ("" = module)
    token: str          # stable signature element (offending call/attr name)
    message: str
    hint: str = ""

    @property
    def key(self) -> str:
        """Line-number-free identity: survives unrelated edits above the
        finding.  Multiple same-token findings in one function are
        handled by COUNT in the baseline, not by distinct keys."""
        return f"{self.rule}:{self.path}:{self.qualname}:{self.token}"

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        who = f" in {self.qualname}" if self.qualname else ""
        out = f"{where}: [{self.severity}] {self.rule}{who}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


class FileContext:
    """One parsed source file, shared by every rule."""

    def __init__(self, relpath: str, source: str, tree: ast.AST):
        self.relpath = relpath
        self.parts = tuple(relpath.split("/"))
        self.basename = self.parts[-1]
        self.source = source
        self.tree = tree
        # line -> set of rule names ignored there ("*" = all)
        self.ignores: dict[int, set[str]] = {}
        for i, line in enumerate(source.splitlines(), 1):
            m = _IGNORE_RE.search(line)
            if m:
                self.ignores[i] = {s.strip()
                                   for s in m.group(1).split(",") if s.strip()}
        # (qualname, node, is_async, async_ancestor)
        self.functions: list[tuple[str, ast.AST, bool, bool]] = []
        self._index(tree, "", False)

    def _index(self, node: ast.AST, prefix: str, async_ctx: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                is_async = isinstance(child, ast.AsyncFunctionDef)
                self.functions.append((q, child, is_async, async_ctx))
                self._index(child, q, async_ctx or is_async)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                self._index(child, q, async_ctx)
            else:
                self._index(child, prefix, async_ctx)

    def ignored(self, rule: str, line: int) -> bool:
        """The escape hatch matches on the finding's line or the line
        immediately above it (a trailing comment on multi-line
        statements would fight the line-length limit)."""
        for ln in (line, line - 1):
            got = self.ignores.get(ln)
            if got and ("*" in got or rule in got):
                return True
        return False


def own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Every node in `fn`'s body EXCLUDING nested function/class bodies
    (nested defs are yielded themselves — so a rule can see that a
    closure exists — but never descended into; they get their own
    FileContext.functions entry)."""
    stack = list(getattr(fn, "body", []))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def dotted(node: ast.AST) -> str:
    """Best-effort dotted name for a call target / attribute chain
    ('time.sleep', 'self._pool_add', 'os.environ.get'); '' when the base
    is an expression (then match on the terminal attr instead)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


class Rule:
    """One invariant.  Subclasses set `name`/`severity`/`hint`/`doc` and
    implement `check(ctx)` (a generator of Findings — emit via
    `self.finding(...)` so ignore comments are honored uniformly)."""

    name = ""
    severity = "error"
    hint = ""
    doc = ""

    def applies(self, ctx: FileContext) -> bool:
        return True

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finding(self, ctx: FileContext, node: ast.AST, qualname: str,
                token: str, message: str) -> Optional[Finding]:
        line = getattr(node, "lineno", 1)
        if ctx.ignored(self.name, line):
            return None
        return Finding(self.name, self.severity, ctx.relpath, line,
                       qualname, token, message, self.hint)


# ------------------------------------------------------------------ engine

def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith(".")
                                     and d != "__pycache__")
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        yield os.path.join(dirpath, f)
        elif p.endswith(".py"):
            yield p


def analyze_paths(paths: Iterable[str], root: str,
                  rules: Optional[list[Rule]] = None) -> list[Finding]:
    """Run `rules` (default: rules.ALL_RULES) over every .py file under
    `paths`; relpaths (rule scoping + finding identity) are taken from
    `root`, so the corpus can mirror the package layout under any dir."""
    if rules is None:
        from .rules import ALL_RULES
        rules = ALL_RULES
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            findings.append(Finding(
                "PARSE-ERROR", "error", rel, e.lineno or 1, "", "syntax",
                f"file does not parse: {e.msg}"))
            continue
        ctx = FileContext(rel, source, tree)
        for rule in rules:
            if rule.applies(ctx):
                findings.extend(f for f in rule.check(ctx) if f is not None)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------- baseline

def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: Optional[str] = None) -> dict:
    path = path or default_baseline_path()
    if not os.path.exists(path):
        return {"version": 1, "findings": {}, "notes": {}}
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def baseline_payload(findings: list[Finding], notes: dict) -> dict:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return {"version": 1,
            "findings": dict(sorted(counts.items())),
            "notes": dict(sorted(notes.items()))}


def compare_to_baseline(findings: list[Finding], baseline: dict
                        ) -> tuple[list[Finding], list[str]]:
    """-> (growth, stale): `growth` is every finding beyond its key's
    baselined count (fails the gate); `stale` lists baseline keys whose
    live count DROPPED (fixed findings — prune them with
    --write-baseline; informational only)."""
    allowed = dict(baseline.get("findings", {}))
    by_key: dict[str, list[Finding]] = {}
    for f in findings:
        by_key.setdefault(f.key, []).append(f)
    growth: list[Finding] = []
    for key, fs in by_key.items():
        fs.sort(key=lambda f: f.line)
        growth.extend(fs[allowed.get(key, 0):])
    growth.sort(key=lambda f: (f.path, f.line, f.rule))
    stale = sorted(k for k, n in allowed.items()
                   if len(by_key.get(k, ())) < n)
    return growth, stale
