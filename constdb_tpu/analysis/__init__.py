"""Invariant lint engine (see core.py for the framework, rules.py for
the repo-specific rules, docs/INVARIANTS.md for the rule ↔ incident
map).  CLI: `python -m constdb_tpu.analysis [--baseline] [paths...]`."""

from .core import (Finding, Rule, analyze_paths, compare_to_baseline,
                   default_baseline_path, load_baseline)
from .rules import ALL_RULES

__all__ = ["Finding", "Rule", "ALL_RULES", "analyze_paths",
           "compare_to_baseline", "default_baseline_path", "load_baseline",
           "run_default_analysis", "check_readme_registry"]


def _package_root() -> tuple[list[str], str]:
    """(default scan paths, scan root): the constdb_tpu package dir,
    relpaths anchored at its parent (so findings read
    `constdb_tpu/replica/link.py`)."""
    import os
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [pkg], os.path.dirname(pkg)


def run_default_analysis() -> list[Finding]:
    """Every rule over the live package tree."""
    paths, root = _package_root()
    return analyze_paths(paths, root=root)


def check_readme_registry(readme_path: str | None = None) -> list[Finding]:
    """Project-level half of ENV-REGISTRY: every conf.ENV_REGISTRY name
    must appear in the README Tuning table (the registry is the source
    of truth; the table is the operator's view of it)."""
    import os

    from .. import conf
    if readme_path is None:
        _, root = _package_root()
        readme_path = os.path.join(root, "README.md")
    if not os.path.exists(readme_path):
        return []
    with open(readme_path, "r", encoding="utf-8") as f:
        text = f.read()
    out = []
    for name in sorted(conf.ENV_REGISTRY):
        if name not in text:
            out.append(Finding(
                "ENV-REGISTRY", "error", os.path.basename(readme_path), 1,
                "", f"{name}:undocumented",
                f"{name} is declared in conf.ENV_REGISTRY but missing "
                "from the README Tuning table",
                "add a row to the README Tuning table"))
    return out
