"""CLI: `python -m constdb_tpu.analysis [options] [paths...]`

Modes:
  (default)          print every finding; exit 1 if any.
  --baseline         compare against analysis/baseline.json; exit 1 only
                     on GROWTH (new keys, or counts above the recorded
                     ones).  This is the CI gate (scripts/lint.sh).
  --write-baseline   regenerate baseline.json from the current findings,
                     preserving existing per-key notes.
  --list-rules       print each rule's name + one-line purpose.
  --json             machine-readable output on stdout instead of the
                     human rendering (composes with --baseline).  The
                     payload's `counts` map uses the same
                     rule:path:qualname:token keys as baseline.json, so
                     CI can artifact a run and diff it against another
                     or against the committed baseline directly.

Default scan: the constdb_tpu package (plus the project-level README ↔
ENV_REGISTRY check).  Explicit paths skip the project-level check and
anchor relpaths at --root (default: cwd).
"""

from __future__ import annotations

import argparse
import os
import sys

from . import (analyze_paths, check_readme_registry, compare_to_baseline,
               default_baseline_path, load_baseline, run_default_analysis)
from .core import baseline_payload
from .rules import ALL_RULES


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m constdb_tpu.analysis",
        description="constdb-tpu invariant lint")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the package)")
    ap.add_argument("--root", default=None,
                    help="relpath anchor for explicit paths (default: cwd)")
    ap.add_argument("--baseline", action="store_true",
                    help="fail only on growth over analysis/baseline.json")
    ap.add_argument("--baseline-path", default=None)
    ap.add_argument("--write-baseline", action="store_true",
                    help="regenerate baseline.json (keeps existing notes)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout (stable "
                         "keys matching baseline.json)")
    ns = ap.parse_args(argv)

    if ns.list_rules:
        for rule in ALL_RULES:
            first = (rule.doc or rule.__doc__ or "").strip().splitlines()[0]
            print(f"{rule.name:<20} {first}")
        return 0

    if ns.paths:
        findings = analyze_paths(ns.paths, root=ns.root or os.getcwd())
    else:
        findings = run_default_analysis() + check_readme_registry()

    bpath = ns.baseline_path or default_baseline_path()
    if ns.write_baseline:
        import json
        notes = load_baseline(bpath).get("notes", {})
        payload = baseline_payload(findings, notes)
        with open(bpath, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {bpath}: {len(payload['findings'])} keys "
              f"({len(findings)} findings)")
        return 0

    if ns.as_json:
        import json
        payload = {
            "version": 1,
            "counts": baseline_payload(findings, {})["findings"],
            "findings": [{
                "key": f.key, "rule": f.rule, "severity": f.severity,
                "path": f.path, "line": f.line, "qualname": f.qualname,
                "token": f.token, "message": f.message, "hint": f.hint,
            } for f in findings],
        }
        if ns.baseline:
            growth, stale = compare_to_baseline(findings,
                                                load_baseline(bpath))
            payload["baseline"] = {"growth": sorted(f.key for f in growth),
                                   "stale": stale}
            print(json.dumps(payload, indent=1, sort_keys=True))
            return 1 if growth else 0
        print(json.dumps(payload, indent=1, sort_keys=True))
        return 1 if findings else 0

    if ns.baseline:
        growth, stale = compare_to_baseline(findings, load_baseline(bpath))
        for f in growth:
            print(f.render())
        for key in stale:
            print(f"note: baselined finding no longer present "
                  f"(prune with --write-baseline): {key}")
        if growth:
            print(f"\n{len(growth)} NEW finding(s) over the baseline "
                  f"({len(findings)} total, "
                  f"{len(findings) - len(growth)} baselined)")
            return 1
        print(f"clean: {len(findings)} finding(s), all baselined "
              f"({len(stale)} stale baseline key(s))")
        return 0

    for f in findings:
        print(f.render())
    if findings:
        print(f"\n{len(findings)} finding(s)")
        return 1
    print("clean: no findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
