"""Control-flow graphs over (async) function bodies.

The per-node rules in :mod:`constdb_tpu.analysis.rules` see one statement at
a time, which is exactly the granularity where every shipped race hid: the
PR 2 close-window, the PR 11 consistency cut and the PR 12 quiesce callback
were all "read before an ``await``, trusted after it".  To reason about that
we need path information — which statements can execute between a read and
its use, and whether an await point sits on that path.

This module builds a deliberately small CFG:

* one :class:`Block` is a maximal run of statements with no internal branch;
* edges follow Python's structured control flow (``if``/``while``/``for``/
  ``try``/``with``/``match``, plus ``break``/``continue``/``return``/``raise``);
* nested ``def``/``class`` bodies are opaque — the analysis is
  intraprocedural, matching the engine's per-function reporting unit;
* await *partitioning* happens downstream: blocks carry raw statements and
  :func:`awaits_in` tells the dataflow engine where the interleaving points
  are inside each statement.

``try`` is approximated conservatively: every handler is reachable from the
start of the protected body *and* after each of its statements, so facts
that may be torn mid-body survive into the handler.  That over-approximates
reachability, which is the safe direction for a may-staleness analysis.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple


_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def awaits_in(node: ast.AST) -> List[ast.Await]:
    """Await expressions syntactically inside ``node``, own scope only
    (nested def/lambda/class bodies are opaque).  Passing a function
    node searches that function's own body."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(node)) \
        if isinstance(node, _SCOPES) else [node]
    hits: List[ast.Await] = []
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Await):
            hits.append(n)
        if isinstance(n, _SCOPES):
            continue
        stack.extend(ast.iter_child_nodes(n))
    hits.sort(key=lambda a: (a.lineno, a.col_offset))
    return hits


def has_await(node: ast.AST) -> bool:
    return bool(awaits_in(node))


@dataclass
class Block:
    """A straight-line run of statements.

    ``stmts`` holds the statements executed when control passes through the
    block.  Branch tests (``if``/``while`` conditions, ``for`` iterables)
    are recorded as ``test`` so the dataflow engine can evaluate their
    reads exactly once per traversal of the block.
    """

    bid: int
    stmts: List[ast.stmt] = field(default_factory=list)
    test: Optional[ast.expr] = None
    succs: List[int] = field(default_factory=list)

    def link(self, other: "Block") -> None:
        if other.bid not in self.succs:
            self.succs.append(other.bid)


class CFG:
    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.blocks: Dict[int, Block] = {}
        self.entry = self._new()
        self.exit = self._new()

    def _new(self) -> Block:
        blk = Block(bid=len(self.blocks))
        self.blocks[blk.bid] = blk
        return blk

    def succ(self, blk: Block) -> Iterator[Block]:
        for bid in blk.succs:
            yield self.blocks[bid]

    def rpo(self) -> List[Block]:
        """Reverse post-order from entry — a good worklist seed order."""
        seen: set[int] = set()
        order: List[Block] = []

        stack: List[Tuple[Block, Iterator[Block]]] = [
            (self.entry, self.succ(self.entry))
        ]
        seen.add(self.entry.bid)
        while stack:
            blk, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt.bid not in seen:
                    seen.add(nxt.bid)
                    stack.append((nxt, self.succ(nxt)))
                    advanced = True
                    break
            if not advanced:
                order.append(blk)
                stack.pop()
        order.reverse()
        return order


class _Builder:
    """Structured-statement walk that threads a "current block" cursor."""

    def __init__(self, cfg: CFG) -> None:
        self.cfg = cfg
        # (continue_target, break_target) stack for loops
        self.loops: List[Tuple[Block, Block]] = []

    def build(self, body: List[ast.stmt]) -> None:
        cur = self._seq(body, self.cfg.entry)
        if cur is not None:
            cur.link(self.cfg.exit)

    # -- helpers ---------------------------------------------------------

    def _seq(self, body: List[ast.stmt], cur: Optional[Block]) -> Optional[Block]:
        for stmt in body:
            if cur is None:
                # dead code after return/raise/break — still build it so
                # the rules can look at it, but leave it unreachable.
                cur = self.cfg._new()
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, stmt: ast.stmt, cur: Block) -> Optional[Block]:
        if isinstance(stmt, (ast.If,)):
            return self._if(stmt, cur)
        if isinstance(stmt, (ast.While,)):
            return self._while(stmt, cur)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._for(stmt, cur)
        if isinstance(stmt, (ast.Try,)):
            return self._try(stmt, cur)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, cur)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, cur)
        if isinstance(stmt, (ast.Return, ast.Raise)):
            cur.stmts.append(stmt)
            cur.link(self.cfg.exit)
            return None
        if isinstance(stmt, ast.Break):
            cur.stmts.append(stmt)
            if self.loops:
                cur.link(self.loops[-1][1])
            else:
                cur.link(self.cfg.exit)
            return None
        if isinstance(stmt, ast.Continue):
            cur.stmts.append(stmt)
            if self.loops:
                cur.link(self.loops[-1][0])
            else:
                cur.link(self.cfg.exit)
            return None
        # Plain statement (incl. nested def/class — opaque to the analysis).
        cur.stmts.append(stmt)
        return cur

    def _if(self, stmt: ast.If, cur: Block) -> Optional[Block]:
        head = self.cfg._new()
        cur.link(head)
        head.test = stmt.test
        join = self.cfg._new()

        then_entry = self.cfg._new()
        head.link(then_entry)
        then_end = self._seq(stmt.body, then_entry)
        if then_end is not None:
            then_end.link(join)

        if stmt.orelse:
            else_entry = self.cfg._new()
            head.link(else_entry)
            else_end = self._seq(stmt.orelse, else_entry)
            if else_end is not None:
                else_end.link(join)
        else:
            head.link(join)
        return join

    def _while(self, stmt: ast.While, cur: Block) -> Optional[Block]:
        head = self.cfg._new()
        cur.link(head)
        head.test = stmt.test
        after = self.cfg._new()

        body_entry = self.cfg._new()
        head.link(body_entry)
        self.loops.append((head, after))
        body_end = self._seq(stmt.body, body_entry)
        self.loops.pop()
        if body_end is not None:
            body_end.link(head)

        if stmt.orelse:
            else_entry = self.cfg._new()
            head.link(else_entry)
            else_end = self._seq(stmt.orelse, else_entry)
            if else_end is not None:
                else_end.link(after)
        else:
            head.link(after)
        return after

    def _for(self, stmt: ast.stmt, cur: Block) -> Optional[Block]:
        # The iterable is evaluated once; the header re-binds the target
        # each iteration.  Model the header as a test block carrying the
        # whole For node so the dataflow can see iter + target together.
        head = self.cfg._new()
        cur.link(head)
        head.stmts.append(stmt_header(stmt))
        after = self.cfg._new()

        body_entry = self.cfg._new()
        head.link(body_entry)
        self.loops.append((head, after))
        body_end = self._seq(stmt.body, body_entry)
        self.loops.pop()
        if body_end is not None:
            body_end.link(head)

        if stmt.orelse:
            else_entry = self.cfg._new()
            head.link(else_entry)
            else_end = self._seq(stmt.orelse, else_entry)
            if else_end is not None:
                else_end.link(after)
        else:
            head.link(after)
        return after

    def _try(self, stmt: ast.Try, cur: Block) -> Optional[Block]:
        join = self.cfg._new()

        body_entry = self.cfg._new()
        cur.link(body_entry)

        handler_entries: List[Block] = []
        for handler in stmt.handlers:
            h_entry = self.cfg._new()
            handler_entries.append(h_entry)
            # Entered from the start of the body (fact may tear anywhere).
            body_entry.link(h_entry)

        body_cur: Optional[Block] = body_entry
        for s in stmt.body:
            if body_cur is None:
                body_cur = self.cfg._new()
            body_cur = self._stmt(s, body_cur)
            if body_cur is not None:
                for h_entry in handler_entries:
                    body_cur.link(h_entry)

        else_end: Optional[Block] = body_cur
        if stmt.orelse:
            else_end = self._seq(stmt.orelse, body_cur)

        ends: List[Optional[Block]] = [else_end]
        for handler, h_entry in zip(stmt.handlers, handler_entries):
            if handler.type is not None:
                h_entry.stmts.append(stmt_header(handler))
            ends.append(self._seq(handler.body, h_entry))

        if stmt.finalbody:
            fin_entry = self.cfg._new()
            for end in ends:
                if end is not None:
                    end.link(fin_entry)
            fin_end = self._seq(stmt.finalbody, fin_entry)
            if fin_end is not None:
                fin_end.link(join)
            else:
                return None
        else:
            linked = False
            for end in ends:
                if end is not None:
                    end.link(join)
                    linked = True
            if not linked:
                return None
        return join

    def _with(self, stmt: ast.stmt, cur: Block) -> Optional[Block]:
        # Context-manager enter/exit is modelled as a header statement
        # (async with = an await point) followed by the body inline.
        cur.stmts.append(stmt_header(stmt))
        return self._seq(stmt.body, cur)

    def _match(self, stmt: ast.Match, cur: Block) -> Optional[Block]:
        head = self.cfg._new()
        cur.link(head)
        head.test = stmt.subject
        join = self.cfg._new()
        for case in stmt.cases:
            c_entry = self.cfg._new()
            head.link(c_entry)
            c_end = self._seq(case.body, c_entry)
            if c_end is not None:
                c_end.link(join)
        # No case may match.
        head.link(join)
        return join


class _Header(ast.stmt):
    """Synthetic statement wrapping a compound node's header.

    Lets the dataflow engine evaluate a ``for`` target/iter, ``with``
    items or ``except`` clause without re-walking the suite it guards
    (the suite's statements already live in their own blocks).
    """

    _fields = ("node",)

    def __init__(self, node: ast.AST) -> None:
        super().__init__()
        self.node = node
        self.lineno = getattr(node, "lineno", 0)
        self.col_offset = getattr(node, "col_offset", 0)


def stmt_header(node: ast.AST) -> _Header:
    return _Header(node)


def is_header(stmt: ast.stmt) -> bool:
    return isinstance(stmt, _Header)


def build_cfg(fn: ast.AST) -> CFG:
    """Build a CFG for a FunctionDef / AsyncFunctionDef body."""
    cfg = CFG(fn)
    _Builder(cfg).build(list(getattr(fn, "body", [])))
    return cfg
