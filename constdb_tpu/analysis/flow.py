"""Flow-sensitive facts over the CFGs from :mod:`cfg`.

Two small dataflow analyses, each one lattice and one transfer function:

* **Staleness** (may-analysis, powers AWAIT-ATOMICITY): for every local
  name, track whether its value was *derived from shared state* (an
  attribute chain rooted at ``self`` or a node/link/plane-style
  parameter) and whether an ``await`` has interleaved since the value was
  captured.  After an await every shared-derived local is *stale*: the
  loop may have run other tasks that mutated the source, so the cached
  view no longer guards anything.  Re-binding from a fresh read clears
  the fact; an explicit ``# lint: pin[name]`` on the capture line opts a
  deliberate snapshot out (the PR 11 fix pattern — capture a consistency
  cut FIRST, on purpose, then await).

* **Cut ordering** (must-analysis, powers CUT-ORDERING): a boolean
  "watermark captured" fact.  Joins take AND, so an awaited state export
  is only blessed when a capture happened on EVERY path reaching it —
  the INVARIANTS "consistency cuts" law (watermarks first, derived state
  after) as a call-order property.

Both are intraprocedural and deliberately approximate: attribute chains
are matched syntactically, awaits inside one statement are treated as
happening before the statement's binding, and unreachable blocks carry
no facts.  The rules consuming these facts only *fire* on high-signal
shapes (a stale name in a guard position over a shared mutation), which
is what keeps the live-tree false-positive rate at zero.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field, replace
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from .cfg import CFG, Block, awaits_in, build_cfg, is_header

# Parameter names that conventionally carry shared runtime state in this
# codebase (server/replica/persist signatures).  ``self`` is always
# shared.  A local *assignment* to one of these names overrides the
# convention — the env tracks it like any other alias from then on.
SHARED_PARAM_ROOTS = {
    "self", "node", "app", "plane", "link", "server", "srv",
    "ks", "store", "eng", "shard",
}

_PIN_RE = re.compile(r"#\s*lint:\s*pin\[([A-Za-z0-9_*,\s]+)\]")


def pins_by_line(source: str) -> Dict[int, Set[str]]:
    """``# lint: pin[name, ...]`` comments: line -> pinned local names."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _PIN_RE.search(line)
        if m:
            out[i] = {s.strip() for s in m.group(1).split(",") if s.strip()}
    return out


@dataclass(frozen=True)
class VarState:
    """What the analysis knows about one local name.

    ``sources`` empty means "explicitly not shared-derived" (a kill —
    distinct from absent, which falls back to the parameter-name
    convention for chain roots)."""

    sources: FrozenSet[str] = frozenset()
    line: int = 0           # where the value was captured
    stale: bool = False     # an await interleaved since capture
    stale_line: int = 0     # the first such await


Env = Dict[str, VarState]


def _join_states(a: VarState, b: VarState) -> VarState:
    return VarState(
        sources=a.sources | b.sources,
        line=min(x for x in (a.line, b.line) if x) if (a.line or b.line)
        else 0,
        stale=a.stale or b.stale,
        stale_line=min(x for x in (a.stale_line, b.stale_line) if x)
        if (a.stale_line or b.stale_line) else 0,
    )


def join_env(a: Env, b: Env) -> Env:
    out = dict(a)
    for k, v in b.items():
        out[k] = _join_states(out[k], v) if k in out else v
    return out


def _iter_own(node: ast.AST) -> Iterator[ast.AST]:
    """Walk an expression/statement without entering nested scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else ""
    return ""


def shared_chains(expr: ast.AST, env: Env) -> FrozenSet[str]:
    """Shared-state sources an expression's value may derive from:
    attribute chains rooted at ``self``/shared params, plus the sources
    of any alias local the expression reads."""
    out: Set[str] = set()
    for node in _iter_own(expr):
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if not d:
                continue
            root = d.split(".", 1)[0]
            if root in env:
                out |= env[root].sources
            elif root in SHARED_PARAM_ROOTS:
                out.add(d)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in env:
                out |= env[node.id].sources
            elif node.id in SHARED_PARAM_ROOTS and node.id != "self":
                # bare shared param used as a value (e.g. passed along)
                # does not taint by itself — only attribute reads do.
                pass
    return frozenset(out)


def load_names(expr: ast.AST) -> Set[str]:
    return {n.id for n in _iter_own(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def value_used_names(expr: ast.AST) -> Set[str]:
    """Names whose *value* the expression consumes.

    Locals are task-private: the binding itself cannot change across an
    await, only data derived from shared state goes stale.  So two
    usage shapes are exempt:

    * the base of an attribute deref (``meta.needs_full`` reads shared
      state afresh at evaluation time — the local is just a route);
    * ``x is None`` / ``x is not None`` (tests the binding, which no
      interleaved task can touch).

    Everything else — truthiness, comparisons, arithmetic, call
    arguments, subscripting — consumes the possibly-stale value."""
    parent: Dict[int, ast.AST] = {}
    for node in _iter_own(expr):
        for ch in ast.iter_child_nodes(node):
            parent[id(ch)] = node
    out: Set[str] = set()
    for node in _iter_own(expr):
        if not (isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)):
            continue
        p = parent.get(id(node))
        if isinstance(p, ast.Attribute) and p.value is node:
            continue
        if isinstance(p, ast.Compare):
            comps = [p.left] + list(p.comparators)
            if all(isinstance(o, (ast.Is, ast.IsNot)) for o in p.ops) \
                    and any(isinstance(c, ast.Constant)
                            and c.value is None for c in comps):
                continue
        out.add(node.id)
    return out


class FunctionFlow:
    """Staleness dataflow for one (async) function.

    After construction, ``env_at[id(node)]`` holds the environment in
    force just before evaluating ``node``, for every top-level statement,
    every ``if``/``while`` test expression, and every ``for`` header —
    the positions the AWAIT-ATOMICITY rule interrogates."""

    def __init__(self, fn: ast.AST, pins: Optional[Dict[int, Set[str]]]
                 = None) -> None:
        self.fn = fn
        # pins are FUNCTION-scoped: a `# lint: pin[name]` anywhere in
        # the function body pins the name throughout.  Rebinding a
        # deliberately-owned local (a send cursor, an accumulated
        # progress value) happens at many sites; per-line pins would
        # just be the same declaration N times.
        self.pins: Set[str] = set()
        if pins:
            lo = getattr(fn, "lineno", 0)
            hi = getattr(fn, "end_lineno", None) or lo
            for ln, names in pins.items():
                if lo <= ln <= hi:
                    self.pins |= names
        self.cfg = build_cfg(fn)
        self.env_at: Dict[int, Env] = {}
        self._record = False
        self._solve()

    # -- pinning ---------------------------------------------------------

    def _pinned(self, name: str, line: int) -> bool:
        return "*" in self.pins or name in self.pins

    # -- transfer --------------------------------------------------------

    def _stale_all(self, env: Env, line: int) -> Env:
        out: Env = {}
        for k, v in env.items():
            if v.sources and not v.stale:
                out[k] = replace(v, stale=True, stale_line=line)
            else:
                out[k] = v
        return out

    def _apply_awaits(self, node: ast.AST, env: Env) -> Env:
        hits = awaits_in(node)
        if hits:
            env = self._stale_all(env, min(a.lineno for a in hits))
        return env

    def _bind(self, env: Env, target: ast.AST, srcs: FrozenSet[str],
              line: int) -> Env:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                env = self._bind(env, el, srcs, line)
            return env
        if isinstance(target, ast.Starred):
            return self._bind(env, target.value, srcs, line)
        if not isinstance(target, ast.Name):
            return env  # attribute/subscript targets are mutations,
            #             not local bindings — the rules look at those.
        env = dict(env)
        if srcs and not self._pinned(target.id, line):
            env[target.id] = VarState(sources=srcs, line=line)
        else:
            env[target.id] = VarState()  # explicit kill / pinned
        return env

    def _transfer_stmt(self, stmt: ast.stmt, env: Env) -> Env:
        if is_header(stmt):
            node = stmt.node
            if isinstance(node, (ast.For, ast.AsyncFor)):
                if self._record:
                    self.env_at[id(node)] = dict(env)
                srcs = shared_chains(node.iter, env)
                env = self._apply_awaits(node.iter, env)
                if isinstance(node, ast.AsyncFor):
                    env = self._stale_all(env, node.lineno)
                return self._bind(env, node.target, srcs, node.lineno)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if self._record:
                    self.env_at[id(node)] = dict(env)
                for item in node.items:
                    srcs = shared_chains(item.context_expr, env)
                    env = self._apply_awaits(item.context_expr, env)
                    if isinstance(node, ast.AsyncWith):
                        env = self._stale_all(env, node.lineno)
                    if item.optional_vars is not None:
                        env = self._bind(env, item.optional_vars, srcs,
                                         node.lineno)
                return env
            if isinstance(node, ast.ExceptHandler):
                if node.name:
                    env = self._bind(env, ast.Name(id=node.name,
                                                   ctx=ast.Store()),
                                     frozenset(), node.lineno)
                return env
            return env

        if self._record:
            self.env_at[id(stmt)] = dict(env)
        if isinstance(stmt, ast.Assign):
            srcs = shared_chains(stmt.value, env)
            env = self._apply_awaits(stmt, env)
            for t in stmt.targets:
                env = self._bind(env, t, srcs, stmt.lineno)
            return env
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            srcs = shared_chains(stmt.value, env)
            env = self._apply_awaits(stmt, env)
            return self._bind(env, stmt.target, srcs, stmt.lineno)
        if isinstance(stmt, ast.AugAssign):
            # x += ... keeps x's provenance; sources may widen.
            env2 = self._apply_awaits(stmt, env)
            if isinstance(stmt.target, ast.Name):
                extra = shared_chains(stmt.value, env)
                cur = env2.get(stmt.target.id)
                if cur is not None and (cur.sources or extra):
                    env2 = dict(env2)
                    env2[stmt.target.id] = replace(
                        cur, sources=cur.sources | extra)
                elif extra:
                    env2 = dict(env2)
                    env2[stmt.target.id] = VarState(sources=extra,
                                                    line=stmt.lineno)
            return env2
        return self._apply_awaits(stmt, env)

    def _transfer_block(self, blk: Block, env: Env) -> Env:
        for stmt in blk.stmts:
            env = self._transfer_stmt(stmt, env)
        if blk.test is not None:
            if self._record:
                self.env_at[id(blk.test)] = dict(env)
            env = self._apply_awaits(blk.test, env)
        return env

    # -- fixpoint --------------------------------------------------------

    def _solve(self) -> None:
        order = self.cfg.rpo()
        in_env: Dict[int, Env] = {self.cfg.entry.bid: {}}
        changed = True
        rounds = 0
        while changed and rounds < 64:
            changed = False
            rounds += 1
            for blk in order:
                if blk.bid not in in_env:
                    continue
                out = self._transfer_block(blk, in_env[blk.bid])
                for succ in blk.succs:
                    merged = join_env(in_env.get(succ, {}), out) \
                        if succ in in_env else out
                    if merged != in_env.get(succ):
                        in_env[succ] = merged
                        changed = True
        # recording pass at the fixpoint
        self._record = True
        for blk in order:
            if blk.bid in in_env:
                self._transfer_block(blk, in_env[blk.bid])
        self._record = False


# ------------------------------------------------------------- cut ordering

# A "capture" pins the consistency cut: reading the replication
# watermark or the replica record table into a local.
CAPTURE_ATTRS = {"last_uuid", "landed_last_uuid"}
CAPTURE_CALLS = {"records"}

# An "export" derives state that must be consistent WITH that cut; when
# awaited, other tasks can advance the watermark mid-derivation, so the
# capture must already be in hand.
EXPORT_CALLS = {
    "export_batches", "export_batch", "state_digest", "_local_digest",
    "local_digest", "key_count", "export_frames", "collect_digest",
}


def is_capture_stmt(stmt: ast.AST) -> bool:
    if not isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        return False
    value = getattr(stmt, "value", None)
    if value is None:
        return False
    for node in _iter_own(value):
        if isinstance(node, ast.Attribute) and node.attr in CAPTURE_ATTRS:
            return True
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name and name.rsplit(".", 1)[-1] in CAPTURE_CALLS:
                return True
    return False


def export_awaits(node: ast.AST) -> List[Tuple[ast.Await, str]]:
    out: List[Tuple[ast.Await, str]] = []
    for aw in awaits_in(node):
        v = aw.value
        if isinstance(v, ast.Call):
            name = _dotted(v.func)
            term = name.rsplit(".", 1)[-1] if name else \
                getattr(v.func, "attr", "")
            if term in EXPORT_CALLS:
                out.append((aw, term))
    return out


def cut_violations(fn: ast.AST) -> List[Tuple[ast.Await, str]]:
    """Export-awaits reachable on SOME path with no prior watermark /
    record capture.  Empty when the function has no capture at all (it
    is not building a cut) or no awaited export."""
    own = list(_iter_own_body(fn))
    has_capture = any(is_capture_stmt(s) for s in own)
    has_export = any(export_awaits(s)
                     for s in own if not isinstance(s, ast.Await))
    if not has_capture or not has_export:
        return []

    cfg = build_cfg(fn)
    order = cfg.rpo()
    # must-analysis: True = "capture happened on every path here"
    in_f: Dict[int, bool] = {b.bid: True for b in order}
    in_f[cfg.entry.bid] = False
    reachable = {cfg.entry.bid}

    def block_nodes(blk: Block) -> List[ast.AST]:
        nodes: List[ast.AST] = []
        for stmt in blk.stmts:
            nodes.append(stmt.node if is_header(stmt) else stmt)
        if blk.test is not None:
            nodes.append(blk.test)
        return nodes

    changed = True
    rounds = 0
    while changed and rounds < 64:
        changed = False
        rounds += 1
        for blk in order:
            if blk.bid not in reachable:
                continue
            fact = in_f[blk.bid]
            for node in block_nodes(blk):
                if is_capture_stmt(node):
                    fact = True
            for succ in blk.succs:
                if succ not in reachable:
                    reachable.add(succ)
                    changed = True
                if in_f[succ] and not fact:
                    in_f[succ] = False
                    changed = True

    violations: List[Tuple[ast.Await, str]] = []
    seen: Set[int] = set()
    for blk in order:
        if blk.bid not in reachable:
            continue
        fact = in_f[blk.bid]
        for node in block_nodes(blk):
            capture = is_capture_stmt(node)
            if not fact and not capture:
                for aw, term in export_awaits(node):
                    if id(aw) not in seen:
                        seen.add(id(aw))
                        violations.append((aw, term))
            if capture:
                fact = True
    violations.sort(key=lambda v: v[0].lineno)
    return violations


def _iter_own_body(fn: ast.AST) -> Iterator[ast.AST]:
    stack = list(getattr(fn, "body", []))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


# -------------------------------------------------------- mutation shapes

# Method names whose call on a shared chain mutates it.  Kept to
# unambiguous container/state mutators: the AWAIT-ATOMICITY rule only
# consults this inside a suite guarded by a stale read, so precision
# here directly bounds the false-positive rate.
MUTATOR_METHODS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "popleft", "remove", "discard", "clear",
    "put_nowait", "push", "set_result", "set_exception",
}


def shared_mutations(stmts: List[ast.stmt], env: Env
                     ) -> List[Tuple[ast.AST, str]]:
    """Mutations of shared state inside a suite: assignments /
    deletions whose target chain is shared-rooted, and mutator-method
    calls on shared chains."""
    out: List[Tuple[ast.AST, str]] = []

    def chain_of(t: ast.AST) -> str:
        while isinstance(t, ast.Subscript):
            t = t.value
        d = _dotted(t)
        if not d or "." not in d:
            return ""
        root = d.split(".", 1)[0]
        if root in SHARED_PARAM_ROOTS:
            return d
        st = env.get(root)
        if st is not None and st.sources:
            return d
        return ""

    for stmt in stmts:
        for node in _iter_own(stmt):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    got = chain_of(t)
                    if got:
                        out.append((node, got))
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    got = chain_of(t)
                    if got:
                        out.append((node, got))
            elif isinstance(node, ast.Call):
                name = _dotted(node.func)
                if name and "." in name:
                    base, _, meth = name.rpartition(".")
                    if meth in MUTATOR_METHODS and chain_of(node.func):
                        out.append((node, name))
    return out
