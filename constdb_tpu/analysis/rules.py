"""The repo-specific invariants, one Rule per postmortem class.

Each rule's docstring names the production incident / advisor finding it
encodes (full writeups: docs/INVARIANTS.md).  Adding a rule is ~30
lines: subclass `Rule`, scope it in `applies`, emit via `self.finding`,
append to ALL_RULES, drop a seeded violation under
tests/analysis_corpus/<mirrored-dir>/, and re-run
`python -m constdb_tpu.analysis --write-baseline` if the live tree has
pre-existing findings worth tracking instead of fixing.
"""

from __future__ import annotations

import ast

from . import flow
from .cfg import awaits_in
from .core import FileContext, Rule, dotted, own_nodes


def _scoped(ctx: FileContext, *dirs: str) -> bool:
    return any(d in ctx.parts[:-1] for d in dirs)


class AsyncBlockRule(Rule):
    """ASYNC-BLOCK: no blocking calls on the event loop.

    The asyncio loop IS the single-writer exec thread (server/io.py
    module header): one blocking call stalls every client, every replica
    link, and the cron.  Round 5's chaos suite found a blocking replica
    path wedging exactly this way.  Flags `time.sleep`, sync socket
    construction, builtin file IO, `Future.result()` and subprocess
    waits inside `async def` — and inside sync helpers NESTED in an
    async def, which run on the loop when called."""

    name = "ASYNC-BLOCK"
    hint = ("move the blocking work to loop.run_in_executor(...), an "
            "async API, or a worker process; bounded local spill-file "
            "IO may be baselined with a note instead")

    BLOCKING = {
        "time.sleep": "blocks the loop for the full sleep",
        "socket.socket": "sync socket on the event loop",
        "socket.create_connection": "sync connect blocks the loop",
        "open": "sync file IO on the event loop",
        "os.system": "blocks until the child exits",
        "os.popen": "blocks on the child's pipe",
        "subprocess.run": "blocks until the child exits",
        "subprocess.call": "blocks until the child exits",
        "subprocess.check_call": "blocks until the child exits",
        "subprocess.check_output": "blocks until the child exits",
    }

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "server", "replica")

    def check(self, ctx: FileContext):
        for qual, fn, is_async, async_ctx in ctx.functions:
            if not (is_async or async_ctx):
                continue
            where = "async def" if is_async else \
                "sync helper nested in an async def (runs on the loop)"
            for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                why = self.BLOCKING.get(name)
                if why is not None:
                    yield self.finding(
                        ctx, node, qual, name,
                        f"blocking call {name}() inside {where}: {why}")
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "result" and not node.args:
                    yield self.finding(
                        ctx, node, qual, ".result()",
                        f".result() inside {where} blocks the loop until "
                        "the future resolves")


class StagePureRule(Rule):
    """STAGE-PURE: the static twin of the runtime stage/dispatch epoch
    guard (engine/tpu.py `_dispatch_elem_rows`).

    merge_many splits every CRDT family into STAGE (host-only prep, runs
    on the staging pool, possibly concurrently) and DISPATCH (device
    calls + pool bookkeeping, main thread, family order).  A `_stage_*`
    function touching jax/device state races the main thread's dispatch;
    a `_dispatch_*` function doing heavy host staging (`_stacked`,
    `_combine_groups`, `np.stack`) burns the critical path the pipeline
    exists to hide."""

    name = "STAGE-PURE"
    hint = ("STAGE runs on the staging pool: keep it numpy+store-plane "
            "only.  Heavy host prep in DISPATCH belongs in the matching "
            "_stage_* step (returned via the plan dict)")

    DEVICE_MARKERS = {
        "_jax", "_put_state", "_put_batch", "_device_get", "_full",
        "_grow", "_src_state", "_resident_state", "_family_done",
        "_pool_add", "flush", "device_put", "device_get",
    }
    HEAVY_STAGE_CALLS = {"self._stacked", "self._combine_groups",
                         "np.stack", "numpy.stack"}

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "engine")

    def check(self, ctx: FileContext):
        for qual, fn, _is_async, _actx in ctx.functions:
            base = qual.rsplit(".", 1)[-1]
            if base.startswith("_stage"):
                for node in own_nodes(fn):
                    if isinstance(node, ast.Attribute) and \
                            isinstance(node.value, ast.Name) and \
                            node.value.id == "self" and \
                            node.attr in self.DEVICE_MARKERS:
                        yield self.finding(
                            ctx, node, qual, f"self.{node.attr}",
                            f"STAGE step touches device state "
                            f"self.{node.attr} — stages run on the "
                            "staging pool and must stay host-pure")
                    elif isinstance(node, ast.Name) and \
                            node.id in ("jax", "jnp"):
                        yield self.finding(
                            ctx, node, qual, node.id,
                            f"STAGE step references {node.id} — device "
                            "work belongs in the _dispatch_* twin")
            elif base.startswith("_dispatch"):
                for node in own_nodes(fn):
                    if isinstance(node, ast.Call) and \
                            dotted(node.func) in self.HEAVY_STAGE_CALLS:
                        yield self.finding(
                            ctx, node, qual, dotted(node.func),
                            f"DISPATCH step calls {dotted(node.func)} — "
                            "heavy host staging on the critical path the "
                            "pipeline exists to overlap")


class CheckThenMutateRule(Rule):
    """CHECK-THEN-MUTATE: ceilings/invariants are checked BEFORE pool or
    table state mutates — the `_pool_add` bug class (ADVICE.md round 5:
    the int32 src-plane ceiling was checked AFTER appending, leaving a
    half-merged round + orphaned pool entry on overflow).

    In engine code, any `raise` OR `assert` that follows (in source
    order) a mutation of `self._pool_*` / `self._win_*` / the win value
    pool / a store-plane `append_block` within the same function is an
    error: the raise path strands partially-mutated state mid-round.
    `assert` counts double — it is a raise path AND `python -O` strips
    it (the codebase's own rule: a real raise, not an assert, guards
    data loss — engine/tpu.py `_resident_state`)."""

    name = "CHECK-THEN-MUTATE"
    hint = ("compute the expected outcome and raise BEFORE mutating "
            "(the fix applied to _pool_add in PR 1), or flush/roll back "
            "before raising")

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "engine")

    @staticmethod
    def _mutation(node: ast.AST) -> str:
        """Non-empty description when `node` mutates guarded state."""
        def _pool_attr(t: ast.AST) -> str:
            if isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self" \
                    and (t.attr.startswith("_pool_")
                         or t.attr.startswith("_win_")
                         or t.attr == "_val_pool"):
                return f"self.{t.attr}"
            return ""

        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                got = _pool_attr(t)
                if got:
                    return got
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name.endswith(".append_block"):
                return name
            if name in ("self._val_pool.append", "self._pool_add"):
                return name
        return ""

    def check(self, ctx: FileContext):
        for qual, fn, _is_async, _actx in ctx.functions:
            first_mut = None   # (lineno, what)
            events = []        # (lineno, kind, node, what)
            for node in own_nodes(fn):
                what = self._mutation(node)
                if what:
                    events.append((node.lineno, "mut", node, what))
                elif isinstance(node, (ast.Raise, ast.Assert)):
                    kind = "assert" if isinstance(node, ast.Assert) \
                        else "raise"
                    events.append((node.lineno, kind, node, ""))
            events.sort(key=lambda e: e[0])
            for lineno, kind, node, what in events:
                if kind == "mut":
                    if first_mut is None:
                        first_mut = (lineno, what)
                elif first_mut is not None:
                    extra = " (and python -O strips asserts entirely)" \
                        if kind == "assert" else ""
                    yield self.finding(
                        ctx, node, qual, kind,
                        f"{kind} path at line {lineno} follows the "
                        f"mutation of {first_mut[1]} at line "
                        f"{first_mut[0]}: failing here strands "
                        f"partially-mutated engine state{extra}")


class EnvRegistryRule(Rule):
    """ENV-REGISTRY: every `CONSTDB_*` env read inside the package goes
    through `conf.py`'s registry helpers and is documented.

    Round 5 grew six tuning knobs read ad hoc across five modules; the
    README table drifted immediately.  conf.ENV_REGISTRY is now the one
    place a knob is declared (the helpers raise on unregistered names at
    runtime; a project-level check pins the registry into the README
    tuning table)."""

    name = "ENV-REGISTRY"
    hint = ("declare the variable in conf.ENV_REGISTRY, read it via "
            "conf.env_str/env_int/env_float/env_flag, and add it to the "
            "README Tuning table")

    READS = {"os.environ.get", "environ.get", "os.getenv",
             "os.environ.setdefault", "environ.setdefault"}
    HELPERS = {"env_str", "env_int", "env_float", "env_flag", "env_raw"}

    def __init__(self) -> None:
        self._registry: set | None = None

    def applies(self, ctx: FileContext) -> bool:
        return ctx.basename != "conf.py"

    def registry(self) -> set:
        if self._registry is None:
            from .. import conf
            self._registry = set(conf.ENV_REGISTRY)
        return self._registry

    @staticmethod
    def _const_env_name(node: ast.AST) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith("CONSTDB_"):
            return node.value
        return ""

    def check(self, ctx: FileContext):
        # qualname stays "" for this rule: the env-var name IS the
        # stable identity (path + token), wherever in the file it moves
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and node.args:
                name = dotted(node.func)
                env = self._const_env_name(node.args[0])
                if not env:
                    continue
                if name in self.READS:
                    yield self.finding(
                        ctx, node, "", env,
                        f"direct {name}({env!r}) bypasses the conf.py "
                        "env registry")
                elif name.rsplit(".", 1)[-1] in self.HELPERS and \
                        env not in self.registry():
                    yield self.finding(
                        ctx, node, "", f"{env}:unregistered",
                        f"{env} is read via a conf helper but missing "
                        "from conf.ENV_REGISTRY")
            elif isinstance(node, ast.Subscript) and \
                    dotted(node.value) in ("os.environ", "environ"):
                env = self._const_env_name(node.slice)
                if env:
                    yield self.finding(
                        ctx, node, "", env,
                        f"os.environ[{env!r}] subscript bypasses the "
                        "conf.py env registry")


class ShmLifecycleRule(Rule):
    """SHM-LIFECYCLE: every `SharedMemory(create=True)` is close()d AND
    unlink()ed on all paths.

    A leaked /dev/shm segment survives the process on Linux — N leaked
    merge-job segments at snapshot scale fill the tmpfs and take the box
    down.  The creating function must reference <name>.close() and
    <name>.unlink() from a try handler/finally; creations whose
    ownership legitimately transfers (e.g. the worker export segment,
    freed by the parent's export_free round-trip) carry an inline
    `# lint: ignore[SHM-LIFECYCLE]` with the reason on the spot."""

    name = "SHM-LIFECYCLE"
    hint = ("wrap the segment's population + hand-off in try/except "
            "BaseException: close()+unlink()+raise, or document the "
            "ownership transfer with # lint: ignore[SHM-LIFECYCLE]")

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "parallel")

    def check(self, ctx: FileContext):
        for qual, fn, _a, _c in ctx.functions:
            creations = []  # (node, var)
            trys = []
            for node in own_nodes(fn):
                if isinstance(node, ast.Try):
                    trys.append(node)
                if not isinstance(node, ast.Assign) or \
                        not isinstance(node.value, ast.Call):
                    continue
                call = node.value
                if not dotted(call.func).endswith("SharedMemory"):
                    continue
                if not any(kw.arg == "create"
                           and isinstance(kw.value, ast.Constant)
                           and kw.value.value is True
                           for kw in call.keywords):
                    continue
                t = node.targets[0]
                var = t.id if isinstance(t, ast.Name) else \
                    getattr(t, "attr", "?")
                creations.append((node, var))
            if not creations:
                continue

            def protected(var: str) -> bool:
                want = {f"{var}.close", f"{var}.unlink",
                        f"self.{var}.close", f"self.{var}.unlink"}
                for t in trys:
                    bodies = list(t.finalbody)
                    for h in t.handlers:
                        bodies.extend(h.body)
                    seen = set()
                    for stmt in bodies:
                        for n in ast.walk(stmt):
                            if isinstance(n, ast.Call):
                                seen.add(dotted(n.func))
                    if {f"{var}.close", f"self.{var}.close"} & seen and \
                            {f"{var}.unlink", f"self.{var}.unlink"} & seen:
                        return True
                return False

            for node, var in creations:
                if not protected(var):
                    yield self.finding(
                        ctx, node, qual, var,
                        f"SharedMemory(create=True) assigned to {var!r} "
                        "has no try handler/finally calling both "
                        f"{var}.close() and {var}.unlink() — an error "
                        "between creation and hand-off leaks the "
                        "/dev/shm segment")


class BareExceptRule(Rule):
    """BARE-EXCEPT-SWALLOW: no `except Exception: pass` in the
    replication/apply paths.

    probe_backend() caching failures forever (ADVICE.md round 5) and the
    close-window zombie link (PR 2) both hid behind broad swallowed
    excepts.  In replica/, server/, parallel/ and persist/, a bare /
    Exception / BaseException handler whose body is only `pass` is an
    error — narrow it to the exceptions the cleanup can actually raise,
    or at minimum log.  `__del__` is exempt (raising there is worse)."""

    name = "BARE-EXCEPT-SWALLOW"
    hint = ("narrow to the concrete exceptions (e.g. OSError for fs "
            "cleanup) or log the swallowed error")

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "replica", "server", "parallel", "persist")

    @staticmethod
    def _broad(h: ast.ExceptHandler) -> bool:
        t = h.type
        if t is None:
            return True
        names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
        return any(isinstance(n, ast.Name)
                   and n.id in ("Exception", "BaseException")
                   for n in names)

    @staticmethod
    def _swallows(h: ast.ExceptHandler) -> bool:
        return all(isinstance(s, ast.Pass)
                   or (isinstance(s, ast.Expr)
                       and isinstance(s.value, ast.Constant))
                   for s in h.body)

    def check(self, ctx: FileContext):
        for qual, fn, _a, _c in ctx.functions:
            if qual.rsplit(".", 1)[-1] == "__del__":
                continue
            for node in own_nodes(fn):
                if isinstance(node, ast.ExceptHandler) and \
                        self._broad(node) and self._swallows(node):
                    yield self.finding(
                        ctx, node, qual, "except-pass",
                        "broad except swallowing every error in a "
                        "replication/apply path hides real failures "
                        "(the probe_backend / zombie-link bug class)")


class ForkCaptureRule(Rule):
    """FORK-CAPTURE: callables crossing the process-pool boundary are
    module-level functions, and their args are plain data.

    host_pool workers are forkserver children: a lambda / closure /
    bound method as `target=` either fails to pickle or — worse —
    drags a captured KeySpace / engine / event loop across the fork,
    where its native tables and device handles are garbage (the module
    contract: only shard ids and plane payloads cross the boundary)."""

    name = "FORK-CAPTURE"
    hint = ("make the worker entry a module-level function; ship shard "
            "ids + encoded plane bytes, never live store/engine/loop "
            "objects")

    SUSPECT_ARGS = {"store", "ks", "keyspace", "engine", "eng", "node",
                    "app", "loop", "server", "self"}

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "parallel")

    def check(self, ctx: FileContext):
        module_defs = {n.name for n in ast.iter_child_nodes(ctx.tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
        for qual, fn, _a, _c in ctx.functions:
            nested_defs = {n.name for n in own_nodes(fn)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))}
            for node in own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                if not (name == "Process" or name.endswith(".Process")):
                    continue
                for kw in node.keywords:
                    if kw.arg == "target":
                        v = kw.value
                        if isinstance(v, ast.Lambda):
                            yield self.finding(
                                ctx, v, qual, "lambda",
                                "lambda as a process target captures its "
                                "defining scope across the fork")
                        elif isinstance(v, ast.Attribute):
                            yield self.finding(
                                ctx, v, qual, dotted(v) or v.attr,
                                "bound method / attribute as a process "
                                "target drags its instance across the "
                                "fork")
                        elif isinstance(v, ast.Name) and \
                                v.id in nested_defs and \
                                v.id not in module_defs:
                            yield self.finding(
                                ctx, v, qual, v.id,
                                f"nested function {v.id!r} as a process "
                                "target is a closure over the enclosing "
                                "frame")
                    elif kw.arg == "args" and \
                            isinstance(kw.value, (ast.Tuple, ast.List)):
                        for el in kw.value.elts:
                            if isinstance(el, ast.Attribute) and \
                                    isinstance(el.value, ast.Name) and \
                                    el.value.id == "self":
                                yield self.finding(
                                    ctx, el, qual, dotted(el),
                                    f"{dotted(el)} shipped as a worker "
                                    "arg: instance state must not cross "
                                    "the process boundary")
                            elif isinstance(el, ast.Name) and \
                                    el.id in self.SUSPECT_ARGS:
                                yield self.finding(
                                    ctx, el, qual, el.id,
                                    f"{el.id!r} shipped as a worker arg "
                                    "looks like a live store/engine/"
                                    "loop object — only shard ids and "
                                    "plane payloads cross the boundary")


class KeyConfinedRule(Rule):
    """KEY-CONFINED: every command registered for coalescing
    (SERVE_PLANNERS via @serve_plan, COLUMNAR_ENCODERS via @columnar,
    SERVE_READS via @serve_read — the read planner routes, flushes, and
    caches by the first argument alone) must be statically
    first-key-confined.

    Three subsystems silently rely on the convention that a data
    command's keyspace effects are confined to the key in its FIRST
    argument: PR 5's barrier scoping (a barrier invalidates only its
    first-arg key's cached probes), the replication coalescer's
    key-scoped barrier commutes, and PR 10's shard routing (the whole
    command executes inside the worker owning `crc32(items[1]) % N`).
    A handler that resolves a key it did not take as its first argument
    would silently corrupt all three.  The check: the handler's first
    `args.next_bytes()` binding is THE key — every keyspace key
    resolution (`lookup` / `query` / `get_or_create` / `create_key`)
    must take exactly that name as its first argument, and a handler
    with no such binding cannot be proven confined at all.  One level
    of helper delegation (`incr` → `_counter_step(node, ctx, args, 1)`)
    is followed."""

    name = "KEY-CONFINED"
    hint = ("derive the key from the handler's FIRST args.next_bytes() "
            "and resolve only that name — or keep the command off the "
            "coalescing tables (it stays an exact per-command barrier)")

    KEY_RESOLVERS = {"lookup", "query", "get_or_create", "create_key"}
    COALESCE_DECOS = {"serve_plan", "columnar", "serve_read"}

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "server")

    @staticmethod
    def _deco_str_arg(deco: ast.AST, names: set) -> str:
        if isinstance(deco, ast.Call) and \
                dotted(deco.func).rsplit(".", 1)[-1] in names and \
                deco.args and isinstance(deco.args[0], ast.Constant) and \
                isinstance(deco.args[0].value, str):
            return deco.args[0].value
        return ""

    def check(self, ctx: FileContext):
        coalesced: set[str] = set()
        handlers: dict[str, tuple] = {}   # cmd name -> (qualname, fn)
        module_fns: dict[str, tuple] = {}  # fn name -> (qualname, fn)
        for qual, fn, _a, _c in ctx.functions:
            if "." not in qual:
                module_fns[qual] = (qual, fn)
            for deco in getattr(fn, "decorator_list", ()):
                got = self._deco_str_arg(deco, self.COALESCE_DECOS)
                if got:
                    coalesced.add(got)
                got = self._deco_str_arg(deco, {"register"})
                if got:
                    handlers[got] = (qual, fn)
        for cmd in sorted(coalesced):
            ent = handlers.get(cmd)
            if ent is None:
                continue  # registered elsewhere; runtime assert covers it
            yield from self._check_fn(ctx, cmd, *ent, module_fns, hops=2)

    def _check_fn(self, ctx: FileContext, cmd: str, qual: str, fn: ast.AST,
                  module_fns: dict, hops: int):
        key_var = None
        nodes = sorted(own_nodes(fn),
                       key=lambda n: getattr(n, "lineno", 0))
        for node in nodes:
            if key_var is None and isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    dotted(node.value.func) == "args.next_bytes" and \
                    node.targets and isinstance(node.targets[0], ast.Name):
                key_var = node.targets[0].id
                continue
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in self.KEY_RESOLVERS and node.args:
                a0 = node.args[0]
                if key_var is None:
                    yield self.finding(
                        ctx, node, qual, cmd,
                        f"coalesced command {cmd!r} resolves a key via "
                        f".{f.attr}(...) before any args.next_bytes() "
                        "binding — first-key confinement is not "
                        "statically derivable")
                elif not (isinstance(a0, ast.Name) and a0.id == key_var):
                    yield self.finding(
                        ctx, node, qual, cmd,
                        f"coalesced command {cmd!r} resolves "
                        f"{ast.unparse(a0)!r} "
                        f"via .{f.attr}(...) but its first-argument key "
                        f"binding is {key_var!r} — the shard router and "
                        "barrier scoping both assume first-key "
                        "confinement")
        if key_var is not None or hops <= 0:
            return
        # no key binding in this body: follow one delegation hop — a
        # call passing `args` through to a module-level helper
        for node in nodes:
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Name) and \
                    node.func.id in module_fns and \
                    any(isinstance(a, ast.Name) and a.id == "args"
                        for a in node.args):
                dq, dfn = module_fns[node.func.id]
                yield from self._check_fn(ctx, cmd, dq, dfn, module_fns,
                                          hops - 1)
                return
        yield self.finding(
            ctx, fn, qual, cmd,
            f"coalesced command {cmd!r} has no args.next_bytes() key "
            "binding and no args-delegating helper — first-key "
            "confinement is not statically derivable")


class AwaitAtomicityRule(Rule):
    """AWAIT-ATOMICITY: a shared-state read cached across an await must
    not guard a mutation — the bug class behind three shipped races
    (PR 2 close-window link sweep, PR 11 consistency cut, PR 12 quiesce
    done-callback).

    Flow-sensitive (analysis/cfg.py + analysis/flow.py): the dataflow
    engine tracks which locals are derived from shared node/link/plane
    state and marks them stale at every await point the CFG says can
    interleave before their use.  The rule fires only on the high-signal
    shape: a STALE local in a guard position (an `if`/`while` test or a
    `for` iterable) over a suite that mutates shared state.  Re-reading
    after the await clears the fact; a deliberate pre-await snapshot
    (the PR 11 fix captures the cut FIRST on purpose) is declared with
    `# lint: pin[name]` on the capture line."""

    name = "AWAIT-ATOMICITY"
    hint = ("re-read the shared state after the await (other tasks ran "
            "there), or declare a deliberate pre-await snapshot with "
            "# lint: pin[name] on the capture line")

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "server", "replica", "persist", "parallel")

    def check(self, ctx: FileContext):
        pins = flow.pins_by_line(ctx.source)
        for qual, fn, is_async, _actx in ctx.functions:
            if not is_async:
                continue
            if not any(isinstance(n, ast.Await) for n in own_nodes(fn)):
                continue
            fa = flow.FunctionFlow(fn, pins)
            for node in own_nodes(fn):
                if isinstance(node, (ast.If, ast.While)):
                    env = fa.env_at.get(id(node.test))
                    if env is None:
                        continue
                    suites = list(node.body) + list(node.orelse)
                    yield from self._guard(ctx, qual, node, node.test,
                                           env, suites, "test")
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    env = fa.env_at.get(id(node))
                    if env is None:
                        continue
                    yield from self._guard(ctx, qual, node, node.iter,
                                           env, list(node.body), "iterable")

    def _guard(self, ctx, qual, node, expr, env, suites, where):
        muts = None
        # only VALUE usages can be stale: locals are task-private, so
        # deref bases and `is None` binding tests read fresh state
        for nm in sorted(flow.value_used_names(expr)):
            st = env.get(nm)
            if st is None or not st.sources or not st.stale:
                continue
            if muts is None:
                muts = flow.shared_mutations(suites, env)
            if not muts:
                return
            src = ", ".join(sorted(st.sources)[:2])
            mut_what = muts[0][1]
            yield self.finding(
                ctx, node, qual, nm,
                f"local {nm!r} (from {src}, line {st.line}) is read in "
                f"this {where} after the await at line {st.stale_line} "
                f"and guards a mutation of {mut_what} — tasks "
                "interleaving at that await can invalidate the cached "
                "view (the close-window / quiesce-callback race shape)")


class SlotEpochRule(AwaitAtomicityRule):
    """SLOT-EPOCH: AWAIT-ATOMICITY specialized to the slot table.

    Slot ownership is epoch-versioned and every migration await is an
    ownership-flap window: the peer can FINALIZE, gossip a newer table,
    or the local node can adopt one over CLUSTERTAB while a coroutine
    sleeps.  A local derived from ``*.cluster`` / slot-table state that
    goes stale across an await must therefore not guard a mutation —
    the handler has to re-read ``cl.epoch`` (or compare against the
    live table) after the await before it flips ownership, pops a
    migrating/importing entry, or adopts a watermark.  Same dataflow
    engine as AWAIT-ATOMICITY; this rule narrows the sources to the
    cluster plane and extends coverage to ``cluster/``, which the
    general rule deliberately leaves to this specialization."""

    name = "SLOT-EPOCH"
    hint = ("re-validate the slot-table epoch after the await "
            "(compare cl.epoch, not a pre-await copy) before mutating "
            "ownership; a deliberate pre-handoff snapshot is declared "
            "with # lint: pin[name] on the capture line")

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "cluster", "server", "replica")

    def _guard(self, ctx, qual, node, expr, env, suites, where):
        muts = None
        for nm in sorted(flow.value_used_names(expr)):
            st = env.get(nm)
            if st is None or not st.sources or not st.stale:
                continue
            if not any("cluster" in s for s in st.sources):
                continue
            if muts is None:
                muts = flow.shared_mutations(suites, env)
            if not muts:
                return
            src = ", ".join(sorted(st.sources)[:2])
            mut_what = muts[0][1]
            yield self.finding(
                ctx, node, qual, nm,
                f"local {nm!r} caches slot-table state ({src}, line "
                f"{st.line}) across the await at line {st.stale_line} "
                f"and guards a mutation of {mut_what} — a FINALIZE or "
                "CLUSTERTAB adoption interleaving there bumps the epoch "
                "and invalidates the cached ownership view")


class LockDisciplineRule(Rule):
    """LOCK-DISCIPLINE: lock windows and the event loop don't mix.

    Two directions, one per lock flavor:
    * a SYNC `with <...>_lock:` body containing an `await` parks the
      thread lock across an arbitrary number of scheduler turns — every
      other thread contending on it (the keyspace `_crc_lock` protects
      merge-worker CRC reads) stalls for as long as the loop pleases,
      and re-entry through the same coroutine path self-deadlocks;
    * an ASYNC `with <...>_lock:` body making blocking sync calls
      (file IO, sleeps, `.result()`) wedges the loop while holding the
      lock, so every waiter behind it (the `_stream_lock` serializes
      snapshot streams against spill downloads) is wedged too — spill
      IO belongs in run_in_executor, like link._stream_file does."""

    name = "LOCK-DISCIPLINE"
    hint = ("keep thread-lock bodies synchronous (snapshot the data, "
            "release, then await), and move blocking IO under asyncio "
            "locks to loop.run_in_executor(...)")

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "server", "replica", "store", "persist",
                       "parallel")

    @staticmethod
    def _lock_names(node: ast.AST) -> list[str]:
        out = []
        for item in node.items:
            name = dotted(item.context_expr)
            if name and name.rsplit(".", 1)[-1].endswith("_lock"):
                out.append(name)
        return out

    def check(self, ctx: FileContext):
        for qual, fn, _is_async, _actx in ctx.functions:
            for node in own_nodes(fn):
                if isinstance(node, ast.With):
                    for lock in self._lock_names(node):
                        hits = [a for s in node.body for a in awaits_in(s)]
                        if hits:
                            yield self.finding(
                                ctx, hits[0], qual, lock,
                                f"await inside the sync `with {lock}:` "
                                "window parks the thread lock across "
                                "scheduler turns — contending threads "
                                "stall and re-entry self-deadlocks")
                elif isinstance(node, ast.AsyncWith):
                    for lock in self._lock_names(node):
                        yield from self._blocking_in(ctx, qual, lock,
                                                     node.body)

    def _blocking_in(self, ctx, qual, lock, body):
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name in AsyncBlockRule.BLOCKING:
                yield self.finding(
                    ctx, node, qual, f"{lock}:{name}",
                    f"blocking call {name}() while holding the "
                    f"asyncio lock {lock} wedges the loop AND every "
                    "waiter queued on the lock — run it in an "
                    "executor")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "result" and not node.args:
                yield self.finding(
                    ctx, node, qual, f"{lock}:.result()",
                    f".result() while holding the asyncio lock "
                    f"{lock} blocks the loop with the lock held")


class CutOrderingRule(Rule):
    """CUT-ORDERING: watermark/record capture precedes any awaited state
    export in the same function — the INVARIANTS "consistency cuts" law
    (PR 11: a digest awaited BEFORE the replication watermark was read
    described a cut no replica could ever converge to, because writes
    landing during the await advanced the watermark past the digest).

    Must-analysis over the CFG (analysis/flow.py cut_violations): an
    awaited export (`export_batches`, `state_digest`, `key_count`, ...)
    is flagged when some path reaches it with NO prior capture of
    `last_uuid`/`landed_last_uuid`/`.records()`.  Functions that never
    capture a watermark are not building a cut and stay out of scope."""

    name = "CUT-ORDERING"
    hint = ("capture the watermark/record cut into locals FIRST, then "
            "await the derived exports (the PR 11 fix ordering: "
            "watermarks first, digest after)")

    def applies(self, ctx: FileContext) -> bool:
        return _scoped(ctx, "server", "replica", "persist", "bin")

    def check(self, ctx: FileContext):
        for qual, fn, is_async, _actx in ctx.functions:
            if not is_async:
                continue
            for aw, term in flow.cut_violations(fn):
                yield self.finding(
                    ctx, aw, qual, term,
                    f"awaited export {term}() is reachable before the "
                    "watermark/record capture in this function — writes "
                    "landing during the await advance the watermark "
                    "past the exported state, describing a cut no "
                    "replica can converge to")


class NativeContractRule(Rule):
    """NATIVE-CONTRACT: the C intake stage's command table and the Python
    serve registries never drift apart.

    native/intake.cpp classifies client commands by a frozen opcode
    table; server/serve.py dispatches those opcodes straight into the
    planners.  A command registered for coalescing (@serve_plan /
    @serve_read) that the C table does not know silently loses its fast
    path (OTHER opcode, per-command execution inside a planned run —
    correct but quietly slow, the exact drift this PR's table froze);
    worse, a table entry with no runtime planner would mean the C side
    claims a command serve.py cannot plan.  Both directions are checked
    against the marker block intake.cpp carries for this purpose
    (NATIVE-INTAKE-TABLE-BEGIN/END): every decorated command name must
    appear in the table's `native`/`native-reads` rows or be listed
    `python-only` with a reason; every `native`/`native-reads` entry
    must exist in the runtime SERVE_PLANNERS/COLUMNAR_ENCODERS/
    SERVE_READS registries."""

    name = "NATIVE-CONTRACT"
    hint = ("add the command to the native/intake.cpp marker table "
            "(native:/native-reads: if the C scanner classifies it, "
            "python-only: with the opcode left to the pure path "
            "otherwise) and keep the C classify() switch in step — or "
            "drop the stale table entry")

    DECOS = {"serve_plan", "serve_read"}

    @staticmethod
    def _register_info(deco: ast.AST):
        """(name, is_ctrl, keyless) for an ``@register("x", FLAGS,
        families=...)`` decorator, else None.  is_ctrl: the flags
        expression names CMD_CTRL.  keyless: families is declared an
        EMPTY tuple/list (default = all families = first-key-confined,
        so only an explicit () opts a command out of key routing)."""
        if not (isinstance(deco, ast.Call)
                and isinstance(deco.func, ast.Name)
                and deco.func.id == "register"
                and deco.args
                and isinstance(deco.args[0], ast.Constant)
                and isinstance(deco.args[0].value, str)):
            return None
        is_ctrl = any(isinstance(n, ast.Name) and n.id == "CMD_CTRL"
                      for a in deco.args[1:]
                      for n in ast.walk(a))
        fam = None
        if len(deco.args) > 2:
            fam = deco.args[2]
        for kw in deco.keywords:
            if kw.arg == "families":
                fam = kw.value
        keyless = isinstance(fam, (ast.Tuple, ast.List)) and not fam.elts
        return deco.args[0].value, is_ctrl, keyless

    def __init__(self) -> None:
        self._table: tuple | None = None
        self._registry: set | None = None
        self._aof_table: tuple | None = None

    def applies(self, ctx: FileContext) -> bool:
        if ctx.basename == "commands.py" and _scoped(ctx, "server"):
            return True
        return ctx.basename == "oplog.py" and _scoped(ctx, "persist")

    def table(self) -> tuple:
        """(found, native, native_reads, python_only) from the marker
        block in native/intake.cpp — resolved from the real source tree
        (the table is repo state, like conf.ENV_REGISTRY for
        ENV-REGISTRY), so corpus mirrors are checked against the same
        contract the live tree is."""
        if self._table is None:
            import os
            import re
            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            path = os.path.join(root, "native", "intake.cpp")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                src = ""
            m = re.search(r"NATIVE-INTAKE-TABLE-BEGIN(.*?)"
                          r"NATIVE-INTAKE-TABLE-END", src, re.S)
            sets: dict[str, set] = {"native": set(), "native-reads": set(),
                                    "python-only": set()}
            if m:
                for line in m.group(1).splitlines():
                    line = line.strip().lstrip("/").strip()
                    for label, dst in sets.items():
                        if line.startswith(label + ":"):
                            dst.update(line[len(label) + 1:].split())
            self._table = (m is not None, sets["native"],
                           sets["native-reads"], sets["python-only"])
        return self._table

    def registry(self) -> set:
        """Runtime command names (str) across the three coalescing
        registries, imported lazily like ENV-REGISTRY's conf read."""
        if self._registry is None:
            from ..server import commands as C
            self._registry = {k.decode() for k in C.SERVE_PLANNERS} | \
                {k.decode() for k in C.COLUMNAR_ENCODERS} | \
                {k.decode() for k in C.SERVE_READS}
        return self._registry

    def aof_table(self) -> tuple:
        """(found, {record-name: int}) from the NATIVE-AOF-TABLE marker
        block in native/aof.cpp (added in PR 17 — the disk-format twin
        of the intake command table)."""
        if self._aof_table is None:
            import os
            import re
            root = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            path = os.path.join(root, "native", "aof.cpp")
            try:
                with open(path, "r", encoding="utf-8") as f:
                    src = f.read()
            except OSError:
                src = ""
            m = re.search(r"NATIVE-AOF-TABLE-BEGIN(.*?)"
                          r"NATIVE-AOF-TABLE-END", src, re.S)
            types: dict[str, int] = {}
            if m:
                for line in m.group(1).splitlines():
                    line = line.strip().lstrip("/").strip()
                    if line.startswith("record-types:"):
                        for pair in line[len("record-types:"):].split():
                            name, _, val = pair.partition("=")
                            if name and val.isdigit():
                                types[name] = int(val)
            self._aof_table = (m is not None, types)
        return self._aof_table

    @staticmethod
    def _rec_constants(ctx: FileContext) -> dict[str, tuple[int, ast.AST]]:
        """Module-level `REC_<NAME> = <int>` bindings of the checked
        file, keyed by the lowercased record name."""
        out: dict[str, tuple[int, ast.AST]] = {}
        for node in ast.iter_child_nodes(ctx.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id.startswith("REC_") \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, int):
                name = node.targets[0].id[len("REC_"):].lower()
                out[name] = (node.value.value, node)
        return out

    def _check_aof(self, ctx: FileContext):
        found, types = self.aof_table()
        if not found:
            yield self.finding(
                ctx, ctx.tree, "", "aof-table-missing",
                "native/aof.cpp has no NATIVE-AOF-TABLE marker block — "
                "the C record-type contract cannot be checked")
            return
        consts = self._rec_constants(ctx)
        # direction 1: every Python record type the C table knows, with
        # the same wire value
        for name, (val, node) in sorted(consts.items()):
            if name not in types:
                yield self.finding(
                    ctx, node, "", f"aof:{name}:missing-from-table",
                    f"REC_{name.upper()}={val} has no entry in the "
                    "native/aof.cpp record-type table — the C scanner's "
                    "crc gate rejects the record as corruption")
            elif types[name] != val:
                yield self.finding(
                    ctx, node, "", f"aof:{name}:drift",
                    f"REC_{name.upper()}={val} but native/aof.cpp "
                    f"declares {name}={types[name]} — the two sides "
                    "would classify each other's records as corrupt")
        # direction 2: every C record type has a Python twin
        for name, val in sorted(types.items()):
            if name not in consts:
                yield self.finding(
                    ctx, ctx.tree, "", f"aof:{name}:unknown-record-type",
                    f"native/aof.cpp record type {name}={val} has no "
                    f"REC_{name.upper()} constant here — the Python "
                    "decoder cannot replay what the C scanner emits")

    def check(self, ctx: FileContext):
        if ctx.basename == "oplog.py":
            yield from self._check_aof(ctx)
            return
        found, native, reads, pyonly = self.table()
        if not found:
            yield self.finding(
                ctx, ctx.tree, "", "intake-table-missing",
                "native/intake.cpp has no NATIVE-INTAKE-TABLE marker "
                "block — the C intake contract cannot be checked")
            return
        covered = native | reads | pyonly
        # direction 1: every command THIS file registers for coalescing
        # is accounted for in the C table
        for qual, fn, _a, _c in ctx.functions:
            for deco in getattr(fn, "decorator_list", ()):
                got = KeyConfinedRule._deco_str_arg(deco, self.DECOS)
                if got and got not in covered:
                    yield self.finding(
                        ctx, deco, qual, got,
                        f"command {got!r} is registered for coalescing "
                        "but absent from the native/intake.cpp table — "
                        "the C scanner demotes it to OTHER silently "
                        "(declare it native/native-reads with a C "
                        "classify() arm, or python-only with a reason)")
        # direction 2: every command the C table claims to classify has
        # a runtime planner/encoder/read-spec behind its opcode
        for entry in sorted(native | reads):
            if entry not in self.registry():
                yield self.finding(
                    ctx, ctx.tree, "", f"{entry}:stale",
                    f"native/intake.cpp table lists {entry!r} but no "
                    "runtime planner/encoder/read-spec is registered "
                    "under that name — the C scanner would emit an "
                    "opcode serve.py cannot plan")
        # direction 3 (cluster): every native-table command must be
        # slot-routable.  The router keys off the first argument
        # (shard_routable: not CMD_CTRL, non-empty families), and the
        # native fast path trusts that the redirect demotion in
        # serve.py can always extract that key from the scanned
        # payload.  A native/native-reads entry registered CMD_CTRL or
        # with families=() would take the C fast path yet be invisible
        # to the router — in cluster mode the two planes disagree on
        # where the command runs.
        for qual, fn, _a, _c in ctx.functions:
            for deco in getattr(fn, "decorator_list", ()):
                info = self._register_info(deco)
                if info is None:
                    continue
                nm, is_ctrl, keyless = info
                if nm in (native | reads) and (is_ctrl or keyless):
                    why = "CMD_CTRL" if is_ctrl else "families=()"
                    yield self.finding(
                        ctx, deco, qual, f"{nm}:unroutable",
                        f"command {nm!r} is in the native/intake.cpp "
                        f"fast-path table but registered {why} — the "
                        "slot router (cluster/slots.py) skips it while "
                        "the C scanner still classifies it, so cluster "
                        "mode would execute it on a non-owner (move it "
                        "to python-only:, or make it first-key-"
                        "confined)")


ALL_RULES: list[Rule] = [
    AsyncBlockRule(),
    StagePureRule(),
    CheckThenMutateRule(),
    EnvRegistryRule(),
    ShmLifecycleRule(),
    BareExceptRule(),
    ForkCaptureRule(),
    KeyConfinedRule(),
    NativeContractRule(),
    AwaitAtomicityRule(),
    SlotEpochRule(),
    LockDisciplineRule(),
    CutOrderingRule(),
]
