"""Process pool for hash-sharded host merge work.

The PR-1 pipeline overlapped host staging with device compute, but every
staged byte was still produced by ONE Python process — BENCH_r06 shows the
10M-key merge spending ~54s of a 62.5s wall in single-threaded host work
(cnt/el staging + flush apply) while the device link sits ~98% idle.  Slots,
counter ranks, and set members are independent across keys (per-key CRDT
merges commute), so the host side shards embarrassingly by key hash.

This module runs N shard WORKERS, each a separate process owning one
`KeySpace` + `MergeEngine` pair, so staging, native-table assigns, and
flush apply all scale with cores instead of fighting the GIL:

  * workers come from a **forkserver** context: they are forked from a
    clean helper process, never from the (possibly JAX-threaded) parent —
    forking a JAX-threaded process can deadlock the child;
  * batch planes cross the process boundary via **shared-memory buffers**
    (one segment per job, holding the snapshot-codec encoding of every
    chunk in the group plus its per-key shard-id column), not pickle; all
    N workers map the SAME segment and each extracts only its shard's
    rows — the parent does zero per-row split work;
  * completions stream back asynchronously over per-worker pipes; the
    parent consumes them as they land (`reap`) and enforces a bounded
    in-flight window, the process-level analogue of PR 1's double
    buffering.

Control messages (flush / canonical / state_bytes / …) ride the same pipes
after a barrier, so replies never interleave with merge acks.
"""

from __future__ import annotations

import os
import traceback
from typing import Optional


def _attach_shm(name: str):
    """Open an existing shared-memory segment.  Forkserver children share
    the parent's resource tracker, so the attach-side registration is a
    set-level no-op and exactly one unregister fires at unlink time —
    no extra bookkeeping needed (and explicitly unregistering here would
    strip the parent's registration, making its unlink() warn)."""
    from multiprocessing import shared_memory

    return shared_memory.SharedMemory(name=name)


def _make_engine(spec: str):
    """Engine factory by spec string (must stay import-lazy: "cpu"
    workers never pay a JAX import).  CONSTDB_SHARD_FOLD carries the
    dense-fold strategy across the process boundary (workers can't take
    a closure), so e.g. bench.py's CONSTDB_BENCH_FOLD stays honored
    under --shards instead of silently reverting to "auto"."""
    if spec == "cpu":
        from ..engine.cpu import CpuMergeEngine
        return CpuMergeEngine()
    from ..conf import env_str
    fold = env_str("CONSTDB_SHARD_FOLD", "auto")
    if spec in ("tpu", "tpu-resident"):
        from ..engine.tpu import TpuMergeEngine
        return TpuMergeEngine(resident=True, dense_fold=fold)
    if spec == "tpu-nonresident":
        from ..engine.tpu import TpuMergeEngine
        return TpuMergeEngine(resident=False, dense_fold=fold)
    raise ValueError(f"unknown shard engine spec {spec!r}")


def _worker_main(conn, shard: int, n_shards: int, engine_spec: str,
                 env: dict) -> None:
    """Shard worker loop: one KeySpace + one lazily-built MergeEngine."""
    # env BEFORE any jax import: the parent's platform pins (JAX_PLATFORMS
    # etc.) were captured at pool creation, which may post-date the
    # forkserver's inherited environment
    os.environ.update(env)
    from ..engine.base import batch_from_keyspace
    from ..persist.snapshot import (_decode_batch, _encode_batch,
                                    _read_bytes_list)
    from ..store.keyspace import KeySpace
    from ..store.sharded_keyspace import (extract_shard,
                                          keyspace_state_bytes, shard_ids)
    from ..utils.varint import VarintReader

    store = KeySpace()
    engine = None
    export_shm = None  # last export segment, freed on "export_free"

    def ensure_engine():
        nonlocal engine
        if engine is None:
            engine = _make_engine(engine_spec)
        return engine

    def flushed_store():
        if engine is not None and getattr(engine, "needs_flush", False):
            engine.flush(store)
        return store

    while True:
        try:
            msg = conn.recv()
        except EOFError:
            break
        cmd = msg[0]
        try:
            if cmd == "merge":
                _, jid, shm_name, planes, entries = msg
                shm = _attach_shm(shm_name)
                try:
                    buf = shm.buf
                    # shared bytes planes (keys / members) decode ONCE
                    # per job, however many replica chunks reference them
                    plane_cache: dict = {}

                    def plane(pid):
                        got = plane_cache.get(pid)
                        if got is None:
                            o, ln = planes[pid]
                            r = VarintReader(bytes(buf[o:o + ln]))
                            got = _read_bytes_list(r, r.uvarint())
                            plane_cache[pid] = got
                        return got

                    sid_cache: dict = {}  # key token -> shard column
                    ex_memo: dict = {}    # extract_shard's plane memo
                    subs = []
                    for off, plen, tok_k, tok_e, hv, kpid, epid in entries:
                        b = _decode_batch(
                            bytes(buf[off:off + plen]),
                            keys=plane(kpid) if kpid >= 0 else None,
                            el_member=plane(epid) if epid >= 0 else None)
                        b.key_shape = tok_k
                        b.el_shape = tok_e
                        b.el_has_vals = hv
                        # hash once per shared key plane; N workers hash
                        # in parallel (the parent ships only bytes)
                        sids = sid_cache.get(tok_k) if tok_k is not None \
                            else None
                        if sids is None:
                            sids = shard_ids(b.keys, n_shards)
                            if tok_k is not None:
                                sid_cache[tok_k] = sids
                        dsids = shard_ids(b.del_keys, n_shards) \
                            if b.del_keys else None
                        sub = extract_shard(b, sids, dsids, shard,
                                            memo=ex_memo)
                        if sub.n_rows or sub.del_keys:
                            subs.append(sub)
                finally:
                    shm.close()
                rows = sum(s.n_rows for s in subs)
                if subs:
                    ensure_engine().merge_many(store, subs)
                conn.send(("done", jid, {"rows": rows}))
            elif cmd == "flush":
                flushed_store()
                conn.send(("ok", None))
            elif cmd == "canonical":
                conn.send(("ok", flushed_store().canonical(keys=msg[1])))
            elif cmd == "state_bytes":
                conn.send(("ok", keyspace_state_bytes(flushed_store())))
            elif cmd == "export":
                # whole-shard columnar state (consolidation): encoded with
                # the snapshot codec into a worker-owned shm segment; the
                # parent copies it out then sends "export_free"
                from multiprocessing import shared_memory
                payload = bytes(_encode_batch(
                    batch_from_keyspace(flushed_store())))
                # ownership transfers across messages BY DESIGN: the
                # parent copies the segment out, then sends export_free,
                # whose branch below close()s + unlink()s it; a crashed
                # worker's segment is reclaimed by the shared resource
                # tracker at exit.  # lint: ignore[SHM-LIFECYCLE]
                export_shm = shared_memory.SharedMemory(
                    create=True, size=max(len(payload), 1))
                export_shm.buf[: len(payload)] = payload
                conn.send(("ok", (export_shm.name, len(payload))))
            elif cmd == "export_free":
                if export_shm is not None:
                    export_shm.close()
                    export_shm.unlink()
                    export_shm = None
                conn.send(("ok", None))
            elif cmd == "secs":
                conn.send(("ok", {
                    "family_secs": dict(getattr(engine, "family_secs",
                                                {}) or {}),
                    "stage_secs": dict(getattr(engine, "stage_secs",
                                               {}) or {}),
                    "bytes_h2d": getattr(engine, "bytes_h2d", 0),
                    "bytes_d2h": getattr(engine, "bytes_d2h", 0),
                    "folds": getattr(engine, "folds", 0),
                    "dev_rounds_resident": getattr(engine,
                                                   "dev_rounds_resident", 0),
                    "host_micro_rounds": getattr(engine,
                                                 "host_micro_rounds", 0),
                    "flush_rows_downloaded": getattr(
                        engine, "flush_rows_downloaded", 0),
                    "flush_rows_full_equiv": getattr(
                        engine, "flush_rows_full_equiv", 0),
                }))
            elif cmd == "memory":
                conn.send(("ok", flushed_store().memory_report()))
            elif cmd == "reset":
                if engine is not None and hasattr(engine, "close"):
                    engine.close()
                if engine is not None and \
                        hasattr(engine, "discard_resident"):
                    engine.discard_resident()
                store = KeySpace()
                engine = None
                conn.send(("ok", None))
            elif cmd == "close":
                break
            else:
                raise ValueError(f"unknown pool command {cmd!r}")
        except BaseException:
            try:
                conn.send(("err", msg[1] if cmd == "merge" else None,
                           traceback.format_exc()))
            except (BrokenPipeError, OSError):  # parent already gone
                break
    conn.close()


_ENV_PREFIXES = ("JAX_", "XLA_", "CONSTDB_", "PALLAS_", "TPU_")


def _capture_env() -> dict:
    return {k: v for k, v in os.environ.items()
            if k.startswith(_ENV_PREFIXES)}


class HostShardPool:
    """N forkserver shard workers + shared-memory job transport.

    `submit_group(prepped)` ships one encoded group (see
    `ShardedKeySpace._prep_batch` for the entry layout) to EVERY worker;
    each extracts its own shard.  Submission is asynchronous: acks drain
    through `reap()` and a bounded in-flight window (`max_inflight`
    groups) backpressures the producer — the caller consumes per-shard
    completions as they land instead of barriering per group.
    """

    def __init__(self, n_shards: int, engine_spec: str = "tpu",
                 max_inflight: int = 2, env: Optional[dict] = None,
                 start_method: str = "forkserver"):
        import multiprocessing as mp

        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = n_shards
        self.engine_spec = engine_spec
        self.max_inflight = max(1, max_inflight)
        wenv = _capture_env()
        if env:
            wenv.update(env)
        try:
            ctx = mp.get_context(start_method)
        except ValueError:  # pragma: no cover - non-POSIX
            ctx = mp.get_context("spawn")
        self._conns = []
        self._procs = []
        for s in range(n_shards):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_worker_main,
                            args=(child, s, n_shards, engine_spec, wenv),
                            daemon=True)
            p.start()
            child.close()
            self._conns.append(parent)
            self._procs.append(p)
        self._next_jid = 0
        # jid -> {"acks": remaining, "shm": segment, "pins": refs}
        self._jobs: dict[int, dict] = {}
        self.rows_merged = [0] * n_shards
        self._closed = False

    # ------------------------------------------------------------- submit

    def submit_group(self, planes: list, entries: list,
                     pins: list = ()) -> int:
        """Ship one group.  `planes` is a list of encoded shared bytes
        planes (uvarint count + bytes-list blob), each shipped ONCE and
        referenced by index from the entries; `entries` is a list of
        (payload_bytes, tok_k, tok_e, hv, kpid, epid) where kpid/epid
        index `planes` (-1 = plane embedded in the payload).  `pins`
        holds whatever must stay alive until the job completes (token
        validity).  Blocks (reaping completions) while the in-flight
        window is full."""
        from multiprocessing import shared_memory

        while len(self._jobs) >= self.max_inflight:
            self.reap(block=True)
        total = sum(len(p) for p in planes) + \
            sum(len(e[0]) for e in entries)
        shm = shared_memory.SharedMemory(create=True, size=max(total, 1))
        try:
            # population + registration under a guard: a failure in here
            # (a bad buffer write, a dead worker pipe) would otherwise
            # leak the /dev/shm segment until process exit — from
            # registration onward, reap()/close() own the cleanup
            off = 0
            plane_spans = []
            for p in planes:
                shm.buf[off:off + len(p)] = p
                plane_spans.append((off, len(p)))
                off += len(p)
            wire = []
            for payload, tok_k, tok_e, hv, kpid, epid in entries:
                shm.buf[off:off + len(payload)] = payload
                wire.append((off, len(payload), tok_k, tok_e, hv, kpid,
                             epid))
                off += len(payload)
            jid = self._next_jid
            self._next_jid += 1
            self._jobs[jid] = {"acks": self.n_shards, "shm": shm,
                               "pins": list(pins)}
        except BaseException:
            shm.close()
            shm.unlink()
            raise
        for conn in self._conns:
            conn.send(("merge", jid, shm.name, plane_spans, wire))
        return jid

    def reap(self, block: bool = False) -> int:
        """Consume any landed completions; returns how many acks arrived.
        With `block`, waits for at least one."""
        from multiprocessing.connection import wait as conn_wait

        got = 0
        while self._jobs:
            ready = conn_wait(self._conns,
                              None if (block and got == 0) else 0)
            if not ready:
                break
            for conn in ready:
                msg = conn.recv()
                self._handle_ack(self._conns.index(conn), msg)
                got += 1
        return got

    def _handle_ack(self, shard: int, msg) -> None:
        kind = msg[0]
        if kind == "err":
            raise RuntimeError(
                f"shard worker {shard} failed:\n{msg[2]}")
        if kind != "done":
            raise RuntimeError(
                f"unexpected pool reply {msg[0]!r} from shard {shard}")
        jid = msg[1]
        self.rows_merged[shard] += msg[2].get("rows", 0)
        job = self._jobs[jid]
        job["acks"] -= 1
        if job["acks"] == 0:
            job["shm"].close()
            job["shm"].unlink()
            del self._jobs[jid]

    def barrier(self) -> None:
        """Drain every in-flight merge."""
        while self._jobs:
            self.reap(block=True)

    # ------------------------------------------------------ control calls

    def call_all(self, cmd: str, *args) -> list:
        """Barrier, then run one control command on every worker and
        collect the per-shard replies (in shard order)."""
        self.barrier()
        for conn in self._conns:
            conn.send((cmd,) + args)
        out = []
        for s, conn in enumerate(self._conns):
            msg = conn.recv()
            if msg[0] == "err":
                raise RuntimeError(f"shard worker {s} failed:\n{msg[2]}")
            out.append(msg[1])
        return out

    def call_one(self, shard: int, cmd: str, *args):
        self.barrier()
        conn = self._conns[shard]
        conn.send((cmd,) + args)
        msg = conn.recv()
        if msg[0] == "err":
            raise RuntimeError(f"shard worker {shard} failed:\n{msg[2]}")
        return msg[1]

    def export_shard(self, shard: int) -> bytes:
        """Copy one shard's whole-state columnar export out of the
        worker's shared-memory segment."""
        name, size = self.call_one(shard, "export")
        shm = _attach_shm(name)
        try:
            payload = bytes(shm.buf[:size])
        finally:
            shm.close()
        self.call_one(shard, "export_free")
        return payload

    def export_all(self) -> list:
        """Whole-state exports from EVERY shard, with the expensive
        worker-side encodes running concurrently: the export command goes
        to all workers first, then the parent copies each segment out as
        its reply lands (vs export_shard in a loop, which would leave
        N-1 workers idle per round-trip)."""
        self.barrier()
        for conn in self._conns:
            conn.send(("export",))
        out = []
        for s, conn in enumerate(self._conns):
            msg = conn.recv()
            if msg[0] == "err":
                raise RuntimeError(f"shard worker {s} failed:\n{msg[2]}")
            name, size = msg[1]
            shm = _attach_shm(name)
            try:
                out.append(bytes(shm.buf[:size]))
            finally:
                shm.close()
            conn.send(("export_free",))
            ack = conn.recv()
            if ack[0] == "err":  # pragma: no cover - free cannot fail
                raise RuntimeError(f"shard worker {s} failed:\n{ack[2]}")
        return out

    # ---------------------------------------------------------- lifecycle

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for conn in self._conns:
            try:
                conn.send(("close",))
            except (BrokenPipeError, OSError):
                pass
        for p in self._procs:
            p.join(timeout=10)
            if p.is_alive():  # pragma: no cover - hung worker
                p.terminate()
        for conn in self._conns:
            conn.close()
        for job in self._jobs.values():
            try:
                job["shm"].close()
                job["shm"].unlink()
            except OSError:  # pragma: no cover - already gone
                pass
        self._jobs.clear()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass
