from .sharded import make_mesh, sharded_merge_step, shard_batch_arrays

__all__ = ["make_mesh", "sharded_merge_step", "shard_batch_arrays"]
