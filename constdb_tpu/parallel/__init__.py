from .sharded import (engine_mesh, make_mesh, shard_batch_arrays,
                      sharded_merge_step)

__all__ = ["engine_mesh", "make_mesh", "sharded_merge_step",
           "shard_batch_arrays"]
