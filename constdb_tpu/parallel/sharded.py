"""Multi-device sharded CRDT merge: SPMD over a 2D device mesh.

The reference's distributed axes (SURVEY.md §2.7) map onto the mesh as:
  * "rep" — replica parallelism: the R axis of the dense [R, S] merge
    tensors (one row per replica snapshot + the local state) is split
    across devices; per-device partial LWW reductions combine with
    `lax.pmax`/`lax.pmin` collectives — the analogue of data-parallel
    gradient reduction, riding ICI.
  * "kv"  — keyspace parallelism: the slot axis S is range-partitioned
    across devices; slots are independent, so this axis needs no
    collectives (the analogue of sequence/context sharding).

Everything compiles under `jit(shard_map(...))` with static shapes; XLA
inserts the collectives.  Works identically on a virtual CPU mesh
(xla_force_host_platform_device_count) and a real TPU slice.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from ..ops.segment import NEUTRAL_T  # noqa: E402

try:  # jax >= 0.8: top-level function
    from jax import shard_map
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map


def make_mesh(n_devices: Optional[int] = None, rep: int = 1) -> Mesh:
    """A (rep × kv) mesh over the first `n_devices` devices."""
    devs = jax.devices()
    n = n_devices or len(devs)
    if n % rep:
        raise ValueError(f"{n} devices do not factor into rep={rep}")
    grid = np.asarray(devs[:n]).reshape(rep, n // rep)
    return Mesh(grid, ("rep", "kv"))


def engine_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1D ("kv",) mesh for `TpuMergeEngine(mesh=...)`: the production
    merge path range-partitions per-slot state over this axis (batches
    arrive sequentially from the replica links, so the engine's only
    intra-node parallel axis is the keyspace)."""
    devs = jax.devices()
    n = n_devices or len(devs)
    return Mesh(np.asarray(devs[:n]), ("kv",))


def _local_merge(vals, ts, at, an, dt, env):
    """Per-device partial reduction over the local R-shard, then global
    combination over the "rep" mesh axis."""
    # ---- counters: (value, uuid) LWW, max value on uuid tie ----
    t_lmax = ts.max(axis=0)
    T = lax.pmax(t_lmax, "rep")
    v_l = jnp.where(ts == T[None, :], vals, NEUTRAL_T).max(axis=0)
    V = lax.pmax(v_l, "rep")

    # ---- elements: lexicographic (add_t, add_node) + max del_t ----
    at_lmax = at.max(axis=0)
    AT = lax.pmax(at_lmax, "rep")
    an_l = jnp.where(at == AT[None, :], an, NEUTRAL_T).max(axis=0)
    AN = lax.pmax(an_l, "rep")
    DT = lax.pmax(dt.max(axis=0), "rep")
    # winning (replica-global) row index; smallest wins so that row 0 — the
    # local store state, living on rep-shard 0 — is preferred on exact ties
    r_local = at.shape[0]
    winner = (at == AT[None, :]) & (an == AN[None, :])
    local_win = jnp.argmax(winner, axis=0)
    local_has = winner.any(axis=0)
    offset = lax.axis_index("rep") * r_local
    cand = jnp.where(local_has, offset + local_win, jnp.iinfo(jnp.int64).max)
    WIN = lax.pmin(cand, "rep")

    # ---- envelopes: pointwise max over [R, S, 4] ----
    ENV = lax.pmax(env.max(axis=0), "rep")

    # a demo global statistic: slots touched by any replica (psum over both
    # mesh axes would double count "kv" — slots are partitioned, so psum
    # over "kv" after the "rep" reduction gives the true global count)
    touched = jnp.sum(T > NEUTRAL_T)
    total_touched = lax.psum(lax.pmax(touched, "rep"), "kv")

    return V, T, AT, AN, DT, WIN, ENV, total_touched


def sharded_merge_step(mesh: Mesh):
    """Build the jitted SPMD merge step for a mesh.

    Inputs (global shapes): vals/ts [R, S] counters, at/an/dt [R, S]
    elements, env [R, S, 4] envelopes.  R splits over "rep", S over "kv".
    Returns per-slot merged columns (sharded over "kv") plus a replicated
    scalar stat.
    """
    fn = shard_map(
        _local_merge,
        mesh=mesh,
        in_specs=(P("rep", "kv"), P("rep", "kv"), P("rep", "kv"),
                  P("rep", "kv"), P("rep", "kv"), P("rep", "kv", None)),
        out_specs=(P("kv"), P("kv"), P("kv"), P("kv"), P("kv"), P("kv"),
                   P("kv", None), P()),
    )
    # the [R, S] batch stacks are one-shot uploads staged solely for this
    # reduction — donating them lets XLA reuse their HBM for the outputs
    # instead of holding both footprints live across the step
    return jax.jit(fn, donate_argnums=(0, 1, 2, 3, 4, 5))


def shard_batch_arrays(mesh: Mesh, *arrays):
    """Place [R, S] (or [R, S, C]) host arrays onto the mesh with the
    step's input sharding."""
    out = []
    for a in arrays:
        spec = P("rep", "kv") if a.ndim == 2 else P("rep", "kv", None)
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)
