"""Process pool for shard-per-core client serving.

PR 5 coalesced pipelined client chunks through the merge engine, but the
whole client path — parse, plan, merge, reply, repl-log — still ran on
ONE event loop: BENCH_r09 pins serving at ~15 µs/cmd of irreducible
per-command Python on this box, all of it single-core.  Per-key CRDT
state is independent across keys (the same property that made snapshot
merge shard in PR 2), and every data command is first-key-confined (the
KEY-CONFINED lint rule), so the serving hot path shards by key hash too.

This module runs N serve WORKERS, each a separate forkserver process
owning one `Node` (shard keyspace + merge engine + repl-log tap), so
planning, merging, reply computation, and log-entry production all scale
with cores.  The PARENT process stays the authority for everything
global — it accepts connections, parses, routes whole pipelined
sub-chunks per shard, **mints every HLC uuid at route time** (so the
uuid stream is byte-identical to the single-loop path's), owns
membership/replication/GC scheduling, and mirrors each worker's log
entries into that shard's repl-log segment as acks land (see
server/serve_shards.py for the plane and server/repl_log.py
MergedReplLog for the merge-sorted peer stream).

Transport: one pipe per worker.  Requests are small pickled tuples
(serve chunks ship the commands re-encoded as RESP bytes — the native
codec is faster than pickling message trees); replies stream back FIFO
per worker and resolve asyncio futures via a reader thread.  Sends are
SYNCHRONOUS on the event loop — this is load-bearing, not a shortcut:
the parent mints uuids at classification time, and a suspension point
between minting and the pipe write would let another connection's newer
uuids reach the worker first, breaking the per-segment
strictly-increasing contract the merged peer stream rests on.  A send
can only block when the OS pipe buffer is full (natural backpressure);
the reader thread keeps draining replies meanwhile, so it cannot
deadlock.
"""

from __future__ import annotations

import asyncio
import threading
import traceback
from collections import deque
from typing import Optional

from .host_pool import _capture_env, _make_engine


class _TapLog:
    """Worker-side repl-log stand-in: records every locally-replicated
    command for the ack instead of retaining a ring — the authoritative
    segments live in the PARENT (mirrored in ack order).  Keeps the
    strictly-increasing-uuid contract so a routing bug cannot silently
    reorder a shard's stream."""

    __slots__ = ("tap", "last_uuid", "evicted_up_to")

    def __init__(self) -> None:
        self.tap: list = []
        self.last_uuid = 0
        self.evicted_up_to = 0

    def push(self, uuid: int, name: bytes, args: list) -> None:
        if uuid <= self.last_uuid:
            raise ValueError(
                f"shard log uuids must be increasing: {uuid} <= "
                f"{self.last_uuid}")
        self.tap.append((uuid, name, args))
        self.last_uuid = uuid

    def push_many(self, cmds: list) -> None:
        for uuid, name, args in cmds:
            self.push(uuid, name, args)

    def drain(self) -> list:
        out, self.tap = self.tap, []
        return out


def _worker_stats(node) -> dict:
    st = node.stats
    # the sampled plan->land latency ring drains into each ack so the
    # parent's INFO percentiles cover sharded serving too
    lat = list(st.serve_lat)
    st.serve_lat.clear()
    rc = node.read_cache
    return {
        "cmds": st.cmds_processed,
        "repl": st.cmds_replicated,
        "msgs": st.serve_msgs_coalesced,
        "flushes": st.serve_flushes,
        "barriers": st.serve_barriers,
        "apply_barriers": st.repl_apply_barriers,
        "gc_freed": st.gc_freed,
        "keys": node.ks.n_keys(),
        "used_bytes": node.governor.used_memory(),
        "oom_shed": st.oom_shed_writes,
        # the read plane's worker-side gauges (the parent folds the
        # counters into the node totals and publishes the bytes gauge
        # per shard — server/serve_shards.py _fold_stats)
        "reads": st.serve_reads_coalesced,
        "read_flushes": st.serve_read_flushes,
        "cache_hits": rc.hits,
        "cache_misses": rc.misses,
        "cache_inv": rc.invalidations,
        "cache_bytes": rc.bytes,
        "lat": lat,
    }


def _serve_worker_main(conn, shard: int, n_shards: int, engine_spec: str,
                       env: dict, node_id: int, alias: str,
                       serve_batch: int, maxmemory=None,
                       maxmemory_soft_pct=None) -> None:
    """Serve worker loop: one shard-confined Node + ServeCoalescer."""
    import os

    os.environ.update(env)
    import numpy as np

    from ..engine.base import batch_from_keyspace
    from ..persist.snapshot import _decode_batch, _encode_batch
    from ..resp.codec import make_parser
    from ..resp.message import NoReply, as_bytes, as_int
    from ..resp.codec import encode_into
    from ..server.node import Node
    from ..server.serve import ServeCoalescer
    from ..store.sharded_keyspace import keyspace_state_bytes

    node = Node(node_id=node_id, alias=alias,
                engine=_make_engine(engine_spec))
    if maxmemory is not None or maxmemory_soft_pct is not None:
        # each worker governs its slice of the node cap (the plane
        # passed maxmemory // n_shards): the keys are hash-partitioned,
        # so per-shard caps bound the node total while the shed decision
        # stays local to the worker owning the written key
        node.governor.configure(maxmemory, maxmemory_soft_pct)
    # a worker's own gc_horizon would be its LOCAL clock (no peers in
    # its ReplicaManager) — unsound for tombstone collection; the
    # parent cron drives worker GC with the real coverage-gated
    # cluster horizon ("gc" command below), so the hard-watermark
    # reclaim must not sweep on its own (server/overload.py)
    node.governor.reclaim_gc = False
    node.repl_log = _TapLog()
    deleted = [False]

    def wire_ks():
        node.ks.on_key_delete = lambda: deleted.__setitem__(0, True)

    wire_ks()
    coal = ServeCoalescer(node, max_run=serve_batch) if serve_batch > 1 \
        else None
    parser = make_parser()

    while True:
        try:
            msg = conn.recv()
        except EOFError:
            break
        cmd = msg[0]
        try:
            if cmd == "serve":
                _, payload, uuids, n = msg
                parser.feed(payload)
                msgs = parser.drain()
                out = bytearray()
                spans: list = []
                deleted[0] = False
                if coal is not None:
                    coal.run_chunk(msgs, out, uuids=uuids, spans=spans)
                else:
                    # CONSTDB_SERVE_BATCH<=1: the exact per-command loop
                    for i, m in enumerate(msgs):
                        reply = node.execute(m, uuid=uuids[i])
                        if not isinstance(reply, NoReply):
                            encode_into(out, reply)
                        spans.append(len(out))
                conn.send(("ok", (bytes(out), spans,
                                  node.repl_log.drain(), deleted[0],
                                  _worker_stats(node))))
            elif cmd == "apply":
                # one peer-stream sub-chunk: full REPLICATE wire frames,
                # applied per-key in stream order (the exact op path —
                # cross-shard parallelism replaces in-shard coalescing;
                # frames here are NOT barriers, so the PR 4
                # repl_apply_barriers stat keeps its single-loop
                # meaning: only non-routable frames, counted by the
                # parent-side ShardApplier)
                _, payload, n = msg
                parser.feed(payload)
                frames = parser.drain()
                deleted[0] = False
                for fr in frames:
                    it = fr.items
                    node.apply_replicated(as_bytes(it[4]), it[5:],
                                          as_int(it[1]), as_int(it[3]))
                conn.send(("ok", (node.repl_log.drain(), deleted[0],
                                  _worker_stats(node))))
            elif cmd == "merge":
                # snapshot-codec encoded sub-batch (catch-up ingest);
                # the key count rides back so INFO's per-shard gauges
                # are populated by restores too, not only serve acks
                b = _decode_batch(msg[1])
                node.merge_batches([b])
                conn.send(("ok", (b.n_rows, node.ks.n_keys())))
            elif cmd == "canonical":
                node.ensure_flushed()
                conn.send(("ok", node.ks.canonical(keys=msg[1])))
            elif cmd == "state_bytes":
                node.ensure_flushed()
                conn.send(("ok", keyspace_state_bytes(node.ks)))
            elif cmd == "export":
                node.ensure_flushed()
                conn.send(("ok", bytes(_encode_batch(
                    batch_from_keyspace(node.ks)))))
            elif cmd == "digest":
                # anti-entropy digest of THIS shard's keys (the crc32
                # partition is layout-invariant, so the parent SUMS the
                # workers' matrices — store/digest.py sum_matrices)
                from ..store.digest import state_digest_matrix
                node.ensure_flushed()
                conn.send(("ok", state_digest_matrix(
                    node.ks, msg[1], msg[2]).astype("<u8").tobytes()))
            elif cmd == "n_keys":
                # live key count (delta-sync leaf sizing): the serving
                # stat gauges can be zero on a node whose state arrived
                # purely via the replication stream, so the plane asks
                # the workers directly
                node.ensure_flushed()
                conn.send(("ok", node.ks.n_keys()))
            elif cmd == "digest_export":
                # encoded BATCH chunks of the masked buckets' state —
                # the delta-sync stream's payload (replica/link.py
                # _send_delta writes them via write_chunk_raw)
                from ..persist.snapshot import batch_chunks
                from ..store.digest import export_bucket_batch
                _, fanout, leaves, mask_bytes, chunk_keys = msg
                node.ensure_flushed()
                mask = np.frombuffer(mask_bytes, dtype=bool)
                b = export_bucket_batch(node.ks, fanout, leaves, mask)
                conn.send(("ok", [bytes(_encode_batch(c))
                                  for c in batch_chunks(b, chunk_keys)]))
            elif cmd == "memory":
                node.ensure_flushed()
                conn.send(("ok", node.ks.memory_report()))
            elif cmd == "gc":
                node.ensure_flushed()
                freed = node.ks.gc(msg[1])
                node.stats.gc_freed += freed
                conn.send(("ok", freed))
            elif cmd == "ident":
                node.node_id = msg[1]
                node.alias = msg[2]
                conn.send(("ok", None))
            elif cmd == "reset":
                # state-clearing full resync: fresh keyspace, tap kept
                eng = node.engine
                if hasattr(eng, "discard_resident"):
                    eng.discard_resident()
                node.ks = node._make_keyspace()
                wire_ks()
                node.repl_log = _TapLog()
                # cached replies describe the wiped shard state
                node.read_cache.clear()
                if coal is not None:
                    coal._reset_caches()
                conn.send(("ok", None))
            elif cmd == "ping":
                conn.send(("ok", None))
            elif cmd == "close":
                break
            else:
                raise ValueError(f"unknown serve-pool command {cmd!r}")
        except BaseException:
            try:
                conn.send(("err", traceback.format_exc()))
            except (BrokenPipeError, OSError):  # parent already gone
                break
    conn.close()


class ServeShardPool:
    """N forkserver serve workers with asyncio request/reply transport.

    `request(shard, msg)` returns an awaitable resolving to the worker's
    reply; per-worker FIFO is preserved (requests are sent under a
    per-worker lock, replies correlate in order), so a shard worker is a
    serialization point exactly like the single event loop was — for
    its shard only."""

    def __init__(self, n_shards: int, engine_spec: str = "cpu",
                 node_id: int = 0, alias: str = "", serve_batch: int = 512,
                 env: Optional[dict] = None,
                 start_method: str = "forkserver",
                 maxmemory: Optional[int] = None,
                 maxmemory_soft_pct: Optional[float] = None):
        import multiprocessing as mp

        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = n_shards
        wenv = _capture_env()
        if env:
            wenv.update(env)
        try:
            ctx = mp.get_context(start_method)
        except ValueError:  # pragma: no cover - non-POSIX
            ctx = mp.get_context("spawn")
        self._loop = asyncio.get_running_loop()
        self._conns = []
        self._procs = []
        self._pending: list[deque] = []
        self._closed = False
        for s in range(n_shards):
            parent, child = ctx.Pipe()
            p = ctx.Process(target=_serve_worker_main,
                            args=(child, s, n_shards, engine_spec, wenv,
                                  node_id, alias, serve_batch,
                                  maxmemory, maxmemory_soft_pct),
                            daemon=True)
            p.start()
            child.close()
            self._conns.append(parent)
            self._procs.append(p)
            self._pending.append(deque())
        self._readers = [
            threading.Thread(target=self._reader, args=(s,), daemon=True)
            for s in range(n_shards)]
        for t in self._readers:
            t.start()

    # ----------------------------------------------------------- transport

    def _reader(self, shard: int) -> None:
        conn = self._conns[shard]
        while True:
            try:
                msg = conn.recv()
            except (EOFError, OSError):
                if not self._closed:
                    try:
                        self._loop.call_soon_threadsafe(
                            self._fail_all, shard,
                            RuntimeError(f"serve worker {shard} died"))
                    except RuntimeError:  # loop already closed
                        pass
                return
            try:
                self._loop.call_soon_threadsafe(self._resolve, shard, msg)
            except RuntimeError:  # loop closed mid-shutdown
                return

    def _resolve(self, shard: int, msg) -> None:
        if not self._pending[shard]:  # late reply after close
            return
        fut = self._pending[shard].popleft()
        if fut.done():
            return
        if msg[0] == "err":
            fut.set_exception(RuntimeError(
                f"serve worker {shard} failed:\n{msg[1]}"))
        else:
            fut.set_result(msg[1])

    def _fail_all(self, shard: int, exc: BaseException) -> None:
        while self._pending[shard]:
            fut = self._pending[shard].popleft()
            if not fut.done():
                fut.set_exception(exc)

    def submit(self, shard: int, msg: tuple) -> asyncio.Future:
        """Send one request SYNCHRONOUSLY, returning the reply future —
        no suspension point between the caller's uuid minting and the
        pipe write (see module docstring), and the plane's ack
        callbacks run in reply order (floor windows, segment
        mirroring)."""
        fut = self._loop.create_future()
        pending = self._pending[shard]
        pending.append(fut)
        try:
            self._conns[shard].send(msg)
        except BaseException:
            pending.remove(fut)
            raise
        return fut

    async def request(self, shard: int, msg: tuple):
        """Send one request and await its reply (FIFO per worker)."""
        return await self.submit(shard, msg)

    # -------------------------------------------------------- conveniences

    async def call_all(self, *msg) -> list:
        """One control command on every worker, replies in shard order.
        FIFO pipes make this an implicit barrier: everything previously
        sent to a worker completes before its reply."""
        futs = [self.submit(s, tuple(msg)) for s in range(self.n_shards)]
        return list(await asyncio.gather(*futs))

    async def barrier(self) -> None:
        """Drain every worker's queue (quiesce)."""
        await self.call_all("ping")

    # ----------------------------------------------------------- lifecycle

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for conn in self._conns:
            try:
                conn.send(("close",))
            except (BrokenPipeError, OSError):
                pass
        loop = self._loop

        def join_all():
            for p in self._procs:
                p.join(timeout=10)
                if p.is_alive():  # pragma: no cover - hung worker
                    p.terminate()

        await loop.run_in_executor(None, join_all)
        for conn in self._conns:
            conn.close()
        for s in range(self.n_shards):
            self._fail_all(s, RuntimeError("serve pool closed"))
