"""Tensor-valued registers: the two-layer CRDT of arXiv 2605.19373.

A tensor key's state is two layers:

  * ENVELOPE (metadata): the key's ct/mt/dt/expire row (the usual
    max-merge envelope), plus a creation-fixed `TensorMeta` — strategy
    id, dtype, shape — and one contributor slot per writer node holding
    that node's latest `(uuid, count, payload)` as an LWW register.
    Envelope merges are the existing LWW/fold machinery: slot merges
    are exactly the counter-slot (value @ time) rule with the payload
    riding the winner.
  * PAYLOAD (read-time reduction): the visible tensor value is a
    REGISTERED STRATEGY applied over the live contributor payloads.
    The state itself is the delivered SET of contributions — merge is a
    pointwise slot LWW, trivially commutative/associative/idempotent —
    and the strategy is a pure function of that set, so replicas
    converge by construction (the paper's "CRDT-compliant model
    merging" decomposition: any aggregation expressible as a
    commutative reduction over stamped dense tensors rides the same
    envelope).

Canonical-order law (docs/INVARIANTS.md "Tensor registers"): float
reductions are NOT associative, so every strategy reduces contributors
in ascending `(node, uuid)` order with a FIXED sequential operation
chain.  `reduce_rows` below is the one reference implementation; the
device twins (ops/dense.py `tensor_reduce`, ops/pallas_dense.py
`tensor_reduce`) unroll the exact same chain, so host and device reads
are bit-identical IEEE operation sequences — replicas cannot diverge
through summation order, whatever engine serves the read.

Strategies (ids are wire/snapshot stable — append only):

  0 lww           payload of the max-(uuid, node) contributor
  1 sum           sequential elementwise sum
  2 avg           count-weighted mean: Σ(cnt_i · p_i) / Σ cnt_i
  3 maxmag        elementwise max-magnitude pick (strict >, so the
                  earlier canonical contributor keeps exact-magnitude
                  ties)
  4 trimmed-mean  drop the elementwise min and max, mean the rest
                  (plain sequential mean below 3 contributors)
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

STRAT_LWW = 0
STRAT_SUM = 1
STRAT_AVG = 2
STRAT_MAXMAG = 3
STRAT_TRIMMED = 4

STRATEGY_IDS = {"lww": STRAT_LWW, "sum": STRAT_SUM, "avg": STRAT_AVG,
                "maxmag": STRAT_MAXMAG, "trimmed-mean": STRAT_TRIMMED}
STRATEGY_NAMES = {v: k for k, v in STRATEGY_IDS.items()}

# dtype codes (wire/snapshot stable)
DTYPE_IDS = {"f32": 0, "f64": 1}
DTYPE_NAMES = {v: k for k, v in DTYPE_IDS.items()}
_DTYPES = {0: np.dtype(np.float32), 1: np.dtype(np.float64)}


class TensorConfigError(ValueError):
    """Malformed or mismatched tensor configuration."""


@dataclass(frozen=True)
class TensorMeta:
    """Creation-fixed tensor key configuration."""

    strat: int
    dtype_code: int
    shape: tuple

    @property
    def dtype(self) -> np.dtype:
        return _DTYPES[self.dtype_code]

    @property
    def elems(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def nbytes(self) -> int:
        return self.elems * self.dtype.itemsize

    @property
    def strat_name(self) -> str:
        return STRATEGY_NAMES.get(self.strat, str(self.strat))


def pack_config(meta: TensorMeta) -> bytes:
    """Wire/snapshot form: strat byte, dtype byte, ndim byte, u32le dims."""
    out = bytearray((meta.strat, meta.dtype_code, len(meta.shape)))
    for d in meta.shape:
        out += int(d).to_bytes(4, "little")
    return bytes(out)


def unpack_config(b: bytes) -> TensorMeta:
    if len(b) < 3:
        raise TensorConfigError("truncated tensor config")
    strat, dcode, ndim = b[0], b[1], b[2]
    if strat not in STRATEGY_NAMES:
        raise TensorConfigError(f"unknown tensor strategy id {strat}")
    if dcode not in _DTYPES:
        raise TensorConfigError(f"unknown tensor dtype code {dcode}")
    if len(b) != 3 + 4 * ndim:
        raise TensorConfigError("tensor config length mismatch")
    shape = tuple(int.from_bytes(b[3 + 4 * i: 7 + 4 * i], "little")
                  for i in range(ndim))
    if any(d <= 0 for d in shape) or not shape:
        raise TensorConfigError("tensor shape must be positive")
    return TensorMeta(strat, dcode, shape)


def parse_meta(strat_s: str, dtype_s: str, shape_s: str,
               default_strat: str = "lww",
               max_elems: int = 1 << 22) -> TensorMeta:
    """Client-argument form: strategy name (`-` = the configured
    default), dtype name (f32/f64), shape as `4096` or `64x64`."""
    if strat_s in ("-", ""):
        strat_s = default_strat
    strat = STRATEGY_IDS.get(strat_s)
    if strat is None:
        raise TensorConfigError(
            f"unknown tensor strategy {strat_s!r} "
            f"(one of {', '.join(sorted(STRATEGY_IDS))})")
    dcode = DTYPE_IDS.get(dtype_s)
    if dcode is None:
        raise TensorConfigError(f"unknown tensor dtype {dtype_s!r} "
                                "(f32 or f64)")
    try:
        shape = tuple(int(p) for p in shape_s.replace("*", "x").split("x"))
    except ValueError:
        raise TensorConfigError(f"bad tensor shape {shape_s!r}") from None
    meta = TensorMeta(strat, dcode, shape)
    # dims must fit the wire config's fields (pack_config: one ndim
    # byte, u32 per dim) — unbounded values would escape as raw
    # OverflowError/ValueError past the command error boundary instead
    # of a clean client error
    if len(shape) > 255:
        raise TensorConfigError("tensor rank must be <= 255")
    if any(d <= 0 or d >= (1 << 32) for d in shape) or not shape:
        raise TensorConfigError("tensor dims must be in [1, 2^32)")
    if meta.elems > max_elems:
        raise TensorConfigError(
            f"tensor too large: {meta.elems} elems > cap {max_elems} "
            "(CONSTDB_TENSOR_MAX_ELEMS)")
    return meta


def check_count(cnt: int) -> None:
    """Contribution counts weight the `avg` strategy's denominator: a
    non-positive count poisons reads with NaN/Inf (0/0) or corrupts the
    weighted mean — rejected at every intake (op commands raise, the
    serve planners demote into that raise, the merge paths skip the row
    like any other malformed contribution)."""
    if cnt < 1:
        raise TensorConfigError(
            f"tensor contribution count must be >= 1, got {cnt}")


def payload_ok(meta: TensorMeta, payload) -> bool:
    """The row-validity predicate `payload_array` enforces, without the
    normalization: wire bytes of exactly the config's byte size, or an
    ndarray of the config's dtype and element count.  The batched
    device path (engine/tpu.py) pre-filters rows with THIS predicate so
    its skip rules cannot drift from the per-row reference
    (KeySpace.tensor_merge_row → payload_array)."""
    if isinstance(payload, np.ndarray):
        return payload.dtype == meta.dtype and payload.size == meta.elems
    return len(payload) == meta.nbytes


def payload_array(meta: TensorMeta, payload) -> np.ndarray:
    """Normalize a wire payload (raw little-endian bytes) or ndarray to
    the flat [elems] array of the key's dtype.  Raises
    TensorConfigError on a size mismatch — the merge paths skip such
    rows exactly like type conflicts."""
    if isinstance(payload, np.ndarray):
        arr = payload.reshape(-1)
        if arr.dtype != meta.dtype:
            raise TensorConfigError("tensor payload dtype mismatch")
    else:
        if len(payload) != meta.nbytes:
            raise TensorConfigError(
                f"tensor payload is {len(payload)} bytes, key config "
                f"needs {meta.nbytes}")
        arr = np.frombuffer(payload, dtype=meta.dtype.newbyteorder("<"))
        if arr.dtype != meta.dtype:  # big-endian host
            arr = arr.astype(meta.dtype)
    if len(arr) != meta.elems:
        raise TensorConfigError(
            f"tensor payload has {len(arr)} elems, key config needs "
            f"{meta.elems}")
    return arr


def canonical_order(nodes: np.ndarray, uuids: np.ndarray) -> np.ndarray:
    """Contributor sort order every strategy reduces in: ascending
    (node, uuid).  One slot per node makes `node` alone total, but the
    uuid tiebreak keeps the order well-defined for any delivered set."""
    return np.lexsort((np.asarray(uuids), np.asarray(nodes)))


def reduce_rows(strat: int, mat: np.ndarray, cnts, uuids, nodes
                ) -> np.ndarray:
    """THE canonical host reduction over contributors already sorted in
    canonical (node, uuid) order: `mat` is [n, K] of the key's dtype,
    `cnts`/`uuids`/`nodes` are the aligned per-contributor columns.

    Every operation below is a fixed sequential IEEE chain — the device
    twins (ops/dense.py / ops/pallas_dense.py `tensor_reduce`) unroll
    the SAME chain, so results are bit-identical across engines."""
    n = len(mat)
    dt = mat.dtype.type
    if strat == STRAT_LWW:
        w = 0
        for i in range(1, n):
            if (int(uuids[i]), int(nodes[i])) > (int(uuids[w]),
                                                 int(nodes[w])):
                w = i
        return np.array(mat[w], copy=True)
    if strat == STRAT_SUM:
        acc = np.array(mat[0], copy=True)
        for i in range(1, n):
            acc = acc + mat[i]
        return acc
    if strat == STRAT_AVG:
        # the count total accumulates in the PAYLOAD dtype, not int —
        # the device twin carries counts as a float plane, so the host
        # must run the identical float chain (identical even when a
        # pathological count total would round in f32)
        acc = mat[0] * dt(cnts[0])
        tot = dt(cnts[0])
        for i in range(1, n):
            acc = acc + mat[i] * dt(cnts[i])
            tot = tot + dt(cnts[i])
        return acc / tot
    if strat == STRAT_MAXMAG:
        acc = np.array(mat[0], copy=True)
        for i in range(1, n):
            acc = np.where(np.abs(mat[i]) > np.abs(acc), mat[i], acc)
        return acc
    if strat == STRAT_TRIMMED:
        if n <= 2:
            acc = np.array(mat[0], copy=True)
            for i in range(1, n):
                acc = acc + mat[i]
            return acc / dt(n)
        s = np.array(mat[0], copy=True)
        mn = np.array(mat[0], copy=True)
        mx = np.array(mat[0], copy=True)
        for i in range(1, n):
            s = s + mat[i]
            mn = np.minimum(mn, mat[i])
            mx = np.maximum(mx, mat[i])
        return (s - mn - mx) / dt(n - 2)
    raise TensorConfigError(f"unknown tensor strategy id {strat}")
