from .semantics import (
    ENC_COUNTER, ENC_BYTES, ENC_DICT, ENC_SET, ENC_MV, ENC_LIST, ENC_TENSOR, ENC_NAMES,
    VALUE_ENCS, lww_wins, elem_alive, key_alive, merge_envelope,
)

__all__ = [
    "ENC_COUNTER", "ENC_BYTES", "ENC_DICT", "ENC_SET", "ENC_MV", "ENC_LIST", "ENC_TENSOR",
    "ENC_NAMES", "VALUE_ENCS",
    "lww_wins", "elem_alive", "key_alive", "merge_envelope",
]
