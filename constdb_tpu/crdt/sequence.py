"""Ordered-sequence CRDT: dense position identifiers + element tombstones.

Capability completion for the reference's `Sequence`/`List` scaffold
(reference src/crdt/list.rs:4-43): there it is an ordered-insert linked
list keyed by u128 ids, wired to nothing (SURVEY.md §2.5).  This is a
WORKING replicated list: every element gets a position identifier drawn
between its neighbors' (LSEQ-style path of (digit, node) pairs, so
identifiers from concurrent inserts at the same spot order
deterministically by writer node), deletes tombstone by identifier, and
merge is a keyed LWW union — commutative, associative, idempotent.
"""

from __future__ import annotations

import bisect
from typing import Optional

# each path digit is (slot, node); slot space per level
_BASE = 1 << 16


class Sequence:
    __slots__ = ("items",)

    def __init__(self) -> None:
        # sorted by position id: [(pos, value, add_t, del_t)]
        self.items: list[list] = []

    # ----------------------------------------------------------- positions

    @staticmethod
    def _between(lo: Optional[tuple], hi: Optional[tuple], node: int) -> tuple:
        """A fresh position strictly between lo and hi."""
        lo = lo or ()
        hi = hi or ()
        path = []
        level = 0
        while True:
            lo_d = lo[level] if level < len(lo) else (0, 0)
            hi_d = hi[level] if level < len(hi) else (_BASE, 0)
            if hi_d[0] - lo_d[0] > 1:
                path.append(((lo_d[0] + hi_d[0]) // 2, node))
                return tuple(path)
            path.append(lo_d)
            level += 1

    # ----------------------------------------------------------------- ops

    def _live(self) -> list:
        return [it for it in self.items if it[2] >= it[3]]

    def insert(self, index: int, value: bytes, node: int, uuid: int) -> tuple:
        """Insert before live index `index`; returns the position id."""
        live = self._live()
        lo = live[index - 1][0] if 0 < index <= len(live) else None
        hi = live[index][0] if index < len(live) else None
        pos = self._between(lo, hi, node)
        self.apply_insert(pos, value, uuid)
        return pos

    def apply_insert(self, pos: tuple, value: bytes, uuid: int) -> None:
        """Keyed add-side LWW write (replication entry point)."""
        i = bisect.bisect_left([it[0] for it in self.items], pos)
        if i < len(self.items) and self.items[i][0] == pos:
            it = self.items[i]
            if uuid > it[2]:
                it[1], it[2] = value, uuid
        else:
            self.items.insert(i, [pos, value, uuid, 0])

    def delete(self, index: int, uuid: int) -> Optional[tuple]:
        live = self._live()
        if not 0 <= index < len(live):
            return None
        pos = live[index][0]
        self.apply_delete(pos, uuid)
        return pos

    def apply_delete(self, pos: tuple, uuid: int) -> None:
        i = bisect.bisect_left([it[0] for it in self.items], pos)
        if i < len(self.items) and self.items[i][0] == pos:
            if uuid > self.items[i][3]:
                self.items[i][3] = uuid
        else:
            # delete for a not-yet-seen insert: tombstone placeholder
            self.items.insert(i, [pos, None, 0, uuid])

    def read(self) -> list[bytes]:
        return [it[1] for it in self._live()]

    # ---------------------------------------------------------------- merge

    def merge(self, other: "Sequence") -> None:
        for pos, value, add_t, del_t in other.items:
            if add_t:
                self.apply_insert(pos, value, add_t)
            if del_t:
                self.apply_delete(pos, del_t)

    def state(self) -> frozenset:
        return frozenset((it[0], it[1], it[2], it[3]) for it in self.items)


# ------------------------------------------------- wire/member serialization
# A list entry is stored as an ELEMENT ROW whose member bytes are its
# position id serialized as fixed-width big-endian digits — byte-lex order
# of members IS position order, so sorting live members reads the list and
# element-plane merges (both engines, snapshots, GC) apply unchanged.

_DIGIT_BYTES = 2 + 8  # slot (16-bit) + writer node (64-bit)


def pos_to_bytes(pos: tuple) -> bytes:
    out = bytearray()
    for slot, node in pos:
        out += slot.to_bytes(2, "big") + node.to_bytes(8, "big")
    return bytes(out)


def pos_from_bytes(b: bytes) -> tuple:
    return tuple((int.from_bytes(b[i:i + 2], "big"),
                  int.from_bytes(b[i + 2:i + _DIGIT_BYTES], "big"))
                 for i in range(0, len(b), _DIGIT_BYTES))


def pos_between_bytes(lo: Optional[bytes], hi: Optional[bytes],
                      node: int) -> bytes:
    """A fresh serialized position strictly between two serialized ones."""
    return pos_to_bytes(Sequence._between(
        pos_from_bytes(lo) if lo else None,
        pos_from_bytes(hi) if hi else None, node))
