"""Multi-value register: vector-clock concurrent-write semantics.

Capability completion for the reference's `VClock`/`MiniMap`/`MultiValue`
scaffold (reference src/crdt/vclock.rs:3-45): the README there advertises a
MultiValueRegister but the type is never wired to an encoding or command
(SURVEY.md §2.5 "vestigial").  This is a WORKING implementation: reads
return every causally-concurrent value (siblings), writes carry the vector
clock the writer observed, and merge keeps exactly the causal frontier.

Unlike the LWW types, no write is silently lost — concurrent writes
surface to the reader (Dynamo-style) for application-level resolution.

Columnar note: sibling sets are tiny (bounded by the number of
concurrently-writing nodes), so this stays a host-side structure; the bulk
engines treat multi-value payloads as opaque bytes.
"""

from __future__ import annotations

from typing import Iterable, Optional


class VClock:
    """node_id -> counter map with the usual partial order
    (the reference's sorted-vec MiniMap, vclock.rs:3-38)."""

    __slots__ = ("c",)

    def __init__(self, c: Optional[dict] = None):
        self.c: dict[int, int] = dict(c or {})

    def bump(self, node: int) -> "VClock":
        out = VClock(self.c)
        out.c[node] = out.c.get(node, 0) + 1
        return out

    def merge(self, other: "VClock") -> "VClock":
        out = VClock(self.c)
        for n, v in other.c.items():
            if v > out.c.get(n, 0):
                out.c[n] = v
        return out

    def dominates(self, other: "VClock") -> bool:
        """self >= other pointwise (a write with clock `self` has SEEN one
        with clock `other`)."""
        return all(self.c.get(n, 0) >= v for n, v in other.c.items())

    def concurrent(self, other: "VClock") -> bool:
        return not self.dominates(other) and not other.dominates(self)

    def __eq__(self, other) -> bool:
        return isinstance(other, VClock) and self.c == other.c

    def __hash__(self) -> int:
        return hash(frozenset(self.c.items()))

    def __repr__(self) -> str:
        return f"VClock({self.c})"


class MultiValue:
    """The register: a set of (value, VClock) siblings on the causal
    frontier."""

    __slots__ = ("siblings",)

    def __init__(self) -> None:
        self.siblings: list[tuple[bytes, VClock]] = []

    # ------------------------------------------------------------------ ops

    def read(self) -> list[bytes]:
        return [v for v, _ in self.siblings]

    def context(self) -> VClock:
        """The clock a reader should attach to its next write (join of all
        siblings — writing with it supersedes everything read)."""
        out = VClock()
        for _, vc in self.siblings:
            out = out.merge(vc)
        return out

    def write(self, value: bytes, node: int,
              context: Optional[VClock] = None) -> VClock:
        """Write `value` having observed `context` (defaults to this
        replica's current frontier).  Returns the write's clock."""
        ctx = context if context is not None else self.context()
        wc = ctx.bump(node)
        self.siblings = [(v, vc) for v, vc in self.siblings
                         if not wc.dominates(vc)]
        self.siblings.append((value, wc))
        return wc

    # ---------------------------------------------------------------- merge

    def merge(self, other: "MultiValue") -> None:
        """Keep exactly the union's causal frontier — commutative,
        associative, idempotent."""
        self.siblings = self._frontier(self.siblings + other.siblings)

    @staticmethod
    def _frontier(pairs: Iterable[tuple[bytes, VClock]]
                  ) -> list[tuple[bytes, VClock]]:
        pairs = list(pairs)
        out: list[tuple[bytes, VClock]] = []
        for i, (v, vc) in enumerate(pairs):
            dominated = False
            for j, (v2, vc2) in enumerate(pairs):
                if i == j:
                    continue
                if vc2.dominates(vc) and not (vc.dominates(vc2) and i < j):
                    # strictly dominated, or an equal-clock duplicate keeps
                    # only its first occurrence
                    dominated = True
                    break
            if not dominated and (v, vc) not in out:
                out.append((v, vc))
        return out

    def state(self) -> frozenset:
        return frozenset((v, frozenset(vc.c.items())) for v, vc in self.siblings)


# ------------------------------------------------- wire/member serialization
# A sibling is stored as an ELEMENT ROW whose member bytes are the write's
# canonical clock serialization: deterministic, so the same write interns to
# the same member on every replica and element-plane merges (both engines,
# snapshots, GC) apply unchanged.

def clock_to_bytes(vc: VClock) -> bytes:
    """Canonical ascii form `node:count,node:count` sorted by node."""
    return b",".join(b"%d:%d" % (n, c) for n, c in sorted(vc.c.items()))


def clock_from_bytes(b: bytes) -> VClock:
    out = VClock()
    if b:
        for part in b.split(b","):
            n, _, c = part.partition(b":")
            out.c[int(n)] = int(c)
    return out


def frontier_of(pairs: list) -> list:
    """Prune causally-dominated entries from [(member, value, clock), ...]
    (read-time view; dominated rows may linger until a later write
    tombstones them)."""
    out = []
    for i, (m, v, vc) in enumerate(pairs):
        dominated = False
        for j, (m2, _v2, vc2) in enumerate(pairs):
            if i != j and vc2.dominates(vc) and not (vc.dominates(vc2)
                                                     and i < j):
                dominated = True
                break
        if not dominated:
            out.append((m, v, vc))
    return out
