"""CRDT conflict-resolution semantics — the single spec both merge engines
(engine/cpu.py and engine/tpu.py) implement bit-identically.

Derived from the reference's rules (SURVEY.md §2.5):
  * uuid = (unix_ms << 22) | seq, minted per executed command
    (reference src/server.rs:159-173); it is the HLC timestamp that orders
    writes.  uuids are NOT globally unique — two nodes can mint the same one.
  * Register (bytes): last-write-wins on write-time
    (reference src/object.rs:63-77).
  * Counter: per-node (value, uuid) LWW, max(value) on uuid tie; read = Σ
    (reference src/type_counter.rs:59-91).
  * Set/Dict element: visible iff add_time >= del_time — add wins on tie
    (reference src/crdt/lwwhash.rs:32-44); merge = pointwise max of
    (add_time, del_time).
  * Key envelope: alive iff create_time >= delete_time; envelope times merge
    as pointwise max.
  * GC: tombstones are physically removed only once every replica's ack
    watermark has passed them (reference src/server.rs:257-263, db.rs:82-119).

Deliberate fixes over the reference (its merges are order-dependent or
broken — SURVEY.md §"Known reference defects"):
  * every LWW decision that the reference resolves by application order
    (register value on equal create_time, element value on equal add_time)
    is resolved here by the total order on (time, writer_node_id): larger
    wins.  Writer node ids are carried with every register/dict-field write
    for this purpose.  Within one node uuids are strictly monotonic, so
    (time, node) uniquely identifies a write and the tie-break is
    deterministic, commutative and associative.
  * Dict merge is implemented (the reference's panics, lwwhash.rs:176-181).
  * Counter.change advances the stored per-node uuid (the reference never
    does after first insert, type_counter.rs:37-51).
  * Counter slots are cumulative-total registers, not deltas: a slot holds
    the writer node's LIFETIME total as an LWW register (total @ uuid), and
    counter deletes record the delete-observed total as a second LWW
    register (base @ delete-uuid, max-base on exact ties); the visible
    value is Σ over slots of (total - base).  Every component is an LWW
    assignment, so replication is idempotent, reorder-safe, and identical
    to state merges.  (The reference's `delcnt` replays negated deltas —
    cmd.rs:233-254 — which requires exactly-once in-order delivery and
    still diverges when a delete and concurrent increments interleave
    differently on different replicas.)
  * element add/rem are pure pointwise ops — adds always LWW-merge into the
    add side and dels always max into the del side — instead of the
    reference's drop-if-older gates (lwwhash.rs:87-128), so the op path and
    the state-merge path compute the same function and replicas that saw
    different interleavings converge bit-identically.
  * envelope times (ct/mt/dt) merge as max for ALL encodings (the reference
    only does so for Bytes, keeping first-merged otherwise).
  * expire times merge as max (latest expiry wins) — the reference's
    expire_at is last-applied-wins and thus divergent.
"""

from __future__ import annotations

# Encoding tags — wire-compatible with the reference's snapshot enc byte
# (reference src/object.rs:19-22).  6/7 are new: the reference advertises a
# MultiValueRegister and scaffolds a List (README.md:10, vclock.rs, list.rs)
# but never assigns them encodings — this build completes them on the
# element plane (crdt/multivalue.py, crdt/sequence.py docstrings).
ENC_NONE = -1
ENC_COUNTER = 0
ENC_BYTES = 3
ENC_DICT = 4
ENC_SET = 5
ENC_MV = 6
ENC_LIST = 7
# 8 is new: tensor-valued registers (crdt/tensor.py) — dense float
# arrays whose merge is a per-node contributor-slot LWW and whose read
# is a registered strategy reduction (arXiv 2605.19373 two-layer CRDT)
ENC_TENSOR = 8

ENC_NAMES = {ENC_COUNTER: "Counter", ENC_BYTES: "Bytes", ENC_DICT: "LWWDict",
             ENC_SET: "LWWSet", ENC_MV: "MultiValue", ENC_LIST: "List",
             ENC_TENSOR: "Tensor"}

# encodings whose element rows carry value bytes (dict fields, multi-value
# siblings, list entries); set members are valueless
VALUE_ENCS = (ENC_DICT, ENC_MV, ENC_LIST)

# "never written" timestamp sentinel: loses to every real timestamp (real
# uuids are >= 0).  Single definition shared by the store layer and the
# device kernels (ops/segment.py re-exports it).
NEUTRAL_T = -(1 << 62)


def lww_wins(t_a: int, node_a: int, t_b: int, node_b: int) -> bool:
    """True iff write A beats write B under the (time, writer-node) total
    order.  Strict: equal (t, node) pairs mean the same write."""
    return (t_a, node_a) > (t_b, node_b)


def elem_alive(add_t: int, del_t: int) -> bool:
    """Element visibility: add wins on tie (reference lwwhash.rs:32-44)."""
    return add_t >= del_t


def key_alive(ct: int, dt: int) -> bool:
    """Key-level tombstone rule (reference object.rs:50-53)."""
    return ct >= dt


def merge_envelope(ct_a: int, mt_a: int, dt_a: int,
                   ct_b: int, mt_b: int, dt_b: int) -> tuple[int, int, int]:
    return max(ct_a, ct_b), max(mt_a, mt_b), max(dt_a, dt_b)


def merge_counter_slot(val_a: int, t_a: int, val_b: int, t_b: int) -> tuple[int, int]:
    """Per-(key, node) counter slot LWW; max value on uuid tie
    (reference type_counter.rs:59-91)."""
    if t_a > t_b:
        return val_a, t_a
    if t_b > t_a:
        return val_b, t_b
    return max(val_a, val_b), t_a


def merge_register(val_a: bytes, t_a: int, node_a: int,
                   val_b: bytes, t_b: int, node_b: int) -> tuple[bytes, int, int]:
    if lww_wins(t_a, node_a, t_b, node_b):
        return val_a, t_a, node_a
    return val_b, t_b, node_b


def merge_elem(add_a: int, anode_a: int, del_a: int,
               add_b: int, anode_b: int, del_b: int):
    """-> (add_t, add_node, del_t, a_value_wins).  Value follows the winning
    add-side write; del side is a plain max."""
    if lww_wins(add_a, anode_a, add_b, anode_b):
        return add_a, anode_a, max(del_a, del_b), True
    return add_b, anode_b, max(del_a, del_b), False


def updated_at(ct: int, mt: int, dt: int, uuid: int) -> tuple[int, int, int]:
    """Envelope bump on a data write (local or replicated).

    Redesigned from the reference's resurrect-only rule (object.rs:34-48,
    `ct = uuid` iff ct < dt <= uuid), which is order-dependent: replicas that
    interleave the same write/delete ops differently end with different
    create_times.  Here ct is simply the max over all data-write uuids and dt
    the max over all delete uuids, so `alive = ct >= dt` becomes the
    element-level add-wins rule lifted to keys and every envelope component
    is a plain max — commutative, associative, idempotent, and identical
    between the op path and the state-merge path (merge_envelope)."""
    return max(ct, uuid), max(mt, uuid), dt
