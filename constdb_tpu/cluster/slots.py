"""Slot math + the epoch-versioned slot table + per-node cluster state.

Slot <-> digest-bucket correspondence (the load-bearing trick): the
digest plane partitions keys by ``crc32(key)`` into ``fanout x leaves``
buckets as ``(crc % fanout) * leaves + (crc // fanout) % leaves``
(store/digest.py _buckets).  With the canonical 64x256 geometry,
``fanout * leaves == NSLOTS`` and both coordinates are exact functions
of ``crc % 16384`` — i.e. of the slot — so

    bucket_of_slot(s) == (s % 64) * 256 + s // 64

is a bijection: every slot IS one digest bucket.  Per-slot digest =
one matrix cell; per-slot export = export_bucket_batch with that one
bucket masked (tombstones included).  Migration therefore ships
O(slot bytes), never a full-keyspace snapshot, with convergence
certified by the same digest the delta-sync plane already trusts.

Routing contract (server/commands.py execute + server/serve.py): every
data command is FIRST-KEY-CONFINED (the KEY-CONFINED lint rule pins
this statically), so ``ClusterState.route(key)`` decides from the first
argument alone:

    owned, not migrating      -> None               (serve locally)
    owned, slot mid-handoff   -> -ASK <slot> <addr>  (writes drain to
                                                      the target during
                                                      the handoff window)
    not owned, slot importing -> None               (serve: the ASK
                                                      target side)
    not owned                 -> -MOVED <slot> <addr>

Ownership is EPOCH-GATED: the table only ever adopts a peer's table at
a strictly higher epoch (adopt()), and every migration finalize bumps
the epoch exactly once, so a stale owner converges to redirecting at
its first gossip exchange and two groups never both serve a slot at
the same epoch."""

from __future__ import annotations

import json
import zlib
from array import array
from typing import Optional

from ..resp.message import Err

NSLOTS = 16384
# the canonical digest geometry under which slot == bucket (module doc)
SLOT_FANOUT = 64
SLOT_LEAVES = 256
assert SLOT_FANOUT * SLOT_LEAVES == NSLOTS


def slot_of(key: bytes) -> int:
    """The hash slot of a key — the digest plane's crc32, mod NSLOTS."""
    return zlib.crc32(key) % NSLOTS


def bucket_of_slot(slot: int) -> int:
    """The flat 64x256 digest-bucket index holding exactly this slot's
    keys (module doc derivation; property-tested against digest._buckets
    in tests/test_cluster.py)."""
    return (slot % SLOT_FANOUT) * SLOT_LEAVES + slot // SLOT_FANOUT


class SlotTable:
    """Epoch-versioned slot -> group ownership map.

    ``owner[slot]`` is a group id (gid); ``groups`` maps gid to the
    group's advertised client address ("host:port" — any member of the
    group; redirects land on it and its mesh replicates inside the
    group).  ``epoch`` totally orders tables: higher epoch wins,
    unconditionally, everywhere (adopt below).  A single-group table
    (every slot owned by gid 0) is the legacy picture — what a
    CONSTDB_CLUSTER=0 node, or any pre-cluster peer, implicitly holds."""

    __slots__ = ("epoch", "owner", "groups")

    def __init__(self, epoch: int = 0, owner=None, groups=None):
        self.epoch = epoch
        self.owner = owner if owner is not None \
            else array("i", bytes(4 * NSLOTS))
        self.groups: dict[int, str] = dict(groups) if groups else {}

    def owner_of(self, slot: int) -> int:
        return self.owner[slot]

    def assign(self, start: int, stop: int, gid: int) -> None:
        """Assign slots [start, stop) to gid (no epoch change — callers
        bump once per atomic ownership flip)."""
        for s in range(start, stop):
            self.owner[s] = gid

    def slots_owned(self, gid: int) -> int:
        return sum(1 for g in self.owner if g == gid)

    def ranges(self) -> list[tuple[int, int, int]]:
        """Contiguous (start, end_inclusive, gid) runs — the CLUSTER
        SLOTS reply shape."""
        out = []
        start = 0
        cur = self.owner[0]
        for s in range(1, NSLOTS):
            g = self.owner[s]
            if g != cur:
                out.append((start, s - 1, cur))
                start, cur = s, g
        out.append((start, NSLOTS - 1, cur))
        return out

    # ------------------------------------------------------------ codec
    # run-length JSON: small (a fresh table is one run), stdlib-only,
    # and self-describing for the CLUSTERTAB gossip frame and the
    # CLUSTER FINALIZE reply.

    def serialize(self) -> bytes:
        return json.dumps({
            "epoch": self.epoch,
            "groups": {str(g): a for g, a in sorted(self.groups.items())},
            "runs": [[a, b, g] for a, b, g in self.ranges()],
        }, separators=(",", ":")).encode()

    @classmethod
    def deserialize(cls, payload: bytes) -> "SlotTable":
        doc = json.loads(payload.decode("utf-8"))
        t = cls(epoch=int(doc["epoch"]),
                groups={int(g): str(a) for g, a in doc["groups"].items()})
        for a, b, g in doc["runs"]:
            t.assign(int(a), int(b) + 1, int(g))
        return t

    def copy(self) -> "SlotTable":
        return SlotTable(self.epoch, array("i", self.owner),
                         dict(self.groups))


def even_split(n_groups: int, addrs=None) -> SlotTable:
    """The bootstrap table: NSLOTS split into n_groups contiguous
    ranges (gid 0..n-1).  ``addrs`` optionally seeds the group address
    map."""
    t = SlotTable(epoch=1)
    per = NSLOTS // max(1, n_groups)
    for g in range(n_groups):
        hi = NSLOTS if g == n_groups - 1 else (g + 1) * per
        t.assign(g * per, hi, g)
    if addrs:
        for g, a in enumerate(addrs):
            if a:
                t.groups[g] = a
    return t


class ClusterState:
    """Per-node cluster view, attached as ``node.cluster`` (None when
    cluster mode is off — every hot-path gate is a single ``is None``
    test, so the disabled cost is one attribute load).

    Holds the slot table, this node's group id, the live migration
    windows (``migrating``: slot -> target addr, the ASK window on the
    source; ``importing``: slot -> source addr, the serve-anyway window
    on the target), the redirect/migration counters INFO reports, and
    the GC migration pin: while any slot is mid-flight, gc_horizon()
    (server/node.py) is clamped at the pin so no tombstone written
    during the handoff is collected before the target holds it — the
    no-resurrection law extended across an ownership flip."""

    __slots__ = ("my_gid", "table", "migrating", "importing",
                 "redirects_sent", "migrations_in", "migrations_out",
                 "_gc_pin", "_import_buf", "_tasks")

    def __init__(self, my_gid: int, table: SlotTable):
        self.my_gid = my_gid
        self.table = table
        self.migrating: dict[int, str] = {}
        self.importing: dict[int, str] = {}
        self.redirects_sent = 0
        self.migrations_in = 0
        self.migrations_out = 0
        self._gc_pin: Optional[int] = None
        self._import_buf: dict[int, bytearray] = {}
        self._tasks: set = set()

    @property
    def epoch(self) -> int:
        return self.table.epoch

    def owns(self, slot: int) -> bool:
        return self.table.owner[slot] == self.my_gid

    def slots_owned(self) -> int:
        return self.table.slots_owned(self.my_gid)

    def addr_of(self, gid: int) -> str:
        return self.table.groups.get(gid, "?")

    # ---------------------------------------------------------- routing

    def needs_redirect(self, key: bytes) -> bool:
        """Counter-free probe of route(): True iff route(key) would
        return a redirect.  The serve coalescer demotes such commands
        out of its planned runs with this, and the ONE counted route()
        call then happens in commands.execute — so pure, native, and
        lone-command intakes produce the identical reply bytes and the
        identical redirects_sent count."""
        slot = slot_of(key)
        if self.table.owner[slot] == self.my_gid:
            return slot in self.migrating
        return slot not in self.importing

    def route(self, key: bytes):
        """None = serve locally; otherwise the exact redirect Err.
        See the module doc for the four-way contract."""
        slot = slot_of(key)
        if self.table.owner[slot] == self.my_gid:
            target = self.migrating.get(slot)
            if target is None:
                return None
            # handoff window: the slot's bulk state is already on the
            # target; new writes must land THERE so the final delta is
            # the whole story (ASK-window exactness law)
            self.redirects_sent += 1
            return Err(b"ASK %d %s" % (slot, target.encode()))
        if slot in self.importing:
            # the ASK target side: serve redirected traffic for a slot
            # we are importing even though the table still names the
            # source as owner
            return None
        self.redirects_sent += 1
        addr = self.addr_of(self.table.owner[slot])
        return Err(b"MOVED %d %s" % (slot, addr.encode()))

    # ------------------------------------------------- table adoption

    def adopt(self, table: SlotTable) -> bool:
        """Adopt a gossiped/finalized table iff it is STRICTLY newer.
        Preserves locally-known group addresses the newer table lacks
        (gossip carries ownership, not necessarily every address)."""
        if table.epoch <= self.table.epoch:
            return False
        merged = dict(self.table.groups)
        merged.update(table.groups)
        table.groups = merged
        self.table = table
        return True

    # ----------------------------------------------------- GC pinning

    def pin_gc(self, uuid: int) -> None:
        """Clamp the tombstone-GC horizon at `uuid` for the duration of
        a migration (lowest pin wins across overlapping migrations)."""
        if self._gc_pin is None or uuid < self._gc_pin:
            self._gc_pin = uuid

    def unpin_gc(self) -> None:
        if not self.migrating and not self.importing:
            self._gc_pin = None

    def gc_pin(self) -> Optional[int]:
        return self._gc_pin

    # ------------------------------------------------------ INFO feed

    def info_pairs(self) -> list[tuple[str, str]]:
        return [
            ("cluster_enabled", "1"),
            ("cluster_group", str(self.my_gid)),
            ("cluster_epoch", str(self.epoch)),
            ("cluster_known_groups", str(len(self.table.groups))),
            ("slots_owned", str(self.slots_owned())),
            ("migrations_in", str(self.migrations_in)),
            ("migrations_out", str(self.migrations_out)),
            ("migrating_slots", str(len(self.migrating))),
            ("importing_slots", str(len(self.importing))),
            ("redirects_sent", str(self.redirects_sent)),
        ]
