"""Slot math + the epoch-versioned slot table + per-node cluster state.

Slot <-> digest-bucket correspondence (the load-bearing trick): the
digest plane partitions keys by ``crc32(key)`` into ``fanout x leaves``
buckets as ``(crc % fanout) * leaves + (crc // fanout) % leaves``
(store/digest.py _buckets).  With the canonical 64x256 geometry,
``fanout * leaves == NSLOTS`` and both coordinates are exact functions
of ``crc % 16384`` — i.e. of the slot — so

    bucket_of_slot(s) == (s % 64) * 256 + s // 64

is a bijection: every slot IS one digest bucket.  Per-slot digest =
one matrix cell; per-slot export = export_bucket_batch with that one
bucket masked (tombstones included).  Migration therefore ships
O(slot bytes), never a full-keyspace snapshot, with convergence
certified by the same digest the delta-sync plane already trusts.

Routing contract (server/commands.py execute + server/serve.py): every
data command is FIRST-KEY-CONFINED (the KEY-CONFINED lint rule pins
this statically), so ``ClusterState.route(key, is_write)`` decides from
the first argument alone:

    owned, not migrating      -> None               (serve locally)
    owned, slot mid-handoff   -> writes: -ASK <slot> <addr> (they drain
                                 to the target, so the final delta is
                                 the whole remaining story); reads:
                                 None (the source's copy holds every
                                 write the source ever acknowledged,
                                 while the target may still lack the
                                 final delta — redirecting a read there
                                 could un-read a committed write)
    not owned, slot importing -> None               (serve: the ASK
                                                      target side)
    not owned                 -> -MOVED <slot> <addr>

Ownership is EPOCH-GATED per slot: every assignment carries the epoch
it was minted at (``SlotTable.slot_epoch``), a migration FINALIZE mints
``max(known)+1`` for exactly its slot, and ``adopt()`` is a per-slot
JOIN — higher ``(epoch, gid)`` wins, gid as the deterministic
tie-break — so two tables minted concurrently at the same epoch MERGE
(both flips survive, any exchange order converges) instead of racing
on who gossips first, and a stale owner converges to redirecting at
its first exchange."""

from __future__ import annotations

import json
import logging
import zlib
from array import array
from typing import Optional

from ..conf import env_int
from ..resp.message import Err

log = logging.getLogger(__name__)

NSLOTS = 16384
# the canonical digest geometry under which slot == bucket (module doc)
SLOT_FANOUT = 64
SLOT_LEAVES = 256
assert SLOT_FANOUT * SLOT_LEAVES == NSLOTS


def slot_of(key: bytes) -> int:
    """The hash slot of a key — the digest plane's crc32, mod NSLOTS."""
    return zlib.crc32(key) % NSLOTS


def bucket_of_slot(slot: int) -> int:
    """The flat 64x256 digest-bucket index holding exactly this slot's
    keys (module doc derivation; property-tested against digest._buckets
    in tests/test_cluster.py)."""
    return (slot % SLOT_FANOUT) * SLOT_LEAVES + slot // SLOT_FANOUT


class SlotTable:
    """Epoch-versioned slot -> group ownership map.

    ``owner[slot]`` is a group id (gid); ``groups`` maps gid to the
    group's advertised client address ("host:port" — any member of the
    group; redirects land on it and its mesh replicates inside the
    group).  ``slot_epoch[slot]`` is the epoch the slot's CURRENT
    assignment was minted at (Redis configEpoch, per slot): adoption
    joins tables per slot on ``(slot_epoch, gid)``, so concurrent
    migrations to different groups can mint the same epoch without the
    meshes diverging — both flips survive the merge.  ``epoch`` is the
    highest mint this table has seen (``max(slot_epoch)``); FINALIZE
    mints from it.  A single-group table (every slot owned by gid 0)
    is the legacy picture — what a CONSTDB_CLUSTER=0 node, or any
    pre-cluster peer, implicitly holds."""

    __slots__ = ("epoch", "owner", "groups", "slot_epoch")

    def __init__(self, epoch: int = 0, owner=None, groups=None,
                 slot_epoch=None):
        self.epoch = epoch
        self.owner = owner if owner is not None \
            else array("i", bytes(4 * NSLOTS))
        self.slot_epoch = slot_epoch if slot_epoch is not None \
            else array("q", [epoch]) * NSLOTS
        self.groups: dict[int, str] = dict(groups) if groups else {}

    def owner_of(self, slot: int) -> int:
        return self.owner[slot]

    def assign(self, start: int, stop: int, gid: int,
               epoch: Optional[int] = None) -> None:
        """Assign slots [start, stop) to gid.  ``epoch`` stamps the
        assignment's mint (FINALIZE passes the bumped value for exactly
        its slot); None leaves the per-slot stamps untouched (bootstrap
        fills them from the table epoch at construction)."""
        for s in range(start, stop):
            self.owner[s] = gid
            if epoch is not None:
                self.slot_epoch[s] = epoch

    def slots_owned(self, gid: int) -> int:
        return sum(1 for g in self.owner if g == gid)

    def ranges(self) -> list[tuple[int, int, int]]:
        """Contiguous (start, end_inclusive, gid) runs — the CLUSTER
        SLOTS reply shape."""
        out = []
        start = 0
        cur = self.owner[0]
        for s in range(1, NSLOTS):
            g = self.owner[s]
            if g != cur:
                out.append((start, s - 1, cur))
                start, cur = s, g
        out.append((start, NSLOTS - 1, cur))
        return out

    def epoch_runs(self) -> list[tuple[int, int, int, int]]:
        """Contiguous (start, end_inclusive, gid, slot_epoch) runs —
        the codec shape (the join needs the per-slot mints)."""
        out = []
        start = 0
        cur = (self.owner[0], self.slot_epoch[0])
        for s in range(1, NSLOTS):
            nxt = (self.owner[s], self.slot_epoch[s])
            if nxt != cur:
                out.append((start, s - 1) + cur)
                start, cur = s, nxt
        out.append((start, NSLOTS - 1) + cur)
        return out

    # ------------------------------------------------------------ codec
    # run-length JSON: small (a fresh table is one run), stdlib-only,
    # and self-describing for the CLUSTERTAB gossip frame and the
    # CLUSTER FINALIZE reply.

    def serialize(self) -> bytes:
        return json.dumps({
            "epoch": self.epoch,
            "groups": {str(g): a for g, a in sorted(self.groups.items())},
            "runs": [[a, b, g, e] for a, b, g, e in self.epoch_runs()],
        }, separators=(",", ":")).encode()

    @classmethod
    def deserialize(cls, payload: bytes) -> "SlotTable":
        doc = json.loads(payload.decode("utf-8"))
        t = cls(epoch=int(doc["epoch"]),
                groups={int(g): str(a) for g, a in doc["groups"].items()})
        for run in doc["runs"]:
            a, b, g = int(run[0]), int(run[1]), int(run[2])
            # 3-element runs predate per-slot mints: stamp the table
            # epoch, the strongest claim the old format could make
            e = int(run[3]) if len(run) > 3 else t.epoch
            t.assign(a, b + 1, g, epoch=e)
        return t

    def copy(self) -> "SlotTable":
        return SlotTable(self.epoch, array("i", self.owner),
                         dict(self.groups),
                         array("q", self.slot_epoch))


def even_split(n_groups: int, addrs=None) -> SlotTable:
    """The bootstrap table: NSLOTS split into n_groups contiguous
    ranges (gid 0..n-1).  ``addrs`` optionally seeds the group address
    map."""
    t = SlotTable(epoch=1)
    per = NSLOTS // max(1, n_groups)
    for g in range(n_groups):
        hi = NSLOTS if g == n_groups - 1 else (g + 1) * per
        t.assign(g * per, hi, g)
    if addrs:
        for g, a in enumerate(addrs):
            if a:
                t.groups[g] = a
    return t


class ClusterState:
    """Per-node cluster view, attached as ``node.cluster`` (None when
    cluster mode is off — every hot-path gate is a single ``is None``
    test, so the disabled cost is one attribute load).

    Holds the slot table, this node's group id, the live migration
    windows (``migrating``: slot -> target addr, the ASK window on the
    source; ``importing``: slot -> source addr, the serve-anyway window
    on the target), the redirect/migration counters INFO reports, and
    the GC migration pins: while any migration or import window is in
    flight, gc_horizon() (server/node.py) is clamped at the lowest pin
    so no tombstone written during the handoff is collected before the
    target holds it — the no-resurrection law extended across an
    ownership flip.  ``rev`` counts local table changes (adoptions,
    finalizes, address learning) — the gossip loop's re-broadcast
    trigger, deliberately finer than ``epoch`` because a join can
    change ownership without minting a new epoch."""

    __slots__ = ("my_gid", "table", "migrating", "importing",
                 "redirects_sent", "migrations_in", "migrations_out",
                 "rev", "import_stall_s", "_gc_pins", "_import_buf",
                 "_import_pins", "_import_touch", "_export_buf",
                 "_tasks", "on_slots_lost")

    def __init__(self, my_gid: int, table: SlotTable):
        self.my_gid = my_gid
        self.table = table
        # called with the set of slots whose ownership just moved AWAY
        # from this group (adopt) — the tracking registry invalidates
        # every tracked key in them (server/tracking.py slots_lost):
        # their future writes land on the new owner, so this node can
        # never keep the one-shot invalidation promise for them
        self.on_slots_lost = None
        self.migrating: dict[int, str] = {}
        self.importing: dict[int, str] = {}
        self.redirects_sent = 0
        self.migrations_in = 0
        self.migrations_out = 0
        self.rev = 0
        self.import_stall_s = float(env_int("CONSTDB_MIGRATE_STALL_S",
                                            120))
        self._gc_pins: list[int] = []
        self._import_buf: dict[int, bytearray] = {}
        self._import_pins: dict[int, int] = {}
        self._import_touch: dict[int, float] = {}
        self._export_buf: dict[int, bytes] = {}
        self._tasks: set = set()

    @property
    def epoch(self) -> int:
        return self.table.epoch

    def owns(self, slot: int) -> bool:
        return self.table.owner[slot] == self.my_gid

    def slots_owned(self) -> int:
        return self.table.slots_owned(self.my_gid)

    def addr_of(self, gid: int) -> str:
        return self.table.groups.get(gid, "?")

    # ---------------------------------------------------------- routing

    def needs_redirect(self, key: bytes, is_write: bool = True) -> bool:
        """Counter-free probe of route(): True iff route(key, is_write)
        would return a redirect.  The serve coalescer demotes such
        commands out of its planned runs with this, and the ONE counted
        route() call then happens in commands.execute — so pure,
        native, and lone-command intakes produce the identical reply
        bytes and the identical redirects_sent count."""
        slot = slot_of(key)
        if self.table.owner[slot] == self.my_gid:
            return is_write and slot in self.migrating
        return slot not in self.importing

    def route(self, key: bytes, is_write: bool = True):
        """None = serve locally; otherwise the exact redirect Err.
        See the module doc for the four-way contract."""
        slot = slot_of(key)
        if self.table.owner[slot] == self.my_gid:
            target = self.migrating.get(slot)
            if target is None or not is_write:
                # reads keep serving from the source during the handoff
                # window: its copy holds every write this group ever
                # acknowledged, while the target may still lack the
                # final delta — redirecting a read there could un-read
                # a committed write.  ASK-window exactness is a WRITE
                # law: only writes must drain to the target.
                return None
            self.redirects_sent += 1
            return Err(b"ASK %d %s" % (slot, target.encode()))
        if slot in self.importing:
            # the ASK target side: serve redirected traffic for a slot
            # we are importing even though the table still names the
            # source as owner
            return None
        self.redirects_sent += 1
        addr = self.addr_of(self.table.owner[slot])
        return Err(b"MOVED %d %s" % (slot, addr.encode()))

    # ------------------------------------------------- table adoption

    def adopt(self, table: SlotTable) -> bool:
        """Join a gossiped/finalized table into ours, PER SLOT: the
        assignment with the higher ``(slot_epoch, gid)`` wins — epoch
        first, gid as the deterministic tie-break (Redis configEpoch
        collision handling).  The join is commutative, associative and
        idempotent, so two tables minted concurrently at the same epoch
        merge identically in any exchange order — both flips survive —
        where a whole-table higher-epoch-wins rule would drop one
        (ownership regression).  Locally-known group addresses the
        incoming table lacks are preserved (gossip carries ownership,
        not necessarily every address).  Returns True iff anything
        changed; ``rev`` advances with it so the gossip loops
        re-broadcast joins that do not mint a new epoch."""
        mine = self.table
        mo, me = mine.owner, mine.slot_epoch
        to, te = table.owner, table.slot_epoch
        changed = False
        gid = self.my_gid
        lost: set = set()
        for s in range(NSLOTS):
            e, g = te[s], to[s]
            if e > me[s] or (e == me[s] and g > mo[s]):
                if mo[s] == gid and g != gid:
                    lost.add(s)
                mo[s], me[s] = g, e
                changed = True
        if lost and self.on_slots_lost is not None:
            self.on_slots_lost(lost)
        if table.epoch > mine.epoch:
            mine.epoch = table.epoch
            changed = True
        for g, a in table.groups.items():
            if mine.groups.get(g) != a:
                mine.groups[g] = a
                changed = True
        if changed:
            self.rev += 1
        return changed

    # ----------------------------------------------------- GC pinning

    def pin_gc(self, uuid: int) -> int:
        """Clamp the tombstone-GC horizon at `uuid` until the matching
        ``unpin_gc(uuid)``.  Pins are a MULTISET — every in-flight
        migration (source side, from before its first await) and every
        import window (target side) holds its own pin, and gc_horizon
        clamps at the min — so one migration finishing or aborting can
        never release a pin a concurrent one still needs."""
        self._gc_pins.append(uuid)
        return uuid

    def unpin_gc(self, uuid: int) -> None:
        """Release ONE holder's pin (no-op if already released — abort
        paths may race their own cleanup)."""
        try:
            self._gc_pins.remove(uuid)
        except ValueError:
            pass

    def gc_pin(self) -> Optional[int]:
        return min(self._gc_pins) if self._gc_pins else None

    # ------------------------------------------- import-window lifecycle

    def open_import(self, slot: int, source: str, pin_uuid: int,
                    now: float) -> None:
        """Mark `slot` importing from `source`: GC pin (once — a
        RETRIED migration re-marks the slot and must not stack a second
        pin on the same window), staleness stamp, and a clean chunk
        buffer (a partial buffer from a dead attempt would corrupt the
        fresh stream's decode)."""
        if slot not in self._import_pins:
            self._import_pins[slot] = self.pin_gc(pin_uuid)
        self.importing[slot] = source
        self._import_buf.pop(slot, None)
        self._import_touch[slot] = now

    def touch_import(self, slot: int, now: float) -> None:
        self._import_touch[slot] = now

    def drop_import(self, slot: int) -> bool:
        """Close an import window: forget the mark, the partial chunk
        buffer, the staleness stamp, and release the window's GC pin.
        Idempotent — FINALIZE, the source's abort path (SETSLOT
        STABLE), and the staleness sweep can all reach it."""
        self._import_touch.pop(slot, None)
        self._import_buf.pop(slot, None)
        self._export_buf.pop(slot, None)
        pin = self._import_pins.pop(slot, None)
        if pin is not None:
            self.unpin_gc(pin)
        return self.importing.pop(slot, None) is not None

    def expire_stale_imports(self, now: float) -> None:
        """Target-side failure path: a source that dies after SETSLOT
        IMPORTING never sends STABLE or FINALIZE, and without this
        sweep the window would serve the slot's partial copy and pin
        tombstone GC forever.  Driven from node.gc_horizon() (the same
        periodic pulse GC itself rides); every IMPORT chunk refreshes
        the stamp, so only a silent source trips it."""
        if not self.importing:
            return
        stale = [s for s, t in self._import_touch.items()
                 if now - t > self.import_stall_s]
        for s in stale:
            log.warning(
                "import window for slot %d went silent for %.0fs; "
                "dropping the window and its GC pin (source %s "
                "presumed dead — a retried migration re-opens cleanly)",
                s, self.import_stall_s, self.importing.get(s, "?"))
            self.drop_import(s)

    # ------------------------------------------------------ INFO feed

    def info_pairs(self) -> list[tuple[str, str]]:
        return [
            ("cluster_enabled", "1"),
            ("cluster_group", str(self.my_gid)),
            ("cluster_epoch", str(self.epoch)),
            ("cluster_known_groups", str(len(self.table.groups))),
            ("slots_owned", str(self.slots_owned())),
            ("migrations_in", str(self.migrations_in)),
            ("migrations_out", str(self.migrations_out)),
            ("migrating_slots", str(len(self.migrating))),
            ("importing_slots", str(len(self.importing))),
            ("redirects_sent", str(self.redirects_sent)),
        ]
