"""The CLUSTER admin + migration command family.

Registered against the ONE dispatch table (server/commands.py) like the
membership commands (replica/commands.py): flags CTRL (takes
subcommands, not keys — shard_routable() and the slot router both skip
it) + WRITE (the import/finalize arms mutate state) + NO_REPLICATE (a
migration intake is STATE transfer, not an op — re-replicating it would
re-broadcast a foreign group's keys into ours, exactly what cluster
mode removes; and merges never adopt watermarks, preserving the
emit-only-durable law across the move).

Observability arms (INFO / SLOTS / SLOTDIGEST) answer on any node;
mutation arms require cluster mode on.  The migration wire protocol
(SETSLOT IMPORTING -> IMPORT chunks -> SLOTDIGEST -> FINALIZE) is
driven by cluster/migrate.py on the source."""

from __future__ import annotations

import asyncio
import logging

from ..errors import CstError, UnknownSubCmd
from ..resp.message import Arr, Bulk, Err, Int, OK
from ..server.commands import (CMD_CTRL, CMD_NO_REPLICATE, CMD_WRITE,
                               register)
from .slots import NSLOTS

log = logging.getLogger(__name__)

_OFF_ERR = b"cluster mode is off (CONSTDB_CLUSTER=0)"


def _slot_arg(args) -> int:
    slot = args.next_int()
    if not 0 <= slot < NSLOTS:
        raise CstError(f"slot {slot} out of range (0..{NSLOTS - 1})")
    return slot


@register("cluster", CMD_WRITE | CMD_CTRL | CMD_NO_REPLICATE)
def cluster_command(node, ctx, args):
    sub = args.next_bytes().lower()
    cl = node.cluster
    if sub == b"info":
        pairs = cl.info_pairs() if cl is not None \
            else [("cluster_enabled", "0")]
        return Bulk("".join(f"{k}:{v}\r\n" for k, v in pairs).encode())
    if cl is None:
        return Err(_OFF_ERR)
    if sub == b"slots":
        return Arr([Arr([Int(a), Int(b), Int(g),
                         Bulk(cl.addr_of(g).encode())])
                    for a, b, g in cl.table.ranges()])
    if sub == b"slotdigest":
        from .migrate import slot_digest
        return Bulk(b"%d" % slot_digest(node, _slot_arg(args)))
    if sub == b"setaddr":
        # address book entry for a group (bootstrap/ops; gossip merges
        # addresses on adopt, so one MEET-style seeding per node is
        # enough).  No epoch bump: addresses are not ownership.
        gid = args.next_int()
        cl.table.groups[gid] = args.next_str()
        return OK
    if sub == b"setslot":
        slot = _slot_arg(args)
        verb = args.next_bytes().lower()
        if verb != b"importing":
            raise UnknownSubCmd(f"setslot {verb.decode('utf-8', 'replace')}")
        args.next_int()  # source epoch (diagnostic; flip is epoch-gated
        #                  by FINALIZE, not by this intake mark)
        source = args.next_str()
        cl.importing[slot] = source
        # a RETRIED migration (the first attempt's channel died mid-
        # chunk) re-marks the slot; any partial chunk buffer from the
        # dead attempt would corrupt the fresh stream's decode
        cl._import_buf.pop(slot, None)
        # tombstone-GC pin mirrors the source's: nothing collected on
        # the target either while the slot's story is still arriving
        cl.pin_gc(node.hlc.current)
        return OK
    if sub == b"import":
        slot = _slot_arg(args)
        more = args.next_int()
        chunk = args.next_bytes()
        if slot not in cl.importing:
            return Err(b"IMPORT for a slot not marked importing")
        buf = cl._import_buf.setdefault(slot, bytearray())
        buf += chunk
        if more:
            return Int(len(buf))
        payload = bytes(cl._import_buf.pop(slot))
        from ..persist.snapshot import _decode_batch
        batch = _decode_batch(payload)
        # state merge, NOT op replay: no repl-log append, no watermark
        # adoption — the batch carries the slot's rows + tombstones and
        # lands through the same engine seam snapshot ingest uses
        node.merge_batches([batch])
        return Int(len(payload))
    if sub == b"finalize":
        slot = _slot_arg(args)
        if slot not in cl.importing:
            return Err(b"FINALIZE for a slot not marked importing")
        table = cl.table.copy()
        table.assign(slot, slot + 1, cl.my_gid)
        table.epoch += 1
        app = node.app
        if app is not None and getattr(app, "advertised_addr", None):
            table.groups[cl.my_gid] = app.advertised_addr
        # the atomic flip: table swap + import-window close together,
        # before the reply carrying the new table leaves this handler
        cl.table = table
        cl.importing.pop(slot, None)
        cl.migrations_in += 1
        cl.unpin_gc()
        return Bulk(table.serialize())
    if sub == b"migrate":
        # source-side admin entry: schedule the async driver; progress
        # is observable via CLUSTER INFO (migrations_out / migrating_
        # slots) and INFO's Cluster section
        start = _slot_arg(args)
        stop = args.next_int()  # exclusive; start+1 migrates one slot
        target = args.next_str()
        from .migrate import migrate_slot_range
        app = node.app
        if app is None:
            return Err(b"MIGRATE needs a serving app context")
        task = asyncio.get_running_loop().create_task(
            migrate_slot_range(node, app, start, stop, target))
        cl._tasks.add(task)
        task.add_done_callback(cl._tasks.discard)
        task.add_done_callback(_log_migrate_result)
        return OK
    raise UnknownSubCmd(sub.decode("utf-8", "replace"))


def _log_migrate_result(task) -> None:
    try:
        st = task.result()
    except asyncio.CancelledError:
        pass
    except Exception as e:
        log.warning("slot migration failed: %s", e)
    else:
        log.info("slot migration done: %s", st)
