"""The CLUSTER admin + migration command family.

Registered against the ONE dispatch table (server/commands.py) like the
membership commands (replica/commands.py): flags CTRL (takes
subcommands, not keys — shard_routable() and the slot router both skip
it) + WRITE (the import/finalize arms mutate state) + NO_REPLICATE (a
migration intake is STATE transfer, not an op — re-replicating it would
re-broadcast a foreign group's keys into ours, exactly what cluster
mode removes; and merges never adopt watermarks, preserving the
emit-only-durable law across the move).

Observability arms (INFO / SLOTS / SLOTDIGEST) answer on any node;
mutation arms require cluster mode on.  The migration wire protocol
(SETSLOT IMPORTING -> IMPORT chunks -> SLOTDIGEST -> FINALIZE, with
SETSLOT STABLE + SLOTEXPORT as the abort legs) is driven by
cluster/migrate.py on the source."""

from __future__ import annotations

import asyncio
import logging
import time

from ..errors import CstError, UnknownSubCmd
from ..resp.message import Arr, Bulk, Err, Int, OK
from ..server.commands import (CMD_CTRL, CMD_NO_REPLICATE, CMD_WRITE,
                               register)
from .slots import NSLOTS

log = logging.getLogger(__name__)

_OFF_ERR = b"cluster mode is off (CONSTDB_CLUSTER=0)"


def _slot_arg(args) -> int:
    slot = args.next_int()
    if not 0 <= slot < NSLOTS:
        raise CstError(f"slot {slot} out of range (0..{NSLOTS - 1})")
    return slot


@register("cluster", CMD_WRITE | CMD_CTRL | CMD_NO_REPLICATE)
def cluster_command(node, ctx, args):
    sub = args.next_bytes().lower()
    cl = node.cluster
    if sub == b"info":
        pairs = cl.info_pairs() if cl is not None \
            else [("cluster_enabled", "0")]
        return Bulk("".join(f"{k}:{v}\r\n" for k, v in pairs).encode())
    if cl is None:
        return Err(_OFF_ERR)
    if sub == b"slots":
        return Arr([Arr([Int(a), Int(b), Int(g),
                         Bulk(cl.addr_of(g).encode())])
                    for a, b, g in cl.table.ranges()])
    if sub == b"slotdigest":
        from .migrate import slot_digest
        return Bulk(b"%d" % slot_digest(node, _slot_arg(args)))
    if sub == b"setaddr":
        # address book entry for a group (bootstrap/ops; gossip merges
        # addresses on adopt, so one MEET-style seeding per node is
        # enough).  No epoch bump: addresses are not ownership.
        gid = args.next_int()
        cl.table.groups[gid] = args.next_str()
        cl.rev += 1  # gossip re-broadcasts the learned address
        return OK
    if sub == b"setslot":
        slot = _slot_arg(args)
        verb = args.next_bytes().lower()
        if verb == b"stable":
            # the source's abort verb: close the import window (mark,
            # chunk buffer, staleness stamp, GC pin) whether or not one
            # is open — idempotent, so retries and the staleness sweep
            # can race it safely.  From here redirected traffic bounces
            # MOVED back at the settled owner instead of being acked
            # into a window that will never finalize.
            cl.drop_import(slot)
            return OK
        if verb != b"importing":
            raise UnknownSubCmd(f"setslot {verb.decode('utf-8', 'replace')}")
        args.next_int()  # source epoch (diagnostic; flip is epoch-gated
        #                  by FINALIZE, not by this intake mark)
        source = args.next_str()
        # tombstone-GC pin mirrors the source's: nothing collected on
        # the target either while the slot's story is still arriving.
        # A RETRIED migration re-marks the slot: the buffer resets (a
        # partial chunk from the dead attempt would corrupt the fresh
        # stream's decode) but the pin does NOT stack (open_import).
        cl.open_import(slot, source, node.hlc.current, time.monotonic())
        return OK
    if sub == b"import":
        slot = _slot_arg(args)
        more = args.next_int()
        chunk = args.next_bytes()
        if slot not in cl.importing:
            return Err(b"IMPORT for a slot not marked importing")
        cl.touch_import(slot, time.monotonic())
        buf = cl._import_buf.setdefault(slot, bytearray())
        buf += chunk
        if more:
            return Int(len(buf))
        payload = bytes(cl._import_buf.pop(slot))
        from ..persist.snapshot import _decode_batch
        batch = _decode_batch(payload)
        # state merge, NOT op replay: no repl-log append, no watermark
        # adoption — the batch carries the slot's rows + tombstones and
        # lands through the same engine seam snapshot ingest uses
        node.merge_batches([batch])
        return Int(len(payload))
    if sub == b"slotexport":
        # the reverse leg of the source's abort path (cluster/migrate.py
        # _reclaim_ask_window): chunked export of this node's copy of
        # the slot, so a source aborting AFTER its ASK window opened can
        # reclaim the writes only this node acknowledged.  Offset 0
        # snapshots the encoded batch — every chunk of one export
        # describes ONE state cut even while this node keeps serving —
        # and the final chunk drops the snapshot.
        slot = _slot_arg(args)
        off = args.next_int()
        maxb = max(1, args.next_int())
        if off == 0:
            from ..persist.snapshot import _encode_batch
            from .migrate import export_slot_batch
            cl._export_buf[slot] = bytes(
                _encode_batch(export_slot_batch(node, slot)))
        payload = cl._export_buf.get(slot)
        if payload is None:
            return Err(b"SLOTEXPORT at a nonzero offset without a "
                       b"snapshot (restart from offset 0)")
        chunk = payload[off:off + maxb]
        more = 1 if off + len(chunk) < len(payload) else 0
        if not more:
            cl._export_buf.pop(slot, None)
        return Arr([Int(more), Bulk(chunk)])
    if sub == b"finalize":
        slot = _slot_arg(args)
        if slot not in cl.importing:
            return Err(b"FINALIZE for a slot not marked importing")
        table = cl.table.copy()
        # mint STRICTLY above every epoch this node knows, and stamp it
        # on exactly the flipped slot: two concurrent migrations to
        # different groups may still mint the same number, but adopt()'s
        # per-slot (epoch, gid) join merges those tables instead of
        # dropping one — no collision resolution protocol needed
        epoch = table.epoch + 1
        table.assign(slot, slot + 1, cl.my_gid, epoch=epoch)
        table.epoch = epoch
        app = node.app
        if app is not None and getattr(app, "advertised_addr", None):
            table.groups[cl.my_gid] = app.advertised_addr
        # the atomic flip: table swap + import-window close together,
        # before the reply carrying the new table leaves this handler
        cl.table = table
        cl.rev += 1
        cl.drop_import(slot)
        cl.migrations_in += 1
        return Bulk(table.serialize())
    if sub == b"migrate":
        # source-side admin entry: schedule the async driver; progress
        # is observable via CLUSTER INFO (migrations_out / migrating_
        # slots) and INFO's Cluster section
        start = _slot_arg(args)
        stop = args.next_int()  # exclusive; start+1 migrates one slot
        target = args.next_str()
        from .migrate import migrate_slot_range
        app = node.app
        if app is None:
            return Err(b"MIGRATE needs a serving app context")
        task = asyncio.get_running_loop().create_task(
            migrate_slot_range(node, app, start, stop, target))
        cl._tasks.add(task)
        task.add_done_callback(cl._tasks.discard)
        task.add_done_callback(_log_migrate_result)
        return OK
    raise UnknownSubCmd(sub.decode("utf-8", "replace"))


def _log_migrate_result(task) -> None:
    try:
        st = task.result()
    except asyncio.CancelledError:
        pass
    except Exception as e:
        log.warning("slot migration failed: %s", e)
    else:
        log.info("slot migration done: %s", st)
