"""Cluster mode: hash-slot keyspace partitioning across replication
groups (ROADMAP item 1 — beyond one box).

The keyspace is split into NSLOTS=16384 hash slots by the SAME crc32
the digest plane partitions on (store/digest.py), so with the canonical
64x256 geometry every slot IS one digest bucket: per-slot digests and
per-slot ColumnarBatch exports come free from the PR 7 machinery, and a
migrating slot is just a replica that catches up by delta then flips
ownership at an epoch bump (docs/INVARIANTS.md "Slot ownership laws").

Layout:
  * slots.py     — slot math, the epoch-versioned SlotTable, ClusterState
                   (routing: None | MOVED | ASK), CLUSTERTAB codec
  * migrate.py   — live slot migration driver (source side) riding the
                   digest->delta path over the command plane
  * commands.py  — the CLUSTER admin/migration command family

Disabled (CONSTDB_CLUSTER=0, the default) the subsystem does not exist:
node.cluster stays None, no capability bit is advertised, and every
wire byte is exactly the pre-cluster single-group stream (pinned by
tests/test_cluster.py)."""

from .slots import (NSLOTS, SLOT_FANOUT, SLOT_LEAVES, ClusterState,
                    SlotTable, bucket_of_slot, even_split, slot_of)

__all__ = ["NSLOTS", "SLOT_FANOUT", "SLOT_LEAVES", "ClusterState",
           "SlotTable", "bucket_of_slot", "even_split", "slot_of"]
