"""Live slot migration — the source-side driver.

A migrating slot is a replica that catches up by delta and then flips
ownership (ISSUE 19 / the certified-MRDT correspondence): the source
streams the slot's digest bucket as a ColumnarBatch (store/digest.py
export_bucket_batch — O(slot bytes), tombstones included) over the
COMMAND plane to any member of the target group, re-exports to catch
up while it keeps serving, then opens the ASK handoff window (new
client writes drain to the target), ships the final delta, proves the
target's coverage with a per-slot digest fixpoint (re-merging the full
export leaves the target's digest unchanged — CRDT idempotence, so
target >= frozen source), and finalizes: the target
assigns itself the slot at a bumped epoch and returns the new table,
which the source adopts and gossip (CLUSTERTAB, replica/link.py)
spreads through both groups' meshes.

Why the command plane and not a repl link: the target is in a
DIFFERENT replication group — there is deliberately no repl stream
between groups (that full-mesh stream is exactly what cluster mode
removes).  The migration connection is a plain RESP client of the
CLUSTER command family (cluster/commands.py), dialed through
app.open_peer_connection so the chaos transport can partition it like
any other link.

Safety laws (docs/INVARIANTS.md "Slot ownership laws"):
  * every ownership mutation re-validates the live epoch after each
    await (the SLOT-EPOCH lint rule pins this shape) — a table adopted
    mid-migration aborts the flip instead of racing it;
  * the GC horizon is pinned below the migration start for its whole
    duration (server/node.py gc_horizon), so a delete landing during
    the handoff is still present — as a tombstone — in the final
    export, and the key cannot resurrect across the flip;
  * the import path merges state batches WITHOUT adopting watermarks
    and WITHOUT re-replication (CMD_NO_REPLICATE), so the emit-only-
    durable law and the repl-log cursor discipline survive the move.
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from ..errors import CstError
from ..resp.codec import encode_msg, make_parser
from ..resp.message import Arr, Bulk, Err, Int, as_bytes
from .slots import NSLOTS, SLOT_FANOUT, SLOT_LEAVES, bucket_of_slot

log = logging.getLogger(__name__)

# catch-up re-export rounds before the ASK window opens; the window
# itself makes the final round exact, so this only bounds how much the
# last delta has to carry
_CATCHUP_ROUNDS = 2
_DIGEST_RETRIES = 5


def slot_digest(node, slot: int) -> int:
    """This node's digest cell for `slot` (flush-first; the 64x256
    geometry under which slot == bucket — cluster/slots.py)."""
    from ..store.digest import state_digest_matrix
    node.ensure_flushed()
    m = state_digest_matrix(node.ks, SLOT_FANOUT, SLOT_LEAVES)
    return int(m.reshape(-1)[bucket_of_slot(slot)])


def export_slot_batch(node, slot: int):
    """The slot's whole logical state as one ColumnarBatch (live rows +
    key tombstones) — O(slot bytes) by construction."""
    from ..store.digest import export_bucket_batch
    node.ensure_flushed()
    mask = np.zeros(NSLOTS, dtype=bool)
    mask[bucket_of_slot(slot)] = True
    return export_bucket_batch(node.ks, SLOT_FANOUT, SLOT_LEAVES, mask)


def migrate_batch_bytes(app) -> int:
    """Wire chunk size for CLUSTER IMPORT payloads (CONSTDB_MIGRATE_
    BATCH_MB): bounds the largest single frame the migration writes, so
    a big slot streams as many bounded frames instead of one giant one."""
    mb = getattr(app, "migrate_batch_mb", None)
    if mb is None:
        from ..conf import env_int
        mb = env_int("CONSTDB_MIGRATE_BATCH_MB", 8)
    return max(1, mb) << 20


class _Chan:
    """One RESP request/response channel to the migration target."""

    def __init__(self, reader, writer, timeout: float):
        self.reader = reader
        self.writer = writer
        self.parser = make_parser()
        self.timeout = timeout

    async def call(self, *parts):
        items = [p if isinstance(p, (Bulk, Int)) else Bulk(p)
                 for p in parts]
        self.writer.write(encode_msg(Arr(items)))
        await self.writer.drain()
        while True:
            msg = self.parser.next_msg()
            if msg is not None:
                if isinstance(msg, Err):
                    raise CstError("migration target error: "
                                   + msg.val.decode("utf-8", "replace"))
                return msg
            data = await asyncio.wait_for(self.reader.read(1 << 16),
                                          self.timeout)
            if not data:
                raise ConnectionError("migration target EOF")
            self.parser.feed(data)

    def close(self):
        try:
            self.writer.close()
        except Exception:
            pass


async def _ship_slot(chan: _Chan, node, slot: int, chunk_bytes: int) -> int:
    """Export + stream one round of the slot's state; returns payload
    bytes shipped."""
    from ..persist.snapshot import _encode_batch
    payload = bytes(_encode_batch(export_slot_batch(node, slot)))
    total = len(payload)
    off = 0
    while True:
        chunk = payload[off:off + chunk_bytes]
        off += len(chunk)
        more = 1 if off < total else 0
        await chan.call(b"cluster", b"import", b"%d" % slot,
                        b"%d" % more, Bulk(chunk))
        if not more:
            return total


async def migrate_slot(node, app, slot: int, target_addr: str, *,
                       timeout: float = 30.0) -> dict:
    """Drive one slot's migration to `target_addr` (any member of the
    target group).  Returns {"slot", "bytes", "rounds", "epoch"} for the
    bench/ops surface.  Raises on any epoch race or digest mismatch —
    ownership never flips on an unproven copy."""
    cl = node.cluster
    if cl is None:
        raise CstError("cluster mode is off")
    if not 0 <= slot < NSLOTS:
        raise CstError(f"slot {slot} out of range")
    if not cl.owns(slot):
        raise CstError(f"slot {slot} not owned by this group")
    if slot in cl.migrating:
        raise CstError(f"slot {slot} already migrating")
    epoch0 = cl.epoch
    # pin tombstone GC below every op the migration window can produce:
    # a delete landing mid-handoff must still be a visible tombstone in
    # the final export (no-resurrection across the flip)
    cl.pin_gc(node.hlc.current)
    chunk_bytes = migrate_batch_bytes(app)
    host, port = target_addr.rsplit(":", 1)
    shipped = rounds = 0
    reader, writer = await asyncio.wait_for(
        app.open_peer_connection(host, int(port)), timeout)
    chan = _Chan(reader, writer, timeout)
    try:
        if node.cluster is not cl or cl.epoch != epoch0:
            raise CstError("slot table changed while dialing; aborting")
        await chan.call(b"cluster", b"setslot", b"%d" % slot,
                        b"importing", b"%d" % epoch0,
                        app.advertised_addr.encode())
        # bulk + catch-up rounds while still serving the slot
        for _ in range(1 + _CATCHUP_ROUNDS):
            if node.cluster is not cl or cl.epoch != epoch0:
                raise CstError("slot table changed mid-migration; aborting")
            shipped += await _ship_slot(chan, node, slot, chunk_bytes)
            rounds += 1
        if node.cluster is not cl or cl.epoch != epoch0:
            raise CstError("slot table changed mid-migration; aborting")
        # ASK handoff window: from here every new client write on the
        # slot redirects to the target, so the final export is the
        # whole remaining story
        cl.migrating[slot] = target_addr
        try:
            # convergence certificate: the flip is safe iff the target
            # holds EVERYTHING the (now frozen — ASK redirects all new
            # writes) source copy holds.  The target may legally hold
            # MORE (ASK-window writes land there), so source-vs-target
            # digest equality is the wrong test; instead we use CRDT
            # idempotence as a fixpoint probe — if re-merging the
            # slot's full export leaves the target's per-slot digest
            # unchanged, the export was a no-op and target >= source.
            for attempt in range(_DIGEST_RETRIES):
                if node.cluster is not cl or cl.epoch != epoch0:
                    raise CstError(
                        "slot table changed mid-handoff; aborting")
                before = int(as_bytes(await chan.call(
                    b"cluster", b"slotdigest", b"%d" % slot)))
                shipped += await _ship_slot(chan, node, slot, chunk_bytes)
                rounds += 1
                after = int(as_bytes(await chan.call(
                    b"cluster", b"slotdigest", b"%d" % slot)))
                if after == before:
                    break
            else:
                raise CstError(
                    f"slot {slot} digest never reached its fixpoint on "
                    f"{target_addr} after {_DIGEST_RETRIES} deltas")
            if node.cluster is not cl or cl.epoch != epoch0:
                raise CstError("slot table changed pre-finalize; aborting")
            # the flip: the target assigns itself the slot at a bumped
            # epoch and returns the table; adopting it atomically turns
            # our ASK window into a plain MOVED
            reply = await chan.call(b"cluster", b"finalize", b"%d" % slot)
            from .slots import SlotTable
            table = SlotTable.deserialize(as_bytes(reply))
            if table.epoch <= epoch0 or \
                    table.owner[slot] == cl.my_gid:
                raise CstError("finalize returned a non-advancing table")
        finally:
            cl.migrating.pop(slot, None)
        cl.adopt(table)
        cl.migrations_out += 1
        log.info("slot %d migrated to %s: %d bytes over %d rounds, "
                 "epoch %d -> %d", slot, target_addr, shipped, rounds,
                 epoch0, table.epoch)
        return {"slot": slot, "bytes": shipped, "rounds": rounds,
                "epoch": table.epoch}
    finally:
        cl.unpin_gc()
        chan.close()


async def migrate_slot_range(node, app, start: int, stop: int,
                             target_addr: str, **kw) -> dict:
    """Migrate slots [start, stop) sequentially; aggregate stats."""
    total = {"slots": 0, "bytes": 0, "rounds": 0}
    for slot in range(start, stop):
        cl = node.cluster
        if cl is not None and not cl.owns(slot):
            continue  # already elsewhere (flap/retry idempotence)
        st = await migrate_slot(node, app, slot, target_addr, **kw)
        total["slots"] += 1
        total["bytes"] += st["bytes"]
        total["rounds"] += st["rounds"]
        total["epoch"] = st["epoch"]
    return total
