"""Live slot migration — the source-side driver.

A migrating slot is a replica that catches up by delta and then flips
ownership (ISSUE 19 / the certified-MRDT correspondence): the source
streams the slot's digest bucket as a ColumnarBatch (store/digest.py
export_bucket_batch — O(slot bytes), tombstones included) over the
COMMAND plane to any member of the target group, re-exports to catch
up while it keeps serving, then opens the ASK handoff window (new
client writes drain to the target), ships the final delta, proves the
target's coverage with a per-slot digest fixpoint (re-merging the full
export leaves the target's digest unchanged — CRDT idempotence, so
target >= frozen source), and finalizes: the target
assigns itself the slot at a bumped epoch and returns the new table,
which the source adopts and gossip (CLUSTERTAB, replica/link.py)
spreads through both groups' meshes.

Why the command plane and not a repl link: the target is in a
DIFFERENT replication group — there is deliberately no repl stream
between groups (that full-mesh stream is exactly what cluster mode
removes).  The migration connection is a plain RESP client of the
CLUSTER command family (cluster/commands.py), dialed through
app.open_peer_connection so the chaos transport can partition it like
any other link.

Safety laws (docs/INVARIANTS.md "Slot ownership laws"):
  * every ownership mutation re-validates the live epoch after each
    await (the SLOT-EPOCH lint rule pins this shape) — a table adopted
    mid-migration aborts the flip instead of racing it;
  * the GC horizon is pinned below the migration start for its whole
    duration (server/node.py gc_horizon), so a delete landing during
    the handoff is still present — as a tombstone — in the final
    export, and the key cannot resurrect across the flip; the pin is
    PER HOLDER (a multiset in ClusterState), so concurrent migrations
    cannot release each other's clamps;
  * the import path merges state batches WITHOUT adopting watermarks
    and WITHOUT re-replication (CMD_NO_REPLICATE), so the emit-only-
    durable law and the repl-log cursor discipline survive the move;
  * an abort is never silent on the target: before the window opened
    the source sends SETSLOT STABLE (closing the target's import
    window and GC pin); after the window opened it additionally
    reverse-ships the slot via SLOTEXPORT (_reclaim_ask_window), so
    writes the target acknowledged during the window land back on the
    source before it resumes serving the slot.  A target whose source
    dies without either leg drops the window itself after
    CONSTDB_MIGRATE_STALL_S of silence (expire_stale_imports).
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from ..errors import CstError
from ..resp.codec import encode_msg, make_parser
from ..resp.message import Arr, Bulk, Err, Int, as_bytes, as_int
from .slots import NSLOTS, SLOT_FANOUT, SLOT_LEAVES, bucket_of_slot

log = logging.getLogger(__name__)

# catch-up re-export rounds before the ASK window opens; the window
# itself makes the final round exact, so this only bounds how much the
# last delta has to carry
_CATCHUP_ROUNDS = 2
_DIGEST_RETRIES = 5


def slot_digest(node, slot: int) -> int:
    """This node's digest cell for `slot` (flush-first; the 64x256
    geometry under which slot == bucket — cluster/slots.py)."""
    from ..store.digest import state_digest_matrix
    node.ensure_flushed()
    m = state_digest_matrix(node.ks, SLOT_FANOUT, SLOT_LEAVES)
    return int(m.reshape(-1)[bucket_of_slot(slot)])


def export_slot_batch(node, slot: int):
    """The slot's whole logical state as one ColumnarBatch (live rows +
    key tombstones) — O(slot bytes) by construction."""
    from ..store.digest import export_bucket_batch
    node.ensure_flushed()
    mask = np.zeros(NSLOTS, dtype=bool)
    mask[bucket_of_slot(slot)] = True
    return export_bucket_batch(node.ks, SLOT_FANOUT, SLOT_LEAVES, mask)


def migrate_batch_bytes(app) -> int:
    """Wire chunk size for CLUSTER IMPORT payloads (CONSTDB_MIGRATE_
    BATCH_MB): bounds the largest single frame the migration writes, so
    a big slot streams as many bounded frames instead of one giant one."""
    mb = getattr(app, "migrate_batch_mb", None)
    if mb is None:
        from ..conf import env_int
        mb = env_int("CONSTDB_MIGRATE_BATCH_MB", 8)
    return max(1, mb) << 20


class _Chan:
    """One RESP request/response channel to the migration target."""

    def __init__(self, reader, writer, timeout: float):
        self.reader = reader
        self.writer = writer
        self.parser = make_parser()
        self.timeout = timeout

    async def call(self, *parts):
        items = [p if isinstance(p, (Bulk, Int)) else Bulk(p)
                 for p in parts]
        self.writer.write(encode_msg(Arr(items)))
        await self.writer.drain()
        while True:
            msg = self.parser.next_msg()
            if msg is not None:
                if isinstance(msg, Err):
                    raise CstError("migration target error: "
                                   + msg.val.decode("utf-8", "replace"))
                return msg
            data = await asyncio.wait_for(self.reader.read(1 << 16),
                                          self.timeout)
            if not data:
                raise ConnectionError("migration target EOF")
            self.parser.feed(data)

    def close(self):
        try:
            self.writer.close()
        except Exception:
            pass


async def _ship_slot(chan: _Chan, node, slot: int, chunk_bytes: int) -> int:
    """Export + stream one round of the slot's state; returns payload
    bytes shipped."""
    from ..persist.snapshot import _encode_batch
    payload = bytes(_encode_batch(export_slot_batch(node, slot)))
    total = len(payload)
    off = 0
    while True:
        chunk = payload[off:off + chunk_bytes]
        off += len(chunk)
        more = 1 if off < total else 0
        await chan.call(b"cluster", b"import", b"%d" % slot,
                        b"%d" % more, Bulk(chunk))
        if not more:
            return total


async def _pull_slot_back(chan: _Chan, node, slot: int,
                          chunk_bytes: int) -> None:
    """The reverse IMPORT: SETSLOT STABLE freezes the target's window
    (from then on redirected traffic bounces MOVED instead of being
    acknowledged into it), then SLOTEXPORT chunks the target's copy of
    the slot home, merged as state — no watermark adoption, the same
    law the forward IMPORT obeys."""
    await chan.call(b"cluster", b"setslot", b"%d" % slot, b"stable")
    parts: list = []
    off = 0
    while True:
        r = await chan.call(b"cluster", b"slotexport", b"%d" % slot,
                            b"%d" % off, b"%d" % chunk_bytes)
        more = as_int(r.items[0])
        chunk = as_bytes(r.items[1])
        parts.append(chunk)
        off += len(chunk)
        if not more:
            break
    payload = b"".join(parts)
    if payload:
        from ..persist.snapshot import _decode_batch
        node.merge_batches([_decode_batch(payload)])


async def _reclaim_ask_window(chan: _Chan, node, app, slot: int,
                              target_addr: str, chunk_bytes: int,
                              timeout: float) -> bool:
    """Abort path for a migration whose ASK window already opened:
    every write the target acknowledged during the window exists ONLY
    there (there is deliberately no inter-group repl stream), so before
    the source resumes serving the slot as settled owner it pulls the
    slot back (_pull_slot_back).  Falls back to one fresh dial when the
    migration channel is the thing that died.  If the target is
    unreachable the acknowledged writes are NOT destroyed — they stay
    merged in the target's keyspace, and the next migration attempt's
    digest fixpoint re-converges them into the flip — but until then
    they are invisible to clients, so the failure is logged loudly."""
    try:
        await _pull_slot_back(chan, node, slot, chunk_bytes)
        return True
    except Exception:
        pass
    try:
        host, port = target_addr.rsplit(":", 1)
        reader, writer = await asyncio.wait_for(
            app.open_peer_connection(host, int(port)), timeout)
        fresh = _Chan(reader, writer, timeout)
        try:
            await _pull_slot_back(fresh, node, slot, chunk_bytes)
            return True
        finally:
            fresh.close()
    except Exception as e:
        log.warning(
            "slot %d migration aborted after its ASK window opened and "
            "the window's writes could not be reclaimed from %s (%s); "
            "they remain merged on the target and the next migration "
            "attempt re-converges them", slot, target_addr, e)
        return False


async def _release_target(chan: _Chan, slot: int) -> None:
    """Pre-window abort: best-effort SETSLOT STABLE so the target drops
    its import window and GC pin NOW instead of waiting out the
    CONSTDB_MIGRATE_STALL_S staleness sweep."""
    try:
        await chan.call(b"cluster", b"setslot", b"%d" % slot, b"stable")
    except Exception:
        pass  # dead channel: the target's staleness sweep cleans up


async def migrate_slot(node, app, slot: int, target_addr: str, *,
                       timeout: float = 30.0) -> dict:
    """Drive one slot's migration to `target_addr` (any member of the
    target group).  Returns {"slot", "bytes", "rounds", "epoch"} for the
    bench/ops surface.  Raises on any epoch race or digest mismatch —
    ownership never flips on an unproven copy — after unwinding the
    target's import window (and, if the ASK window already opened,
    reclaiming the writes it acknowledged)."""
    cl = node.cluster
    if cl is None:
        raise CstError("cluster mode is off")
    if not 0 <= slot < NSLOTS:
        raise CstError(f"slot {slot} out of range")
    if not cl.owns(slot):
        raise CstError(f"slot {slot} not owned by this group")
    if slot in cl.migrating:
        raise CstError(f"slot {slot} already migrating")
    epoch0 = cl.epoch
    # pin tombstone GC below every op the migration window can produce:
    # a delete landing mid-handoff must still be a visible tombstone in
    # the final export (no-resurrection across the flip).  The pin is
    # held from HERE — before the first await — because the whole
    # dial/bulk/catch-up phase needs it, and it is this migration's own
    # token: releasing it cannot disturb a concurrent move's pin.
    pin = cl.pin_gc(node.hlc.current)
    chunk_bytes = migrate_batch_bytes(app)
    host, port = target_addr.rsplit(":", 1)
    shipped = rounds = 0
    try:
        reader, writer = await asyncio.wait_for(
            app.open_peer_connection(host, int(port)), timeout)
    except BaseException:
        cl.unpin_gc(pin)
        raise
    chan = _Chan(reader, writer, timeout)
    marked = False        # target told SETSLOT IMPORTING
    window_open = False   # ASK window: client writes drain to target
    try:
        if node.cluster is not cl or cl.epoch != epoch0:
            raise CstError("slot table changed while dialing; aborting")
        await chan.call(b"cluster", b"setslot", b"%d" % slot,
                        b"importing", b"%d" % epoch0,
                        app.advertised_addr.encode())
        marked = True
        # bulk + catch-up rounds while still serving the slot
        for _ in range(1 + _CATCHUP_ROUNDS):
            if node.cluster is not cl or cl.epoch != epoch0:
                raise CstError("slot table changed mid-migration; aborting")
            shipped += await _ship_slot(chan, node, slot, chunk_bytes)
            rounds += 1
        if node.cluster is not cl or cl.epoch != epoch0:
            raise CstError("slot table changed mid-migration; aborting")
        # ASK handoff window: from here every new client WRITE on the
        # slot redirects to the target, so the final export is the
        # whole remaining story (reads keep serving locally — the
        # source copy stays complete until the flip)
        cl.migrating[slot] = target_addr
        window_open = True
        # convergence certificate: the flip is safe iff the target
        # holds EVERYTHING the (now frozen — ASK redirects all new
        # writes) source copy holds.  The target may legally hold
        # MORE (ASK-window writes land there), so source-vs-target
        # digest equality is the wrong test; instead we use CRDT
        # idempotence as a fixpoint probe — if re-merging the
        # slot's full export leaves the target's per-slot digest
        # unchanged, the export was a no-op and target >= source.
        for attempt in range(_DIGEST_RETRIES):
            if node.cluster is not cl or cl.epoch != epoch0:
                raise CstError(
                    "slot table changed mid-handoff; aborting")
            before = int(as_bytes(await chan.call(
                b"cluster", b"slotdigest", b"%d" % slot)))
            shipped += await _ship_slot(chan, node, slot, chunk_bytes)
            rounds += 1
            after = int(as_bytes(await chan.call(
                b"cluster", b"slotdigest", b"%d" % slot)))
            if after == before:
                break
        else:
            raise CstError(
                f"slot {slot} digest never reached its fixpoint on "
                f"{target_addr} after {_DIGEST_RETRIES} deltas")
        if node.cluster is not cl or cl.epoch != epoch0:
            raise CstError("slot table changed pre-finalize; aborting")
        # the flip: the target assigns itself the slot at a bumped
        # epoch and returns the table; adopting it atomically turns
        # our ASK window into a plain MOVED (adopt BEFORE the window
        # closes — no gap where this node serves the slot as settled
        # owner)
        reply = await chan.call(b"cluster", b"finalize", b"%d" % slot)
        from .slots import SlotTable
        table = SlotTable.deserialize(as_bytes(reply))
        if table.epoch <= epoch0 or \
                table.owner[slot] == cl.my_gid:
            raise CstError("finalize returned a non-advancing table")
        cl.adopt(table)
        cl.migrating.pop(slot, None)
        window_open = False
        cl.migrations_out += 1
        log.info("slot %d migrated to %s: %d bytes over %d rounds, "
                 "epoch %d -> %d", slot, target_addr, shipped, rounds,
                 epoch0, table.epoch)
        return {"slot": slot, "bytes": shipped, "rounds": rounds,
                "epoch": table.epoch}
    except BaseException:
        if window_open:
            # stop redirecting first (new writes stay local and are
            # CRDT-safe against the pull-back), then reclaim what the
            # target acknowledged while the window was open
            cl.migrating.pop(slot, None)
            await _reclaim_ask_window(chan, node, app, slot,
                                      target_addr, chunk_bytes, timeout)
        elif marked:
            await _release_target(chan, slot)
        raise
    finally:
        cl.migrating.pop(slot, None)
        cl.unpin_gc(pin)
        chan.close()


async def migrate_slot_range(node, app, start: int, stop: int,
                             target_addr: str, **kw) -> dict:
    """Migrate slots [start, stop) sequentially; aggregate stats."""
    total = {"slots": 0, "bytes": 0, "rounds": 0}
    for slot in range(start, stop):
        cl = node.cluster
        if cl is not None and not cl.owns(slot):
            continue  # already elsewhere (flap/retry idempotence)
        st = await migrate_slot(node, app, slot, target_addr, **kw)
        total["slots"] += 1
        total["bytes"] += st["bytes"]
        total["rounds"] += st["rounds"]
        total["epoch"] = st["epoch"]
    return total
