"""Compact bulk-merge kernels: per-batch gather → merge → scatter on device.

The transfer-optimal device path for bulk merges (snapshot ingest, replica
catch-up).  The host ships each batch as COMPACT rows — int32 slot ids plus
value columns — and folds batches into per-slot device state one kernel call
per batch.  State is donated, so it never leaves the device between calls,
and `jax.device_put` is async, so batch b+1 uploads while batch b merges.

Within one batch every slot appears at most once
(`ColumnarBatch.rows_unique_per_slot`), so scatters carry
`unique_indices=True` and run at HBM speed; collisions exist only ACROSS
batches, which the call sequence serializes by construction.

Contrast with ops/dense.py (the [R, S] pad-align strategy): dense inflates
host→device traffic by R× the slot space, which is the dominant cost when
the device hangs off a slow host link; compact moves each row exactly once.
Measured on v5e: the merge step itself is ~0.5 ms for 8×1M rows — bulk
merge throughput is bounded by the interconnect, not the VPU.

Padding protocol: rows are padded to a power-of-two count; padded rows get
slot id = state_size + offset (distinct, out of bounds), so scatters drop
them (`mode='drop'`), gathers clamp, and win-flags mask them off.

All semantics mirror crdt/semantics.py exactly:
  * LWW pair: (t, writer-node) lexicographic max — registers, element adds;
  * counter slot pair: (time, value) lexicographic max — max-value on ties;
  * plain max: envelopes ct/mt/dt/expire, element del_t.
"""

from __future__ import annotations

from functools import partial

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from ..crdt.semantics import NEUTRAL_T  # noqa: E402

__all__ = ["NEUTRAL_T", "device_full", "bulk_max", "bulk_max1", "bulk_lww",
           "bulk_counters", "bulk_counters_vu", "bulk_counters_vu_src",
           "bulk_counters_src", "bulk_elems",
           "bulk_lww_src", "bulk_elems_src_nodt", "bulk_elems_nodt",
           "bulk_lww_src_iota", "bulk_counters_vu_src_iota",
           "bulk_elems_src_nodt_iota", "gather_rows"]

# An element add-side without its del side IS the plain LWW pair — same
# kernels, no duplicate _pair_win call sites:
#   * bulk_elems_src_nodt(at, an, src, idx, bat, ban, base)
#   * bulk_elems_nodt(at, an, idx, bat, ban) -> (at, an, win-ignored)
# (aliases assigned after the definitions below).  The element DEL side
# never touches the device in the resident src path: del-merge is a plain
# max the engine applies straight to the host column (engine/tpu.py).
#
# The *_src kernels track DEFERRED win resolution: instead of returning win
# flags (whose download blocks the pipeline every call — fatal when the
# device hangs off a high-latency link), the winning batch row's host
# value-pool id scatters into a resident int32 `src` plane.  Ids are NOT
# uploaded — pool entries are consecutive, so the kernel derives them as
# `base + iota` (zero extra host→device bytes).  The engine downloads the
# int32 `src` plane ONCE at flush and both resolves win values and
# RECONSTRUCTS the winner-carried columns (el add_t/add_node, reg
# rv_t/rv_node, cnt val/uuid) from host-side pools — those columns then
# never cross the link at all (the round-4 flush was ~45% of wall time,
# dominated by exactly these downloads).


@jax.jit
def gather_rows(state, idx):
    """Compact dirty-row gather: the flush path downloads ONLY the rows a
    resident plane's merges touched since the last flush — gather them
    into one contiguous [D] (or [D, C]) buffer on device, then a single
    small transfer replaces the whole-plane download.  Non-donating: the
    resident plane stays put."""
    return jnp.take(state, idx, axis=0)


@partial(jax.jit, static_argnames=("n", "fill", "i32"))
def device_full(n: int, fill: int, i32: bool = False):
    """Neutral state created ON device (avoids uploading zeros when every
    touched slot is brand new).  `i32` for the src plane — pool ids fit
    int32, halving its flush download."""
    return jnp.full((n,), fill, dtype=jnp.int32 if i32 else jnp.int64)


def _iota_src(base, np_: int):
    """Pool ids of one batch: consecutive from `base` (int32 on device)."""
    return base + jax.lax.iota(jnp.int32, np_)


@partial(jax.jit, donate_argnums=(0,))
def bulk_max(state, idx, cols):
    """state [Sp, C] ← elementwise max with one batch; idx [Np] int32,
    cols [Np, C].  Envelope merge (ct/mt/dt/expire are all max-merges)."""
    return state.at[idx].max(cols, mode="drop", unique_indices=True)


@partial(jax.jit, donate_argnums=(0,))
def bulk_max1(state, idx, vals):
    """One-column twin of bulk_max: state [Sp] ← per-slot max (the
    element DEL plane on the resident micro path — the host column and
    the device mirror advance together so a later bulk round never
    merges against a stale device del_t)."""
    return state.at[idx].max(vals, mode="drop", unique_indices=True)




def _pair_win(cv, ct, vi, ti, in_range):
    """Lexicographic (t, v) winner — shared by registers/elements/counters
    (the tie-rule core of crdt/semantics.py lww_wins/merge_counter_slot)."""
    return ((ti > ct) | ((ti == ct) & (vi > cv))) & in_range


@partial(jax.jit, donate_argnums=(0, 1))
def bulk_lww(t, n, idx, bt, bn):
    """Plain LWW slots (registers): lexicographic (t, node) winner.
    -> (t [Sp], n [Sp], win [Np] bool) — win marks batch rows whose VALUE
    must replace the slot's value."""
    size = t.shape[0]
    ic = jnp.minimum(idx, size - 1)
    ct, cn = t[ic], n[ic]
    win = _pair_win(cn, ct, bn, bt, idx < size)
    t = t.at[idx].set(jnp.where(win, bt, ct), mode="drop", unique_indices=True)
    n = n.at[idx].set(jnp.where(win, bn, cn), mode="drop", unique_indices=True)
    return t, n, win


@partial(jax.jit, donate_argnums=(0, 1))
def bulk_counters_vu(val, uuid, idx, bv, bt):
    """Counter value pair only — batches with a neutral base plane (no
    counter deletes anywhere in the batch, the overwhelmingly common case)
    skip uploading and merging the base columns entirely."""
    size = val.shape[0]
    ic = jnp.minimum(idx, size - 1)
    cv, ct = val[ic], uuid[ic]
    win = _pair_win(cv, ct, bv, bt, idx < size)
    val = val.at[idx].set(jnp.where(win, bv, cv), mode="drop",
                          unique_indices=True)
    uuid = uuid.at[idx].set(jnp.where(win, bt, ct), mode="drop",
                            unique_indices=True)
    return val, uuid


@partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def bulk_counters(val, uuid, base, base_t, idx, bv, bt, bb, bbt):
    """Counter slots: two independent (value @ time) pairs per slot, each
    LWW on time with max-value tie-break.  -> merged (val, uuid, base,
    base_t), all [Sp]."""
    size = val.shape[0]
    ic = jnp.minimum(idx, size - 1)
    in_range = idx < size

    cv, ct = val[ic], uuid[ic]
    win = _pair_win(cv, ct, bv, bt, in_range)
    val = val.at[idx].set(jnp.where(win, bv, cv), mode="drop",
                          unique_indices=True)
    uuid = uuid.at[idx].set(jnp.where(win, bt, ct), mode="drop",
                            unique_indices=True)

    cb, cbt = base[ic], base_t[ic]
    win = _pair_win(cb, cbt, bb, bbt, in_range)
    base = base.at[idx].set(jnp.where(win, bb, cb), mode="drop",
                            unique_indices=True)
    base_t = base_t.at[idx].set(jnp.where(win, bbt, cbt), mode="drop",
                                unique_indices=True)
    return val, uuid, base, base_t


def _lww_src_body(t, n, src, idx, bt, bn, base):
    size = t.shape[0]
    ic = jnp.minimum(idx, size - 1)
    ct, cn, cs = t[ic], n[ic], src[ic]
    win = _pair_win(cn, ct, bn, bt, idx < size)
    t = t.at[idx].set(jnp.where(win, bt, ct), mode="drop", unique_indices=True)
    n = n.at[idx].set(jnp.where(win, bn, cn), mode="drop", unique_indices=True)
    src = src.at[idx].set(jnp.where(win, _iota_src(base, idx.shape[0]), cs),
                          mode="drop", unique_indices=True)
    return t, n, src


@partial(jax.jit, donate_argnums=(0, 1, 2))
def bulk_lww_src(t, n, src, idx, bt, bn, base):
    """bulk_lww with deferred win resolution (see the *_src block comment
    at the top of the file): winners scatter `base + iota` into `src`."""
    return _lww_src_body(t, n, src, idx, bt, bn, base)


def _idx_iota(r0, nrows, np_: int, size):
    """Contiguous batch idx derived on device: [r0, r0+nrows) then
    out-of-range pad slots — same protocol as the host-built vector."""
    i = jax.lax.iota(jnp.int32, np_)
    return jnp.where(i < nrows, r0 + i, size + i)


@partial(jax.jit, donate_argnums=(0, 1, 2), static_argnames=("np_",))
def bulk_lww_src_iota(t, n, src, r0, nrows, bt, bn, base, *, np_: int):
    """bulk_lww_src for CONTIGUOUS batch rows: the idx vector is derived
    inside the same kernel from (r0, nrows) scalars — one dispatch instead
    of an iota build plus a scatter, and no intermediate idx buffer."""
    idx = _idx_iota(r0, nrows, np_, t.shape[0])
    return _lww_src_body(t, n, src, idx, bt, bn, base)


def _counters_vu_src_body(val, uuid, src, idx, bv, bt, base):
    size = val.shape[0]
    ic = jnp.minimum(idx, size - 1)
    cv, ct, cs = val[ic], uuid[ic], src[ic]
    win = _pair_win(cv, ct, bv, bt, idx < size)
    val = val.at[idx].set(jnp.where(win, bv, cv), mode="drop",
                          unique_indices=True)
    uuid = uuid.at[idx].set(jnp.where(win, bt, ct), mode="drop",
                            unique_indices=True)
    src = src.at[idx].set(jnp.where(win, _iota_src(base, idx.shape[0]), cs),
                          mode="drop", unique_indices=True)
    return val, uuid, src


@partial(jax.jit, donate_argnums=(0, 1, 2))
def bulk_counters_vu_src(val, uuid, src, idx, bv, bt, base):
    """bulk_counters_vu with deferred win resolution: the merged val/uuid
    pair is RECONSTRUCTED at flush from the host pool via `src`, so the two
    widest counter columns never download."""
    return _counters_vu_src_body(val, uuid, src, idx, bv, bt, base)


@partial(jax.jit, donate_argnums=(0, 1, 2), static_argnames=("np_",))
def bulk_counters_vu_src_iota(val, uuid, src, r0, nrows, bv, bt, base, *,
                              np_: int):
    """bulk_counters_vu_src for CONTIGUOUS batch rows (see
    bulk_lww_src_iota)."""
    idx = _idx_iota(r0, nrows, np_, val.shape[0])
    return _counters_vu_src_body(val, uuid, src, idx, bv, bt, base)


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def bulk_counters_src(val, uuid, base_c, base_t, src, idx, bv, bt, bb, bbt,
                      base):
    """bulk_counters with deferred win resolution on the val/uuid pair
    (the base pair keeps its own winner on device and downloads when
    written — counter deletes are rare)."""
    size = val.shape[0]
    ic = jnp.minimum(idx, size - 1)
    in_range = idx < size

    cv, ct, cs = val[ic], uuid[ic], src[ic]
    win = _pair_win(cv, ct, bv, bt, in_range)
    val = val.at[idx].set(jnp.where(win, bv, cv), mode="drop",
                          unique_indices=True)
    uuid = uuid.at[idx].set(jnp.where(win, bt, ct), mode="drop",
                            unique_indices=True)
    src = src.at[idx].set(jnp.where(win, _iota_src(base, idx.shape[0]), cs),
                          mode="drop", unique_indices=True)

    cb, cbt = base_c[ic], base_t[ic]
    win = _pair_win(cb, cbt, bb, bbt, in_range)
    base_c = base_c.at[idx].set(jnp.where(win, bb, cb), mode="drop",
                                unique_indices=True)
    base_t = base_t.at[idx].set(jnp.where(win, bbt, cbt), mode="drop",
                                unique_indices=True)
    return val, uuid, base_c, base_t, src


@partial(jax.jit, donate_argnums=(0, 1, 2))
def bulk_elems(at, an, dt, idx, bat, ban, bdt):
    """Element slots (set members / dict fields): add side = lexicographic
    (add_t, add_node) LWW, del side = plain max.
    -> (at, an, dt [Sp], win [Np] bool) — win marks rows whose dict VALUE
    must replace the slot's value."""
    size = at.shape[0]
    ic = jnp.minimum(idx, size - 1)
    ca, cn, cd = at[ic], an[ic], dt[ic]
    win = _pair_win(cn, ca, ban, bat, idx < size)
    at = at.at[idx].set(jnp.where(win, bat, ca), mode="drop",
                        unique_indices=True)
    an = an.at[idx].set(jnp.where(win, ban, cn), mode="drop",
                        unique_indices=True)
    dt = dt.at[idx].max(bdt, mode="drop", unique_indices=True)
    return at, an, dt, win


bulk_elems_src_nodt = bulk_lww_src
bulk_elems_src_nodt_iota = bulk_lww_src_iota
bulk_elems_nodt = bulk_lww
