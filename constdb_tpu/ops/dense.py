"""Dense R-way CRDT merge kernels — the TPU fast path.

XLA scatter on TPU serializes colliding updates (measured ~11M updates/s on
v5e), so the batched engine avoids it for bulk merges: the host pad-aligns
every batch's rows into the store's dense row space (numpy fancy writes at
C speed), producing [R, S] tensors whose row 0 is the current store state.
The merge is then a dense reduction over the R axis — pure VPU elementwise
work at HBM bandwidth, the same shape trick used to batch ragged data for
the MXU.

Absent slots carry NEUTRAL_T and lose every comparison.  Row 0 is the local
state, so `win_batch == 0` means "no value copy needed" — and argmax's
first-match tie rule makes that automatic when the local write is the winner.
"""

from __future__ import annotations

from functools import partial

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from .segment import NEUTRAL_T  # noqa: E402


@partial(jax.jit, donate_argnums=(0, 1))
def dense_merge_counters(vals, ts):
    """[R, S] per-slot (value, uuid) LWW with max-value tie.
    -> (val[S], t[S])."""
    t_max = ts.max(axis=0)
    val = jnp.where(ts == t_max[None, :], vals, NEUTRAL_T).max(axis=0)
    return val, t_max


@partial(jax.jit, donate_argnums=(0, 1, 2))
def dense_merge_elems(at, an, dt):
    """[R, S] element merge: lexicographic (add_t, add_node) winner + max
    del_t.  -> (at[S], an[S], dt[S], win_batch[S]); win_batch==0 keeps the
    local value."""
    at_max = at.max(axis=0)
    an_cand = jnp.where(at == at_max[None, :], an, NEUTRAL_T)
    an_max = an_cand.max(axis=0)
    winner = (at == at_max[None, :]) & (an == an_max[None, :])
    win_batch = jnp.argmax(winner, axis=0)  # first winner; row 0 = local
    return at_max, an_max, dt.max(axis=0), win_batch


@partial(jax.jit, donate_argnums=(0, 1))
def dense_merge_lww(t, n):
    """[R, S] plain LWW slots (registers): lexicographic (t, node) winner.
    -> (t[S], n[S], win_batch[S])."""
    t_max = t.max(axis=0)
    n_cand = jnp.where(t == t_max[None, :], n, NEUTRAL_T)
    n_max = n_cand.max(axis=0)
    winner = (t == t_max[None, :]) & (n == n_max[None, :])
    return t_max, n_max, jnp.argmax(winner, axis=0)


@partial(jax.jit, donate_argnums=(0,))
def dense_max(cols):
    """[R, S, C] pointwise max over R — envelopes."""
    return cols.max(axis=0)


@partial(jax.jit, static_argnames=("n_seg",))
def segment_sum(ids, vals, n_seg: int):
    """Per-segment int64 sums over unsorted segment ids — the XLA twin of
    ops/pallas_dense.py segment_sum (counter-sum re-derivation from
    resident slot contributions)."""
    return jnp.zeros(n_seg, dtype=jnp.int64).at[ids].add(vals)
