"""Dense R-way CRDT merge kernels — the TPU fast path.

XLA scatter on TPU serializes colliding updates (measured ~11M updates/s on
v5e), so the batched engine avoids it for bulk merges: the host pad-aligns
every batch's rows into the store's dense row space (numpy fancy writes at
C speed), producing [R, S] tensors whose row 0 is the current store state.
The merge is then a dense reduction over the R axis — pure VPU elementwise
work at HBM bandwidth, the same shape trick used to batch ragged data for
the MXU.

Absent slots carry NEUTRAL_T and lose every comparison.  Row 0 is the local
state, so `win_batch == 0` means "no value copy needed" — and argmax's
first-match tie rule makes that automatic when the local write is the winner.
"""

from __future__ import annotations

from functools import partial

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

from .segment import NEUTRAL_T  # noqa: E402


@partial(jax.jit, donate_argnums=(0, 1))
def dense_merge_counters(vals, ts):
    """[R, S] per-slot (value, uuid) LWW with max-value tie.
    -> (val[S], t[S])."""
    t_max = ts.max(axis=0)
    val = jnp.where(ts == t_max[None, :], vals, NEUTRAL_T).max(axis=0)
    return val, t_max


@partial(jax.jit, donate_argnums=(0, 1, 2))
def dense_merge_elems(at, an, dt):
    """[R, S] element merge: lexicographic (add_t, add_node) winner + max
    del_t.  -> (at[S], an[S], dt[S], win_batch[S]); win_batch==0 keeps the
    local value."""
    at_max = at.max(axis=0)
    an_cand = jnp.where(at == at_max[None, :], an, NEUTRAL_T)
    an_max = an_cand.max(axis=0)
    winner = (at == at_max[None, :]) & (an == an_max[None, :])
    win_batch = jnp.argmax(winner, axis=0)  # first winner; row 0 = local
    return at_max, an_max, dt.max(axis=0), win_batch


@partial(jax.jit, donate_argnums=(0, 1))
def dense_merge_lww(t, n):
    """[R, S] plain LWW slots (registers): lexicographic (t, node) winner.
    -> (t[S], n[S], win_batch[S])."""
    t_max = t.max(axis=0)
    n_cand = jnp.where(t == t_max[None, :], n, NEUTRAL_T)
    n_max = n_cand.max(axis=0)
    winner = (t == t_max[None, :]) & (n == n_max[None, :])
    return t_max, n_max, jnp.argmax(winner, axis=0)


@partial(jax.jit, donate_argnums=(0,))
def dense_max(cols):
    """[R, S, C] pointwise max over R — envelopes."""
    return cols.max(axis=0)


@partial(jax.jit, static_argnames=("n_seg",))
def segment_sum(ids, vals, n_seg: int):
    """Per-segment int64 sums over unsorted segment ids — the XLA twin of
    ops/pallas_dense.py segment_sum (counter-sum re-derivation from
    resident slot contributions)."""
    return jnp.zeros(n_seg, dtype=jnp.int64).at[ids].add(vals)


# ----------------------------------------------------- tensor registers
# Device twins for the tensor-register family (crdt/tensor.py).  The
# reductions UNROLL the canonical sequential operation chain of
# crdt.tensor.reduce_rows — same IEEE ops in the same order, so host,
# XLA and Pallas reads are bit-identical (the canonical-order law).


@partial(jax.jit, donate_argnums=(0,))
def pool_scatter(buf, idx, vals):
    """Resident tensor payload pool update: buf [C, Kp] ← vals [W, Kp]
    at unique rows idx [W] int32 (donated — the pool never copies)."""
    return buf.at[idx].set(vals, mode="drop", unique_indices=True)


@jax.jit
def tensor_scale(mat, cnts):
    """avg stage 1: weight the [G, n, Kp] contributor slab by the [G, n]
    counts — a SEPARATE dispatch on purpose.  XLA contracts an adjacent
    multiply-add chain into FMAs (no intermediate rounding), which would
    silently diverge from the host reference's rounded products; a
    dispatch boundary forces the products to materialize as f32/f64
    exactly like numpy does.  The canonical avg chain is therefore
    scale → sequential sum (tensor_reduce STRAT_SUM) → divide
    (tensor_div), on every backend including the host
    (crdt.tensor.reduce_rows runs the same rounded-product chain)."""
    return mat * cnts[:, :, None]


@jax.jit
def tensor_div(acc, tot):
    """avg stage 3: [G, Kp] / [G, 1] count totals (totals accumulate on
    host with the same sequential dtype chain)."""
    return acc / tot


@partial(jax.jit, static_argnames=("strat", "n", "g"))
def tensor_take_reduce(buf, idx, div, *, strat: int, n: int, g: int):
    """Fused pool-gather + strategy reduction: one dispatch, no
    materialized [G, n, Kp] intermediate (XLA fuses the take into the
    fold loop — on the CPU backend this halves the read's memory
    traffic, which is exactly what the device-vs-host bench measures).
    Same sequential chain as tensor_reduce, so still bit-identical to
    the host reference; `sum`/`maxmag`/`trimmed-mean` only — avg's
    products must round at a dispatch boundary (tensor_take_scale)."""
    mat = buf[idx].reshape(g, n, buf.shape[1])
    return _reduce_chain(mat, strat, n, div)


@partial(jax.jit, static_argnames=("n", "g"))
def tensor_take_scale(buf, idx, cnts, *, n: int, g: int):
    """avg stage 1, fused with the pool gather (products still round at
    this dispatch's boundary — the FMA fence tensor_scale documents)."""
    return buf[idx].reshape(g, n, buf.shape[1]) * cnts[:, :, None]


@partial(jax.jit, static_argnames=("n",))
def tensor_sum_div(wmat, tot, *, n: int):
    """avg stages 2+3 fused: sequential sum of the rounded products,
    then the count-total divide (adds and a divide cannot contract)."""
    acc = wmat[:, 0]
    for i in range(1, n):
        acc = acc + wmat[:, i]
    return acc / tot


def _reduce_chain(mat, strat: int, n: int, div):
    """The canonical sequential fold over a [G, n, Kp] stack — the one
    chain crdt.tensor.reduce_rows defines, branch for branch.  `div` is
    the trimmed-mean divisor (n or n-2) as a RUNTIME scalar of the
    payload dtype: a compile-time-constant divisor gets rewritten by
    XLA into a reciprocal multiply, which rounds differently from the
    host's true division (caught by the bench oracle at n=8 — n-2=6 is
    the first non-pow2 divisor)."""
    from ..crdt.tensor import STRAT_MAXMAG, STRAT_SUM, STRAT_TRIMMED
    if strat == STRAT_SUM:
        acc = mat[:, 0]
        for i in range(1, n):
            acc = acc + mat[:, i]
        return acc
    if strat == STRAT_MAXMAG:
        acc = mat[:, 0]
        for i in range(1, n):
            acc = jnp.where(jnp.abs(mat[:, i]) > jnp.abs(acc),
                            mat[:, i], acc)
        return acc
    if strat == STRAT_TRIMMED:
        if n <= 2:
            acc = mat[:, 0]
            for i in range(1, n):
                acc = acc + mat[:, i]
            return acc / div
        s = mat[:, 0]
        mn = mat[:, 0]
        mx = mat[:, 0]
        for i in range(1, n):
            s = s + mat[:, i]
            mn = jnp.minimum(mn, mat[:, i])
            mx = jnp.maximum(mx, mat[:, i])
        return (s - mn - mx) / div
    raise ValueError(f"tensor_reduce: strategy {strat} reduces on host")


@partial(jax.jit, static_argnames=("strat", "n"))
def tensor_reduce(mat, cnts, div, *, strat: int, n: int):
    """[G, n, Kp] contributor stacks (canonical (node, uuid) row order)
    -> [G, Kp] strategy reduction; `cnts` [G, n] in the payload dtype.
    Bit-identical to crdt.tensor.reduce_rows — the sequential chains
    mirror it branch for branch.  `avg` and `lww` never reach this
    kernel: avg composes scale/sum/div (see tensor_scale — FMA
    contraction), lww picks its winner from host stamps."""
    del cnts  # counts only weight avg, which composes outside
    return _reduce_chain(mat, strat, n, div)
