"""Fused dense CRDT merge kernels in Pallas (TPU).

One VMEM pass computes what the XLA path (ops/dense.py) expresses as
several reductions + an argmax: the lexicographic (add_t, add_node) winner,
the merged del side, and the winning replica row, over [R, S] dense merge
tensors blocked along S.

TPU VMEM lanes are 32-bit, so int64 columns travel as two int32/uint32
planes; a signed 64-bit comparison is exactly the lexicographic
(hi signed, lo unsigned) comparison.  All merge values here (uuids,
NEUTRAL_T, node ids) are ordinary int64s, so the split/join is lossless.

`merge_elems(..., interpret=True)` runs the same kernel through the Pallas
interpreter on CPU — that is how tests/test_pallas_dense.py differential-
tests it against ops/dense.py without TPU hardware.
"""

from __future__ import annotations

from functools import partial

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

try:  # TPU backends
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    _VMEM = None

BLOCK_S = 512
_I32_MIN = jnp.iinfo(jnp.int32).min


def _split64(x):
    """int64 -> (hi int32, lo uint32); (hi, lo) lex order == int64 order."""
    return ((x >> 32).astype(jnp.int32),
            (x & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32))


def _join64(hi, lo):
    return (hi.astype(jnp.int64) << 32) | lo.astype(jnp.int64)


def _lex_mask(hi, lo, mask, lo_zero):
    """Among rows where `mask`, the rows achieving the (hi, lo) lex max.
    -> (new_mask, m_hi [S], m_lo [S])."""
    hi_c = jnp.where(mask, hi, _I32_MIN)
    m_hi = jnp.max(hi_c, axis=0)
    mask = mask & (hi == m_hi[None, :])
    lo_c = jnp.where(mask, lo, lo_zero)
    m_lo = jnp.max(lo_c, axis=0)
    mask = mask & (lo == m_lo[None, :])
    return mask, m_hi, m_lo


def _elems_kernel(at_hi, at_lo, an_hi, an_lo, dt_hi, dt_lo,
                  o_at_hi, o_at_lo, o_an_hi, o_an_lo, o_dt_hi, o_dt_lo,
                  o_win):
    R = at_hi.shape[0]
    full = jnp.ones(at_hi.shape, dtype=jnp.bool_)
    zero_u = jnp.uint32(0)

    # 4-level lexicographic winner: (at_hi, at_lo, an_hi, an_lo)
    m, ah, al = _lex_mask(at_hi[:], at_lo[:], full, zero_u)
    m, nh, nl = _lex_mask(an_hi[:], an_lo[:], m, zero_u)

    # first winning row (ties share identical (t, node) == the same write)
    rows = jax.lax.broadcasted_iota(jnp.int32, at_hi.shape, 0)
    win = jnp.min(jnp.where(m, rows, R), axis=0)

    # del side: independent 2-level max
    _, dh, dl = _lex_mask(dt_hi[:], dt_lo[:], full, zero_u)

    o_at_hi[:] = ah[None, :]
    o_at_lo[:] = al[None, :]
    o_an_hi[:] = nh[None, :]
    o_an_lo[:] = nl[None, :]
    o_dt_hi[:] = dh[None, :]
    o_dt_lo[:] = dl[None, :]
    o_win[:] = win[None, :]


@partial(jax.jit, static_argnames=("interpret",))
def merge_elems(at, an, dt, interpret: bool = False):
    """Fused [R, S] element merge: lexicographic (add_t, add_node) winner +
    max del_t.  -> (at[S], an[S], dt[S], win_batch[S]) — bit-identical to
    ops/dense.py dense_merge_elems."""
    R, S = at.shape
    sp = -(-S // BLOCK_S) * BLOCK_S
    neutral = jnp.int64(-(1 << 62))

    def prep(x, fill):
        if sp != S:
            x = jnp.concatenate(
                [x, jnp.full((R, sp - S), fill, dtype=jnp.int64)], axis=1)
        return _split64(x)

    planes = [*prep(at, neutral), *prep(an, neutral), *prep(dt, 0)]
    grid = (sp // BLOCK_S,)
    in_spec = pl.BlockSpec((R, BLOCK_S), lambda i: (0, i))
    out_spec = pl.BlockSpec((1, BLOCK_S), lambda i: (0, i))
    shapes = ([jax.ShapeDtypeStruct((1, sp), jnp.int32),
               jax.ShapeDtypeStruct((1, sp), jnp.uint32)] * 3
              + [jax.ShapeDtypeStruct((1, sp), jnp.int32)])
    out = pl.pallas_call(
        _elems_kernel,
        grid=grid,
        in_specs=[in_spec] * 6,
        out_specs=[out_spec] * 7,
        out_shape=shapes,
        interpret=interpret,
    )(*planes)
    ah, al, nh, nl, dh, dl, win = (o[0] for o in out)
    return (_join64(ah, al)[:S], _join64(nh, nl)[:S],
            _join64(dh, dl)[:S], win.astype(jnp.int64)[:S])


def _counters_kernel(v_hi, v_lo, t_hi, t_lo, o_v_hi, o_v_lo, o_t_hi, o_t_lo):
    full = jnp.ones(v_hi.shape, dtype=jnp.bool_)
    zero_u = jnp.uint32(0)
    # (uuid, value) lexicographic max == LWW with max-value tie-break
    m, th, tl = _lex_mask(t_hi[:], t_lo[:], full, zero_u)
    _, vh, vl = _lex_mask(v_hi[:], v_lo[:], m, zero_u)
    o_v_hi[:] = vh[None, :]
    o_v_lo[:] = vl[None, :]
    o_t_hi[:] = th[None, :]
    o_t_lo[:] = tl[None, :]


@partial(jax.jit, static_argnames=("interpret",))
def merge_counters(vals, ts, interpret: bool = False):
    """Fused [R, S] counter-slot merge: per-slot (value @ uuid) LWW with
    max-value tie — bit-identical to ops/dense.py dense_merge_counters."""
    R, S = vals.shape
    sp = -(-S // BLOCK_S) * BLOCK_S
    neutral = jnp.int64(-(1 << 62))

    def prep(x, fill):
        if sp != S:
            x = jnp.concatenate(
                [x, jnp.full((R, sp - S), fill, dtype=jnp.int64)], axis=1)
        return _split64(x)

    planes = [*prep(vals, neutral), *prep(ts, neutral)]
    in_spec = pl.BlockSpec((R, BLOCK_S), lambda i: (0, i))
    out_spec = pl.BlockSpec((1, BLOCK_S), lambda i: (0, i))
    shapes = [jax.ShapeDtypeStruct((1, sp), jnp.int32),
              jax.ShapeDtypeStruct((1, sp), jnp.uint32)] * 2
    out = pl.pallas_call(
        _counters_kernel,
        grid=(sp // BLOCK_S,),
        in_specs=[in_spec] * 4,
        out_specs=[out_spec] * 4,
        out_shape=shapes,
        interpret=interpret,
    )(*planes)
    vh, vl, th, tl = (o[0] for o in out)
    return _join64(vh, vl)[:S], _join64(th, tl)[:S]
