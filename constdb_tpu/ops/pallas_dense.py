"""Fused dense + resident-scatter CRDT merge kernels in Pallas (TPU).

Two kernel families live here:

  * FOLD kernels (`merge_elems`, `merge_counters`): one VMEM pass computes
    what the XLA path (ops/dense.py) expresses as several reductions + an
    argmax — the lexicographic (add_t, add_node) winner, the merged del
    side, and the winning replica row, over [R, S] dense merge tensors
    blocked along S.
  * RESIDENT-SCATTER kernels (`scatter_pair_src`, `segment_sum`): the
    steady-state path for device-resident planes (engine/tpu.py micro
    merges).  `scatter_pair_src` is a gather-compare-scatter over one LWW
    pair: a scalar-prefetched slot-id vector drives the BlockSpec index
    maps, so each grid step DMAs exactly the state row the batch row
    targets, runs the lexicographic compare, and writes the winner back
    in place (`input_output_aliases` — untouched rows never move).
    `segment_sum` re-derives per-key counter sums from resident slot
    contributions with a VMEM scratch accumulator carried across the
    sequential TPU grid.

TPU VMEM lanes are 32-bit, so int64 columns travel as two int32/uint32
planes; a signed 64-bit comparison is exactly the lexicographic
(hi signed, lo unsigned) comparison.  All merge values here (uuids,
NEUTRAL_T, node ids) are ordinary int64s, so the split/join is lossless;
`segment_sum` accumulates the pair with an explicit unsigned carry, which
is exact mod 2^64 (host sums are int64, so no real sum can wrap).

`merge_elems(..., interpret=True)` (and every kernel here) runs through
the Pallas interpreter on CPU — that is how tests/test_pallas_dense.py
differential-tests them against ops/dense.py, ops/bulk.py, and the host
reference without TPU hardware.
"""

from __future__ import annotations

from functools import partial

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

try:  # TPU backends
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    _VMEM = None

BLOCK_S = 512
_I32_MIN = jnp.iinfo(jnp.int32).min


def _split64(x):
    """int64 -> (hi int32, lo uint32); (hi, lo) lex order == int64 order."""
    return ((x >> 32).astype(jnp.int32),
            (x & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32))


def _join64(hi, lo):
    return (hi.astype(jnp.int64) << 32) | lo.astype(jnp.int64)


def _lex_mask(hi, lo, mask, lo_zero):
    """Among rows where `mask`, the rows achieving the (hi, lo) lex max.
    -> (new_mask, m_hi [S], m_lo [S])."""
    hi_c = jnp.where(mask, hi, _I32_MIN)
    m_hi = jnp.max(hi_c, axis=0)
    mask = mask & (hi == m_hi[None, :])
    lo_c = jnp.where(mask, lo, lo_zero)
    m_lo = jnp.max(lo_c, axis=0)
    mask = mask & (lo == m_lo[None, :])
    return mask, m_hi, m_lo


def _elems_kernel(at_hi, at_lo, an_hi, an_lo, dt_hi, dt_lo,
                  o_at_hi, o_at_lo, o_an_hi, o_an_lo, o_dt_hi, o_dt_lo,
                  o_win):
    R = at_hi.shape[0]
    full = jnp.ones(at_hi.shape, dtype=jnp.bool_)
    zero_u = jnp.uint32(0)

    # 4-level lexicographic winner: (at_hi, at_lo, an_hi, an_lo)
    m, ah, al = _lex_mask(at_hi[:], at_lo[:], full, zero_u)
    m, nh, nl = _lex_mask(an_hi[:], an_lo[:], m, zero_u)

    # first winning row (ties share identical (t, node) == the same write)
    rows = jax.lax.broadcasted_iota(jnp.int32, at_hi.shape, 0)
    win = jnp.min(jnp.where(m, rows, R), axis=0)

    # del side: independent 2-level max
    _, dh, dl = _lex_mask(dt_hi[:], dt_lo[:], full, zero_u)

    o_at_hi[:] = ah[None, :]
    o_at_lo[:] = al[None, :]
    o_an_hi[:] = nh[None, :]
    o_an_lo[:] = nl[None, :]
    o_dt_hi[:] = dh[None, :]
    o_dt_lo[:] = dl[None, :]
    o_win[:] = win[None, :]


@partial(jax.jit, static_argnames=("interpret",))
def merge_elems(at, an, dt, interpret: bool = False):
    """Fused [R, S] element merge: lexicographic (add_t, add_node) winner +
    max del_t.  -> (at[S], an[S], dt[S], win_batch[S]) — bit-identical to
    ops/dense.py dense_merge_elems."""
    R, S = at.shape
    sp = -(-S // BLOCK_S) * BLOCK_S
    neutral = jnp.int64(-(1 << 62))

    def prep(x, fill):
        if sp != S:
            x = jnp.concatenate(
                [x, jnp.full((R, sp - S), fill, dtype=jnp.int64)], axis=1)
        return _split64(x)

    planes = [*prep(at, neutral), *prep(an, neutral), *prep(dt, 0)]
    grid = (sp // BLOCK_S,)
    in_spec = pl.BlockSpec((R, BLOCK_S), lambda i: (0, i))
    out_spec = pl.BlockSpec((1, BLOCK_S), lambda i: (0, i))
    shapes = ([jax.ShapeDtypeStruct((1, sp), jnp.int32),
               jax.ShapeDtypeStruct((1, sp), jnp.uint32)] * 3
              + [jax.ShapeDtypeStruct((1, sp), jnp.int32)])
    out = pl.pallas_call(
        _elems_kernel,
        grid=grid,
        in_specs=[in_spec] * 6,
        out_specs=[out_spec] * 7,
        out_shape=shapes,
        interpret=interpret,
    )(*planes)
    ah, al, nh, nl, dh, dl, win = (o[0] for o in out)
    return (_join64(ah, al)[:S], _join64(nh, nl)[:S],
            _join64(dh, dl)[:S], win.astype(jnp.int64)[:S])


def _counters_kernel(v_hi, v_lo, t_hi, t_lo, o_v_hi, o_v_lo, o_t_hi, o_t_lo):
    full = jnp.ones(v_hi.shape, dtype=jnp.bool_)
    zero_u = jnp.uint32(0)
    # (uuid, value) lexicographic max == LWW with max-value tie-break
    m, th, tl = _lex_mask(t_hi[:], t_lo[:], full, zero_u)
    _, vh, vl = _lex_mask(v_hi[:], v_lo[:], m, zero_u)
    o_v_hi[:] = vh[None, :]
    o_v_lo[:] = vl[None, :]
    o_t_hi[:] = th[None, :]
    o_t_lo[:] = tl[None, :]


@partial(jax.jit, static_argnames=("interpret",))
def merge_counters(vals, ts, interpret: bool = False):
    """Fused [R, S] counter-slot merge: per-slot (value @ uuid) LWW with
    max-value tie — bit-identical to ops/dense.py dense_merge_counters."""
    R, S = vals.shape
    sp = -(-S // BLOCK_S) * BLOCK_S
    neutral = jnp.int64(-(1 << 62))

    def prep(x, fill):
        if sp != S:
            x = jnp.concatenate(
                [x, jnp.full((R, sp - S), fill, dtype=jnp.int64)], axis=1)
        return _split64(x)

    planes = [*prep(vals, neutral), *prep(ts, neutral)]
    in_spec = pl.BlockSpec((R, BLOCK_S), lambda i: (0, i))
    out_spec = pl.BlockSpec((1, BLOCK_S), lambda i: (0, i))
    shapes = [jax.ShapeDtypeStruct((1, sp), jnp.int32),
              jax.ShapeDtypeStruct((1, sp), jnp.uint32)] * 2
    out = pl.pallas_call(
        _counters_kernel,
        grid=(sp // BLOCK_S,),
        in_specs=[in_spec] * 4,
        out_specs=[out_spec] * 4,
        out_shape=shapes,
        interpret=interpret,
    )(*planes)
    vh, vl, th, tl = (o[0] for o in out)
    return _join64(vh, vl)[:S], _join64(th, tl)[:S]


# ------------------------------------------------------- resident scatter
# The steady-state kernels: engine/tpu.py's resident micro path folds a
# micro-batch's duplicate slots on host (rows become unique) and then
# merges the folded rows IN PLACE against device-resident planes.  The
# slot-id vector is scalar-prefetched, so the BlockSpec index maps gather
# (and scatter back) exactly the touched state rows — the gather-compare-
# scatter the XLA twins in ops/bulk.py express as `state.at[idx].set`.
#
# Contract shared with the XLA twins: slot ids are UNIQUE within one call
# (the host fold guarantees it) — each real state row is visited by at
# most one grid step, so the aliased in-place writes can never race.  The
# caller PRE-PADS (idx, bp, bs) to one shared pow2 length (the jit then
# retraces per pow2 bucket, not per batch size): padded rows carry
# (NEUTRAL_T, NEUTRAL_T) batch values — which lose every comparison, so
# they rewrite their target row with its own current value — and MUST
# target an in-range row that NO real row targets (unique rows over a
# pow2 plane leave one whenever padding is needed; engine/tpu.py
# _scatter_pad_row finds it).  A pad aliased onto a real row's target
# would re-write it from a STALE pre-merge read and silently revert the
# merge — pinned by test_pallas_dense.py's pad-collision case.

_NEUTRAL64 = jnp.int64(-(1 << 62))

# ONE pow2-rounding policy across the ops modules (callers and tests
# reach it as PD._pow2)
from .segment import next_pow2 as _pow2  # noqa: E402


def split_plane(x):
    """int64 plane [Sp] -> pre-split ((Sp, 1) int32 hi, (Sp, 1) uint32
    lo) COLUMN form — the storage layout `scatter_pair_src_split`
    consumes and produces, so consecutive micro rounds never pay the
    O(plane) split/join wrapper (the PR 8 flagged follow-up).  Column
    shape on purpose: the kernel reads (1, 1) blocks of (Sp, 1) planes,
    and keeping the stored form identical to the kernel form lets the
    jit-level donation alias buffers across rounds."""
    hi, lo = _split64(x)
    return hi.reshape(-1, 1), lo.reshape(-1, 1)


split_plane = jax.jit(split_plane)


@jax.jit
def join_plane(hi, lo):
    """Pre-split (Sp, 1) pair -> int64 [Sp] (the bulk kernels and the
    resident-state grow path still speak int64)."""
    return _join64(hi[:, 0], lo[:, 0])


def _scatter_pair_kernel(idx_ref, base_ref,
                         p_hi, p_lo, s_hi, s_lo, src,
                         bp_hi, bp_lo, bs_hi, bs_lo,
                         o_p_hi, o_p_lo, o_s_hi, o_s_lo, o_src):
    i = pl.program_id(0)
    cp_hi, cp_lo = p_hi[0, 0], p_lo[0, 0]
    cs_hi, cs_lo = s_hi[0, 0], s_lo[0, 0]
    np_hi, np_lo = bp_hi[0, 0], bp_lo[0, 0]
    ns_hi, ns_lo = bs_hi[0, 0], bs_lo[0, 0]
    # 64-bit lexicographic (primary, secondary) >: exactly ops/bulk.py
    # _pair_win with the int64s split (hi signed, lo unsigned)
    gt_p = (np_hi > cp_hi) | ((np_hi == cp_hi) & (np_lo > cp_lo))
    eq_p = (np_hi == cp_hi) & (np_lo == cp_lo)
    gt_s = (ns_hi > cs_hi) | ((ns_hi == cs_hi) & (ns_lo > cs_lo))
    win = gt_p | (eq_p & gt_s)
    o_p_hi[0, 0] = jnp.where(win, np_hi, cp_hi)
    o_p_lo[0, 0] = jnp.where(win, np_lo, cp_lo)
    o_s_hi[0, 0] = jnp.where(win, ns_hi, cs_hi)
    o_s_lo[0, 0] = jnp.where(win, ns_lo, cs_lo)
    o_src[0, 0] = jnp.where(win, base_ref[0] + jnp.int32(i), src[0, 0])


@partial(jax.jit, static_argnames=("interpret",),
         donate_argnums=(0, 1, 2, 3, 4))
def scatter_pair_src_split(p_hi, p_lo, s_hi, s_lo, src, idx, bp, bs, base,
                           interpret: bool = False):
    """Gather-compare-scatter one LWW pair against PRE-SPLIT resident
    state planes — the steady-state form of `scatter_pair_src`.

    `p_hi`/`s_hi` [Sp, 1] int32 and `p_lo`/`s_lo` [Sp, 1] uint32 are the
    hi/lo halves of the int64 planes in `split_plane`'s column layout;
    `src` [Sp] int32; `idx`/`bp`/`bs`/`base` exactly as in the int64
    wrapper below.  -> (p_hi, p_lo, s_hi, s_lo, src) merged IN PLACE:
    input and output dtypes now MATCH, so the `input_output_aliases` are
    true aliases and the jit-level donations are live — consecutive
    micro rounds on a warm plane run ZERO whole-plane passes (the PR 8
    flagged follow-up: the old wrapper re-split and re-joined the full
    plane around every call).  engine/tpu.py keeps the split pair as the
    plane's truth between rounds and joins only at bulk-round / grow
    boundaries (`join_plane`)."""
    np_ = idx.shape[0]
    sp = p_hi.shape[0]
    bp_hi, bp_lo = (x.reshape(np_, 1) for x in _split64(bp))
    bs_hi, bs_lo = (x.reshape(np_, 1) for x in _split64(bs))
    state_spec = pl.BlockSpec((1, 1), lambda i, idx_ref, base_ref:
                              (idx_ref[i], 0))
    batch_spec = pl.BlockSpec((1, 1), lambda i, idx_ref, base_ref: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(np_,),
        in_specs=[state_spec] * 5 + [batch_spec] * 4,
        out_specs=[state_spec] * 5,
    )
    shapes = [jax.ShapeDtypeStruct((sp, 1), jnp.int32),
              jax.ShapeDtypeStruct((sp, 1), jnp.uint32)] * 2 + \
        [jax.ShapeDtypeStruct((sp, 1), jnp.int32)]
    out = pl.pallas_call(
        _scatter_pair_kernel,
        grid_spec=grid_spec,
        out_shape=shapes,
        # operand numbering includes the scalar-prefetch args: 0=idx,
        # 1=base, 2..6 = the five state planes -> outputs 0..4 in place
        input_output_aliases={2: 0, 3: 1, 4: 2, 5: 3, 6: 4},
        interpret=interpret,
    )(idx, jnp.full(1, base, dtype=jnp.int32),
      p_hi, p_lo, s_hi, s_lo, src.reshape(sp, 1),
      bp_hi, bp_lo, bs_hi, bs_lo)
    o_p_hi, o_p_lo, o_s_hi, o_s_lo, o_src = out
    return o_p_hi, o_p_lo, o_s_hi, o_s_lo, o_src[:, 0]


def scatter_pair_src(p, s, src, idx, bp, bs, base, interpret: bool = False):
    """Gather-compare-scatter one LWW pair against resident state planes.

    `p`/`s` [Sp] int64 (primary/secondary: registers (t, node), element
    adds (add_t, add_node), counter pairs (uuid, val)); `src` [Sp] int32
    win-source plane; `idx` [Np] int32 slot rows, UNIQUE over the real
    prefix and PRE-PADDED to a pow2 length (padding targets an in-range
    state row, ideally a plane padding row); `bp`/`bs` [Np] int64 batch
    columns, padded with NEUTRAL (losing) values; `base` int32 pool id of
    the batch's first row — row j's pool id is derived as base + j, so
    ids never upload.  -> (p, s, src) merged in place — bit-identical to
    ops/bulk.py bulk_lww_src (differential-tested).

    Compatibility wrapper: splits the int64 planes, runs
    `scatter_pair_src_split`, joins back.  The split/join are O(plane)
    XLA passes PER CALL — steady-state callers (engine/tpu.py) keep the
    planes pre-split across rounds instead and call the split kernel
    directly, which is the whole point of the layout change."""
    p_hi, p_lo = split_plane(p)
    s_hi, s_lo = split_plane(s)
    o_p_hi, o_p_lo, o_s_hi, o_s_lo, o_src = scatter_pair_src_split(
        p_hi, p_lo, s_hi, s_lo, src, idx, bp, bs, base,
        interpret=interpret)
    return join_plane(o_p_hi, o_p_lo), join_plane(o_s_hi, o_s_lo), o_src


# ------------------------------------------------------ tensor registers
# Strategy reduction over contributor stacks (crdt/tensor.py): one grid
# step owns one (key, K-block) tile, loads the [n, BLOCK] contributor
# slab, and folds it with the EXACT sequential operation chain of
# crdt.tensor.reduce_rows (the canonical-order law: float reductions are
# order-fixed so replicas cannot diverge through summation order; the
# XLA twin in ops/dense.py unrolls the same chain).  f32 only — TPU VMEM
# lanes are 32-bit; the engine routes f64 tensors onto the XLA twin.

TENSOR_BLOCK = 512


def _tensor_reduce_kernel(mat, cnts, div, out, *, strat: int, n: int):
    # avg never reaches the kernel: its multiply-add chain would FMA-
    # contract (no intermediate rounding — diverging from the host's
    # rounded products), so it composes as scale → STRAT_SUM → divide
    # across dispatch boundaries (ops/dense.py tensor_scale docstring).
    # `div` is the trimmed divisor as a RUNTIME operand — a constant
    # divisor gets strength-reduced to a reciprocal multiply, which
    # rounds differently from the host's true division.
    from ..crdt.tensor import STRAT_MAXMAG, STRAT_SUM, STRAT_TRIMMED
    del cnts
    if strat == STRAT_SUM:
        acc = mat[0, 0, :]
        for i in range(1, n):
            acc = acc + mat[0, i, :]
    elif strat == STRAT_MAXMAG:
        acc = mat[0, 0, :]
        for i in range(1, n):
            acc = jnp.where(jnp.abs(mat[0, i, :]) > jnp.abs(acc),
                            mat[0, i, :], acc)
    elif strat == STRAT_TRIMMED and n <= 2:
        acc = mat[0, 0, :]
        for i in range(1, n):
            acc = acc + mat[0, i, :]
        acc = acc / div[0, 0]
    elif strat == STRAT_TRIMMED:
        s = mat[0, 0, :]
        mn = mat[0, 0, :]
        mx = mat[0, 0, :]
        for i in range(1, n):
            s = s + mat[0, i, :]
            mn = jnp.minimum(mn, mat[0, i, :])
            mx = jnp.maximum(mx, mat[0, i, :])
        acc = (s - mn - mx) / div[0, 0]
    else:
        raise ValueError(f"tensor_reduce kernel: strategy {strat}")
    out[0, :] = acc


@partial(jax.jit, static_argnames=("strat", "n", "interpret"))
def tensor_reduce(mat, cnts, div, *, strat: int, n: int,
                  interpret: bool = False):
    """[G, n, Kp] f32 contributor stacks (canonical (node, uuid) row
    order, Kp a TENSOR_BLOCK multiple) -> [G, Kp] strategy reduction;
    `cnts` [G, n] f32; `div` the trimmed divisor as a runtime f32
    scalar.  Bit-identical to ops/dense.py tensor_reduce and
    crdt.tensor.reduce_rows."""
    G, n_, Kp = mat.shape
    assert n_ == n and Kp % TENSOR_BLOCK == 0
    assert mat.dtype == jnp.float32, "pallas tensor_reduce is f32-only"
    grid = (G, Kp // TENSOR_BLOCK)
    return pl.pallas_call(
        partial(_tensor_reduce_kernel, strat=strat, n=n),
        grid=grid,
        in_specs=[pl.BlockSpec((1, n, TENSOR_BLOCK), lambda g, k: (g, 0, k)),
                  pl.BlockSpec((1, n), lambda g, k: (g, 0)),
                  pl.BlockSpec((1, 1), lambda g, k: (0, 0))],
        out_specs=pl.BlockSpec((1, TENSOR_BLOCK), lambda g, k: (g, k)),
        out_shape=jax.ShapeDtypeStruct((G, Kp), jnp.float32),
        interpret=interpret,
    )(mat, cnts, jnp.reshape(div, (1, 1)))


# per-key counter-sum scratch cap: two (1, n_seg) int32 planes must fit
# VMEM alongside the blocks — 2^20 segments = 8 MB, a safe ceiling; the
# engine routes larger keyspaces onto the XLA twin (ops/dense.py)
SEGMENT_SUM_MAX_SEG = 1 << 20


def _segment_sum_kernel(ids_ref, v_hi, v_lo, o_hi, o_lo, acc_hi, acc_lo):
    i = pl.program_id(0)
    n = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        acc_hi[...] = jnp.zeros_like(acc_hi)
        acc_lo[...] = jnp.zeros_like(acc_lo)

    s = ids_ref[i]
    sl = (pl.dslice(jnp.int32(0), 1), pl.dslice(s, 1))
    cur_lo = pl.load(acc_lo, sl)
    new_lo = cur_lo + v_lo[0, 0]          # uint32: wraps mod 2^32
    carry = (new_lo < cur_lo).astype(jnp.int32)
    pl.store(acc_lo, sl, new_lo)
    pl.store(acc_hi, sl, pl.load(acc_hi, sl) + v_hi[0, 0] + carry)

    @pl.when(i == n - 1)
    def _emit():
        o_hi[...] = acc_hi[...]
        o_lo[...] = acc_lo[...]


@partial(jax.jit, static_argnames=("n_seg", "interpret"))
def segment_sum(ids, vals, n_seg: int, interpret: bool = False):
    """Per-segment int64 sums over unsorted segment ids (the counter-sum
    re-derivation: ids = slot kid, vals = val - base).  Accumulates in a
    VMEM scratch carried across the sequential grid — exact mod 2^64 via
    an explicit unsigned carry — and emits on the last step.  Bit-
    identical to ops/dense.py segment_sum / numpy add.at."""
    n = ids.shape[0]
    if n == 0:
        return jnp.zeros(n_seg, dtype=jnp.int64)
    if n_seg > SEGMENT_SUM_MAX_SEG:
        raise ValueError(f"segment_sum scratch cap: {n_seg} segments "
                         f"> {SEGMENT_SUM_MAX_SEG}")
    np_ = _pow2(n)
    if np_ != n:
        ids = jnp.concatenate([ids, jnp.zeros(np_ - n, dtype=jnp.int32)])
        vals = jnp.concatenate([vals, jnp.zeros(np_ - n, dtype=jnp.int64)])
    sg = _pow2(n_seg)
    v_hi, v_lo = (x.reshape(np_, 1) for x in _split64(vals))
    batch_spec = pl.BlockSpec((1, 1), lambda i, ids_ref: (i, 0))
    out_spec = pl.BlockSpec((1, sg), lambda i, ids_ref: (0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(np_,),
        in_specs=[batch_spec, batch_spec],
        out_specs=[out_spec, out_spec],
        scratch_shapes=[pltpu.VMEM((1, sg), jnp.int32),
                        pltpu.VMEM((1, sg), jnp.uint32)],
    )
    o_hi, o_lo = pl.pallas_call(
        _segment_sum_kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((1, sg), jnp.int32),
                   jax.ShapeDtypeStruct((1, sg), jnp.uint32)],
        interpret=interpret,
    )(ids, v_hi, v_lo)
    return _join64(o_hi[0], o_lo[0])[:n_seg]
