"""Batched CRDT merge kernels: scatter/segment reductions on device.

These lift the reference's per-key merge loops (src/type_counter.rs:59-91
PN-Counter, src/crdt/lwwhash.rs Set/Dict element merges, src/object.rs:63-83
envelopes) into data-parallel reductions over columnar row tensors:

  * counter slots:  per-(key,node) LWW = segment-max on uuid, then a masked
                    segment-max on value for the uuid tie;
  * elements:       add side = lexicographic (time, node) segment-max in two
                    scatter passes + winning-row recovery; del side = plain
                    segment-max;
  * envelopes:      pointwise max over aligned vectors.

All timestamps are int64 (uuids use 63 bits: 41-bit ms << 22 | seq), so x64
mode is required; this module enables it at import, before any tracing.

Duplicate slot ids within one batch are the normal case (same key updated by
many replicas in one snapshot window) — scatter-max handles them natively,
which is why this is scatter and not a naive reshape-reduce.

Rows are padded to power-of-two buckets so jit recompiles O(log n) times,
never per batch size.  Padded rows carry t = NEUTRAL_T and a dummy slot id,
so they lose every reduction and land in a slot that is sliced off.
"""

from __future__ import annotations

from functools import partial

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402

# loses to every real timestamp (real uuids are >= 0; element add_t >= 0);
# canonical definition lives in the jax-free crdt layer
from ..crdt.semantics import NEUTRAL_T  # noqa: E402


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


@partial(jax.jit, static_argnames=("n_slots",))
def merge_counters(slot_ids, vals, ts, cur_val, cur_t, n_slots: int):
    """Merge incoming counter rows into per-slot current state.

    slot_ids/vals/ts: incoming rows (padded; padded rows have ts=NEUTRAL_T
    and slot_ids pointing at the dummy tail slot).
    cur_val/cur_t: (n_slots,) current state; new slots carry (0, NEUTRAL_T).
    -> (new_val, new_t) per slot.
    """
    t_max = cur_t.at[slot_ids].max(ts)
    # value on the max-uuid write; max(value) breaks exact-uuid ties
    cand_cur = jnp.where(cur_t == t_max, cur_val, NEUTRAL_T)
    row_cand = jnp.where(ts == t_max[slot_ids], vals, NEUTRAL_T)
    new_val = cand_cur.at[slot_ids].max(row_cand)
    # slots never touched keep their value even if cur_t != t_max can't happen
    return new_val, t_max


@partial(jax.jit, static_argnames=("n_slots",))
def merge_elems(slot_ids, add_t, add_node, del_t, cur_at, cur_an, cur_dt,
                n_slots: int):
    """Merge incoming element rows (set members / dict fields) into per-slot
    current state.

    -> (at, an, dt, win_row) per slot; win_row is the incoming row index
    whose value should be taken, or -1 when the current write survives.
    """
    n = slot_ids.shape[0]
    at_max = cur_at.at[slot_ids].max(add_t)
    # lexicographic tie-break on writer node
    cand_cur = jnp.where(cur_at == at_max, cur_an, NEUTRAL_T)
    row_cand = jnp.where(add_t == at_max[slot_ids], add_node, NEUTRAL_T)
    an_max = cand_cur.at[slot_ids].max(row_cand)
    # recover the winning incoming row (unique: (t, node) identifies a write)
    rows = jnp.arange(n, dtype=jnp.int64)
    winner_rows = jnp.where(
        (add_t == at_max[slot_ids]) & (add_node == an_max[slot_ids]), rows, -1)
    win_row = jnp.full((n_slots,), -1, dtype=jnp.int64).at[slot_ids].max(winner_rows)
    # the current write wins outright (or ties as the same write)
    cur_wins = (cur_at == at_max) & (cur_an == an_max)
    win_row = jnp.where(cur_wins, -1, win_row)
    dt = cur_dt.at[slot_ids].max(del_t)
    return at_max, an_max, dt, win_row


@partial(jax.jit, static_argnames=("n_slots",))
def scatter_max4(slot_ids, a, b, c, d, cur_a, cur_b, cur_c, cur_d, n_slots: int):
    """Four aligned scatter-max reductions in one device call (key envelope
    ct/mt/dt/expire merge; n_slots only pins the jit cache key)."""
    del n_slots
    return (cur_a.at[slot_ids].max(a), cur_b.at[slot_ids].max(b),
            cur_c.at[slot_ids].max(c), cur_d.at[slot_ids].max(d))


