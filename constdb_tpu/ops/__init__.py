from .dense import dense_max, dense_merge_counters, dense_merge_elems, dense_merge_lww
from .segment import NEUTRAL_T, merge_counters, merge_elems, next_pow2, scatter_max4

__all__ = [
    "NEUTRAL_T", "merge_counters", "merge_elems", "next_pow2", "scatter_max4",
    "dense_max", "dense_merge_counters", "dense_merge_elems", "dense_merge_lww",
]
