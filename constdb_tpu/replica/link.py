"""Per-peer replication link: dial/adopt, sync handshake, pull+push loops.

Capability parity with the reference's `Replica` link + `Puller`/`Pusher`
state machines (reference src/replica/replica.rs:155-359, pull.rs, push.rs),
redesigned for one asyncio loop instead of tokio IO threads + main thread:
the loop IS the single-writer exec thread, so apply/push steps simply run
inline between awaits.

Wire protocol (RESP frames on one TCP stream, symmetric after handshake):
  dialer:   *[sync, 0, node_id, alias, my_addr, resume_uuid, caps]
  acceptor: *[sync, 1, node_id, alias, my_addr, resume_uuid, caps]
  (`caps` is a capability bitmask — CAP_* below; pre-capability peers
  send 6-item frames and parse as caps=0)
  then each side concurrently pushes its own stream and pulls the peer's:
    *[fullsync, size, repl_last_uuid]  + `size` raw snapshot bytes
    *[partsync]
    *[replicate, origin_nodeid, prev_uuid, uuid, cmd, args...]
    *[replbatch, origin_nodeid, first_prev_uuid, last_uuid, n, payload]
      — a RUN of n consecutive encodable ops, group-encoded once into a
      columnar payload (replica/wire.py); only sent to peers that
      advertised CAP_BATCH_STREAM, under the CONSTDB_WIRE_BATCH /
      CONSTDB_WIRE_LATENCY_MS dual bound.  Non-encodable ops
      (membership, key-scoped sweeps, malformed) break runs and ship as
      ordinary per-frame barriers; CONSTDB_WIRE_BATCH=1 degenerates to
      the byte-exact per-frame stream, as does any peer without the bit.
    *[replack, uuid, now_ms]
  delta anti-entropy (both peers advertise CAP_DELTA_SYNC; pusher-driven):
    *[digest, token, 0, fanout, leaves, rollup]       per-shard rollups
    *[digestack, token, 0, shard_ids]                 puller's mismatches
    *[digest, token, 1, fanout, leaves, shard_ids, leaf_digests]
    *[digestack, token, 1, bucket_ids]
    *[deltasync, size, repl_last_uuid, n_buckets] + `size` bytes — a
      snapshot-FORMAT stream holding only the divergent buckets' state

Sync decision (reference push.rs:91-111): partial iff the peer's resume
uuid is still gap-free in my repl_log; re-checked every round AND before
every frame, so a pusher that falls off its own ring mid-stream recovers on
the SAME connection instead of shipping a gapped frame and paying a
teardown + redial (the reference leaves this case as a TODO —
pull.rs:167-172; regression-tested in tests/test_link_pushloop.py).

Off-ring recovery is digest-driven when both peers allow it (`_send_delta`,
store/digest.py): instead of re-shipping the whole keyspace, pusher and
puller exchange a two-level digest over the crc32 shard partition —
per-shard rollups first, per-key-range leaf digests for shards that
mismatch — and only the divergent buckets stream, as a snapshot-format
delta applied through the same coalesced merge path.  Resync cost becomes
O(divergence) instead of O(keyspace).  The full snapshot remains the
fallback for: peers without CAP_DELTA_SYNC (they get the exact pre-delta
byte stream), state-clearing resyncs (needs_full → FULLSYNC reset), excess
divergence (CONSTDB_DELTA_MAX_DIVERGENCE), and any failed/timed-out
negotiation.

Connection ownership: one link per peer address.  The link dials when it
has no live connection; an inbound SYNC for the same address *adopts* its
connection into the link, closing any previous one.  Replication is
idempotent (watermark dup-skip), so a brief double-connection race is
harmless.
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Optional, TYPE_CHECKING

import numpy as np

from ..errors import (CstError, InvalidSnapshot, InvalidSnapshotChecksum,
                      ReplicateCommandsLost)
from ..persist.snapshot import SectionDemux, batch_chunks
from ..resp.codec import RespParser, encode_into, encode_msg, make_parser
from ..resp.message import Arr, Bulk, Int, as_bytes, as_int
from ..server.commands import COLUMNAR_ENCODERS
from ..server.events import (EVENT_PULL_LANDED, EVENT_REPLICA_ACKED,
                             EVENT_REPLICATED)
from ..utils.hlc import now_ms
from . import wire
from .manager import ReplicaMeta

if TYPE_CHECKING:
    from ..server.io import ServerApp

log = logging.getLogger(__name__)

SYNC = b"sync"
FULLSYNC = b"fullsync"
PARTSYNC = b"partsync"
REPLICATE = b"replicate"
REPLBATCH = b"replbatch"
REPLACK = b"replack"
DIGEST = b"digest"
DIGESTACK = b"digestack"
DELTASYNC = b"deltasync"
CLUSTERTAB = b"clustertab"

# Handshake capability bits: items[6] of BOTH sync frames (dialer and
# reply).  A pre-capability peer sends 6-item frames and parses as 0 —
# absence is tolerated, never assumed to mean support (ADVICE.md round
# 5: the FULLSYNC reset flag silently downgraded on mixed-version
# meshes, recreating exactly the resurrection scenario it prevents).
CAP_FULLSYNC_RESET = 1   # honors FULLSYNC's 4th (state-wipe) field
CAP_DELTA_SYNC = 2       # answers digest frames / applies deltasync
CAP_BATCH_STREAM = 4     # decodes REPLBATCH columnar run frames
CAP_COMPRESS = 8         # validates the chunked compression framing
#                          (utils/compressio.py): REPLBATCH payloads
#                          over the floor + FULLSYNC/DELTASYNC windows
CAP_CLUSTER = 16         # decodes CLUSTERTAB slot-table gossip frames
#                          (cluster/slots.py).  Advertised ONLY when
#                          cluster mode is on — deliberately outside
#                          MY_CAPS, so a CONSTDB_CLUSTER=0 node (and
#                          every stream to/from a legacy peer) stays
#                          byte-exact pre-cluster (tests/test_cluster.py
#                          pins the stream)
MY_CAPS = CAP_FULLSYNC_RESET | CAP_DELTA_SYNC | CAP_BATCH_STREAM \
    | CAP_COMPRESS


def my_caps(app, meta=None) -> int:
    """The capability bitmask this node advertises in SYNC handshakes.
    CONSTDB_DELTA_SYNC=0 removes CAP_DELTA_SYNC so the kill switch
    disables BOTH legs: we never initiate deltas (push-loop gate) and
    conforming peers never ask us digest questions (no capability), so
    the node pays no responder-side digest folds either.
    CAP_BATCH_STREAM follows the same discipline — CONSTDB_WIRE_BATCH=1
    stops both sending batches (push-loop gate) and inviting them — and
    is additionally withheld when this node cannot or must not receive
    them: a shard-per-core receiver applies per-key inside the workers
    (server/serve_shards.py ShardApplier), CONSTDB_APPLY_BATCH=1 pins
    the whole replication intake to the exact per-frame apply path (a
    REPLBATCH would route through the columnar merge engine the pin
    exists to bypass), and a peer that once shipped a malformed payload
    is pinned to per-frame delivery (`meta.batch_wire_off`,
    replica/coalesce.py apply_wire_batch).
    CAP_COMPRESS follows the same two-leg discipline —
    CONSTDB_WIRE_COMPRESS=0 stops both compressing outbound AND
    inviting compressed frames — and is withheld per-peer after a
    malformed compressed frame (`meta.compress_wire_off`), so the
    redelivery window arrives plain."""
    caps = MY_CAPS
    if not getattr(app, "delta_sync", True):
        caps &= ~CAP_DELTA_SYNC
    if wire_batch_limit(app) <= 1 or apply_batch_limit(app) <= 1 or \
            getattr(app, "serve_plane", None) is not None or \
            (meta is not None and getattr(meta, "batch_wire_off", False)):
        caps &= ~CAP_BATCH_STREAM
    if not wire_compress_of(app) or \
            (meta is not None and
             getattr(meta, "compress_wire_off", False)):
        caps &= ~CAP_COMPRESS
    if getattr(getattr(app, "node", None), "cluster", None) is not None:
        # slot-table gossip rides the repl stream only between two
        # cluster-mode nodes; a disabled node advertises nothing and a
        # legacy peer is never sent a CLUSTERTAB frame (push-loop gate)
        caps |= CAP_CLUSTER
    return caps


def apply_batch_limit(app) -> int:
    """The node's replication-apply coalescing bound (<= 1 = the exact
    per-frame apply path, replica/coalesce.py)."""
    ab = getattr(app, "apply_batch", None)
    if ab is None:
        from ..conf import env_int
        return env_int("CONSTDB_APPLY_BATCH", 512)
    return ab


def wire_batch_limit(app) -> int:
    """Max frames per REPLBATCH run (1 = the exact per-frame stream)."""
    wb = getattr(app, "wire_batch", None)
    if wb is None:
        from ..conf import env_int
        return env_int("CONSTDB_WIRE_BATCH", 512)
    return wb


def wire_compress_of(app) -> bool:
    """Is negotiated replication compression on for this node (both
    legs: compress outbound to CAP_COMPRESS peers AND advertise the
    capability)?  CONSTDB_WIRE_COMPRESS=0 is the kill switch."""
    wc = getattr(app, "wire_compress", None)
    if wc is None:
        from ..conf import env_flag
        return env_flag("CONSTDB_WIRE_COMPRESS", True)
    return bool(wc)


def wire_compress_min(app) -> int:
    """Min REPLBATCH payload bytes before the negotiated stream
    compression engages (framing overhead beats the savings below)."""
    wm = getattr(app, "wire_compress_min", None)
    if wm is None:
        from ..conf import env_int
        return env_int("CONSTDB_WIRE_COMPRESS_MIN", 512)
    return wm


def wire_latency_of(app) -> float:
    """Seconds a drained frame may sit in the push loop's aggregated
    wire buffer before a socket flush is forced (idle cycles always
    flush at their end, so a lone write never waits this long)."""
    wl = getattr(app, "wire_latency", None)
    if wl is None:
        from ..conf import env_float
        return env_float("CONSTDB_WIRE_LATENCY_MS", 5.0) / 1000.0
    return wl


def backoff_delay(base: float, factor: float, cap: float, jitter: float,
                  node_id: int, addr: str, attempt: int,
                  salt: int = 0) -> float:
    """Reconnect delay before dial `attempt` (0-based count of
    CONSECUTIVE failures): bounded exponential growth with
    DETERMINISTIC jitter.  The jitter fraction derives from a splitmix64
    hash of (node_id, peer addr, attempt, salt) — two nodes dialing one
    returned peer still de-synchronize (no thundering herd), but a chaos
    scenario's retry cadence is a pure function of its inputs, so a
    failure replays exactly from its printed seed (random.random() here
    would make every replay walk a different schedule).  `salt` varies
    the jitter without touching the exponent (the dial loop feeds its
    iteration count, so even the flat connected-supervisor cadence
    drifts apart across nodes — see the lockstep note there)."""
    d = min(cap, base * (factor ** min(attempt, 32)))
    if jitter <= 0.0:
        return d
    import zlib
    x = (node_id * 0x9E3779B97F4A7C15 + zlib.crc32(addr.encode())
         + attempt * 1000003 + salt) & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    x ^= x >> 31
    frac = (x & 0xFFFF) / 65535.0  # [0, 1]
    return d * (1.0 - jitter + 2.0 * jitter * frac)


_READ_CHUNK = 1 << 16
# push-loop wire buffer: flush to the socket at this many buffered bytes
# (backpressure bound; the latency bound is CONSTDB_WIRE_LATENCY_MS)
_WIRE_FLUSH_BYTES = 1 << 18
# per-frame drain unit (the legacy 64-frame drain cadence)
_RUN_FRAMES = 64
# runs shorter than this ship per-frame: a 1-op REPLBATCH buys no batch
# bookkeeping and costs header + payload framing over the plain frame
_MIN_WIRE_RUN = 2


class ReplicaLink:
    """Drives replication with one peer.  `start()` begins the dial loop;
    `adopt()` installs an inbound connection."""

    def __init__(self, app: "ServerApp", meta: ReplicaMeta):
        self.app = app
        self.node = app.node
        self.meta = meta
        meta.link = self
        self.closing = False
        self._dial_task: Optional[asyncio.Task] = None
        self._serve_task: Optional[asyncio.Task] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        # node.reset_epoch at connection install; a mismatch marks this
        # stream as pre-dating a local state wipe (see _pull_loop REPLACK)
        self._epoch = 0
        # capability bits the peer advertised in the live connection's
        # handshake (0 = pre-capability peer / no connection yet)
        self._peer_caps = 0
        # digest negotiation plumbing: the push loop initiates rounds and
        # awaits DIGESTACK replies, which arrive on the PULL loop — the
        # queue bridges them (fresh per connection, so a dead stream's
        # late acks can never answer a new round's question); the cache
        # pins the puller-side matrix across a round's two levels so both
        # comparisons see ONE consistent state cut
        self._digest_acks: Optional[asyncio.Queue] = None
        self._digest_cache = None
        self._delta_token = 0
        # held by _stream_file for a whole raw payload window: the pull
        # loop answers the peer's digest questions on the SAME writer,
        # and a whole-frame write is only atomic BETWEEN frames — a
        # DIGESTACK landing inside a FULLSYNC/DELTASYNC byte window
        # would corrupt the peer's spill download
        self._stream_lock = asyncio.Lock()
        # per-download spill-file serial: a reconnect/adopt overlap can
        # briefly run TWO pull loops for one peer, and a shared spill
        # path would interleave their downloads into one corrupt file
        # (caught by the chaos harness as a spurious InvalidSnapshot on
        # a perfectly healthy stream)
        self._spill_seq = 0
        # reconnect observability (INFO repl_link_state/repl_reconnects)
        # + the backoff ladder's position: consecutive dial failures
        # since the last live connection
        self._attempts = 0
        self._ever_connected = False
        self.reconnects = 0
        # replication flow-control observability (INFO replica<i> rows):
        # unacked stream bytes in the peer's window, and whether the
        # push loop is currently pausing the ring drain on it
        self.win_unacked = 0
        self.win_paused = False
        # broadcast-plane observability (INFO replica<i> rows): bytes
        # written to this peer, encode-cache reuse, and the negotiated
        # compression's raw-vs-wire accounting for this link's stream
        self.bytes_out = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.comp_raw_bytes = 0
        self.comp_wire_bytes = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._dial_task is None or self._dial_task.done():
            self._dial_task = asyncio.create_task(self._dial_loop())

    async def stop(self) -> None:
        self.closing = True
        for t in (self._dial_task, self._serve_task):
            if t is not None and not t.done():
                t.cancel()
        await self._close_conn()
        self.meta.link = None

    @property
    def connected(self) -> bool:
        return self._serve_task is not None and not self._serve_task.done()

    @property
    def state(self) -> str:
        """Link lifecycle for INFO (`repl_link_state`): `connected`, a
        first `dialing`, or `backoff:N` after N consecutive failures —
        the previously-implicit retry cadence, made observable (the
        chaos harness's fault accounting reads it too)."""
        if self.connected:
            return "connected"
        if self.meta.dial_suspended:
            return "suspended"
        if self.closing:
            return "closed"
        return f"backoff:{self._attempts}" if self._attempts else "dialing"

    # ------------------------------------------------------ byte accounting
    # replication traffic counts into the node's net totals plus dedicated
    # repl_* gauges (reference buf_read.rs:218-236 / buf_write.rs:165-183
    # count every socket byte; a node mid-catch-up is busiest exactly here)

    def _count_in(self, n: int) -> None:
        st = self.node.stats
        st.net_in_bytes += n
        st.repl_in_bytes += n

    def _write(self, writer, data: bytes) -> None:
        st = self.node.stats
        st.net_out_bytes += len(data)
        st.repl_out_bytes += len(data)
        self.bytes_out += len(data)
        writer.write(data)

    def _flush_wire(self, writer, out: bytearray) -> bytearray:
        """One aggregated steady-state stream write — a drain cycle's
        frames in one transport call instead of one per frame (the PR 5
        reply-buffer swap: ownership moves to the transport, which
        copies only what it cannot send immediately).  Counted into
        `repl_wire_bytes_out` so the bench's wire-bytes-per-op compare
        sees ONLY stream frames, not snapshots or acks."""
        self.node.stats.repl_wire_bytes_out += len(out)
        self._write(writer, out)
        return bytearray()

    def _encode_frames(self, out: bytearray, run: list) -> None:
        """The per-frame REPLICATE encoding, byte-exact with the pre-PR
        stream — the ONE definition both the legacy-peer branch and the
        demoted-run fallback share (the byte-exactness pin in
        tests/test_wire_batch.py covers every caller through it)."""
        nid = self.node.node_id
        for e in run:
            encode_into(out, Arr([
                Bulk(REPLICATE), Int(nid), Int(e.prev_uuid), Int(e.uuid),
                Bulk(e.name), *e.args]))

    def _encode_wire_run(self, out: bytearray, run: list, cursor: int,
                         compress: bool = False,
                         comp_min: int = 0) -> tuple:
        """Encode one drained run into `out`: maximal sub-runs of
        consecutive encodable ops become REPLBATCH frames
        (replica/wire.py), everything else — barriers, sub-runs below
        _MIN_WIRE_RUN, runs the codec demotes — ships as the exact
        per-frame REPLICATE frames.  `compress`: wrap payloads of at
        least `comp_min` bytes in the negotiated compression framing
        (utils/compressio.py), kept only when it actually shrinks them.
        Returns (cursor, batches, batch_frames, comp_raw, comp_wire) —
        the counts the encode-once cache republishes per reusing peer."""
        node = self.node
        nid = node.node_id
        st = node.stats
        enc_has = COLUMNAR_ENCODERS.__contains__
        batches = batch_frames = comp_raw = comp_wire = 0
        i, n = 0, len(run)
        while i < n:
            j = i
            while j < n and enc_has(run[j].name):
                j += 1
            if j - i >= _MIN_WIRE_RUN:
                sub = run[i:j]
                payload = wire.build_wire_batch(sub, nid)
                if payload is not None:
                    if compress and len(payload) >= comp_min:
                        from ..utils.compressio import compress_bytes
                        z = compress_bytes(payload, level=1)
                        if len(z) < len(payload):
                            comp_raw += len(payload)
                            comp_wire += len(z)
                            payload = z
                    encode_into(out, Arr([
                        Bulk(REPLBATCH), Int(nid), Int(sub[0].prev_uuid),
                        Int(sub[-1].uuid), Int(len(sub)),
                        Bulk(payload)]))
                    st.repl_wire_batches_out += 1
                    st.repl_wire_batch_frames_out += len(sub)
                    batches += 1
                    batch_frames += len(sub)
                    i = j
                    cursor = sub[-1].uuid
                    continue
                # demotion must be LOUD: count it and log it — a codec
                # that silently lags the encoder table would erase the
                # whole batching win without tripping a single test
                x = st.extra
                x["repl_wire_encode_demotions"] = \
                    x.get("repl_wire_encode_demotions", 0) + 1
                log.warning(
                    "push %s: wire codec demoted a run of %d encodable "
                    "ops to per-frame delivery", self.meta.addr, j - i)
            stop = j if j > i else i + 1
            self._encode_frames(out, run[i:stop])
            cursor = run[stop - 1].uuid
            i = stop
        if comp_raw:
            st.repl_comp_raw_bytes += comp_raw
            st.repl_comp_wire_bytes += comp_wire
        return cursor, batches, batch_frames, comp_raw, comp_wire

    async def _close_conn(self) -> None:
        w, self._writer = self._writer, None
        if w is not None:
            try:
                w.close()
                await w.wait_closed()
            except (ConnectionError, OSError):
                pass

    # ----------------------------------------------------------------- dial

    async def _dial_loop(self) -> None:
        """Reconnect-forever with BOUNDED EXPONENTIAL backoff (the
        reference retries at a flat 5s — replica/replica.rs:254-271; a
        flat cadence hammers a recovering peer from the whole mesh at
        once, and an implicit one is unobservable).  Consecutive
        failures walk base * factor^n up to the ceiling, with
        deterministic jitter (`backoff_delay`); any live connection —
        dialed or adopted — resets the ladder.  While connected this
        loop is just the reconnect supervisor, polling at the base
        cadence."""
        app = self.app
        it = 0
        while not self.closing and self.meta.alive and \
                not self.meta.dial_suspended:
            if not self.connected:
                try:
                    await self._dial_once()
                except (ConnectionError, OSError, CstError,
                        asyncio.TimeoutError) as e:
                    self._attempts += 1
                    log.debug("dial %s failed (attempt %d): %s",
                              self.meta.addr, self._attempts, e)
            it += 1
            if self.connected:
                # supervisor cadence: base delay, but still JITTERED
                # (per iteration) — two peers that dial each other in
                # the same instant each install their own connection
                # and close the other's; identical un-jittered sleeps
                # would redo that collision forever, in lockstep (the
                # chaos suite's connection-kill test caught exactly
                # this livelock when the jitter briefly covered only
                # the failure branch)
                delay = backoff_delay(
                    app.reconnect_delay, 1.0, app.reconnect_delay,
                    app.reconnect_jitter, self.node.node_id,
                    self.meta.addr, 0, salt=it)
            else:
                # _attempts was already bumped for the failure this
                # sleep follows, so rung 0 — the documented BASE delay
                # of the first retry — is attempts-1 (a drop without a
                # failed dial yet leaves attempts at 0: also the base)
                delay = backoff_delay(
                    app.reconnect_delay, app.reconnect_factor,
                    app.reconnect_max, app.reconnect_jitter,
                    self.node.node_id, self.meta.addr,
                    max(0, self._attempts - 1), salt=it)
            await asyncio.sleep(delay)

    async def _dial_once(self) -> None:
        host, port = self.meta.addr.rsplit(":", 1)
        epoch0 = self.node.reset_epoch  # watermark snapshot validity fence
        reader, writer = await self.app.open_peer_connection(host,
                                                             int(port))
        try:
            self._write(writer, encode_msg(Arr([
                Bulk(SYNC), Int(0), Int(self.node.node_id),
                Bulk(self.node.alias.encode()),
                Bulk(self.app.advertised_addr.encode()),
                Int(self.meta.uuid_he_sent),
                Int(my_caps(self.app, self.meta))])))
            await writer.drain()
            parser = make_parser()
            msg = await _read_msg(reader, parser,
                                  timeout=self.app.handshake_timeout,
                                  count=self._count_in)
            peer_resume = self._check_sync_reply(msg)
            if self.node.reset_epoch != epoch0:
                # a local state wipe landed mid-handshake: the resume
                # watermark we already sent is PRE-wipe, so the peer would
                # stream nothing and its drained beacon would advance our
                # zeroed watermark past ops the wipe discarded.  Abort;
                # the dial loop retries with the post-wipe watermark.
                raise CstError("local state wiped mid-handshake; redialing")
        except BaseException:
            writer.close()
            raise
        self._install(reader, writer, parser, peer_resume)

    def _check_sync_reply(self, msg) -> int:
        from ..resp.message import Err
        if isinstance(msg, Err) and msg.val.startswith(b"FORGOTTEN"):
            # the peer expelled us (FORGET): stop dialing it.  The flag is
            # cleared when someone re-MEETs us and dials in (adopt()).
            self.meta.dial_suspended = True
            log.info("peer %s rejected sync: forgotten; suspending dial",
                     self.meta.addr)
            raise CstError(f"forgotten by {self.meta.addr}")
        items = msg.items if isinstance(msg, Arr) else None
        if not items or as_bytes(items[0]).lower() != SYNC or \
                as_int(items[1]) != 1:
            raise CstError(f"bad sync reply from {self.meta.addr}: {msg!r}")
        self.meta.node_id = as_int(items[2])
        self.meta.alias = as_bytes(items[3]).decode("utf-8", "replace")
        self._peer_caps = as_int(items[6]) if len(items) > 6 else 0
        return as_int(items[5])

    # ---------------------------------------------------------------- adopt

    def adopt(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
              parser: RespParser, peer_resume: int,
              peer_caps: int = 0) -> None:
        """Install an inbound connection (the passive side of SYNC —
        reference replica.rs:16-40 steals the client's Conn).
        `peer_caps`: capability bits from the peer's SYNC frame (0 = a
        pre-capability peer)."""
        self.meta.dial_suspended = False  # the mesh re-admitted us
        self._peer_caps = peer_caps
        self._install(reader, writer, parser, peer_resume)

    def kick(self) -> None:
        """Drop the live connection (if any) so the dial loop — ours or the
        peer's — re-handshakes from the meta's CURRENT watermarks.  Used
        after a local state wipe (Node.reset_for_full_resync): an existing
        stream's positions describe state that no longer exists."""
        t = self._serve_task
        if t is not None and not t.done():
            t.cancel()
        w, self._writer = self._writer, None
        if w is not None:
            w.close()

    def _install(self, reader, writer, parser, peer_resume: int) -> None:
        self.meta.last_seen_ms = now_ms()
        self._attempts = 0  # any live connection resets the backoff ladder
        if self._ever_connected:
            # every re-established connection after the link's first —
            # dialed or adopted — is one reconnect (INFO repl_reconnects;
            # the chaos oracle checks this against its injected kills)
            self.reconnects += 1
            self.node.stats.repl_reconnects += 1
        self._ever_connected = True
        self._epoch = self.node.reset_epoch
        self._digest_acks = asyncio.Queue()
        self._digest_cache = None
        old_task, old_writer = self._serve_task, self._writer
        self._writer = writer
        self._serve_task = asyncio.create_task(
            self._serve(reader, writer, parser, peer_resume))
        if old_task is not None and not old_task.done():
            old_task.cancel()
        if old_writer is not None:
            old_writer.close()

    # ---------------------------------------------------------------- serve

    async def _serve(self, reader, writer, parser, peer_resume: int) -> None:
        push = asyncio.create_task(self._push_loop(writer, peer_resume))
        try:
            await self._pull_loop(reader, writer, parser)
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            log.debug("link %s dropped: %s", self.meta.addr, e)
        except ReplicateCommandsLost as e:
            log.warning("link %s: %s — forcing full resync", self.meta.addr, e)
        except CstError as e:
            log.warning("link %s protocol error: %s", self.meta.addr, e)
        except asyncio.CancelledError:
            raise
        finally:
            push.cancel()
            if self._writer is writer:
                self._writer = None
            writer.close()

    # ----------------------------------------------------------------- push

    async def _push_loop(self, writer, peer_resume: int) -> None:
        """Outbound half (reference push.rs): full-vs-partial, then stream
        repl_log frames; REPLACK heartbeat.

        The send position is a LOCAL cursor, never read back from the
        shared meta.  During a reconnect/adopt overlap two push loops
        briefly coexist on one meta; with a shared cursor the dying loop
        keeps advancing it while writing to a dead socket, the new loop
        then skips those entries as already-sent, and its drained beacon
        advances the peer's pull watermark straight over the hole —
        silently lost ops mesh-wide (found by the round-5 chaos suite).
        A local cursor confines every advance to the connection it was
        actually written to; meta.uuid_i_sent is only mirrored for
        observability while this connection is still the live one."""
        node = self.node
        meta = self.meta
        # EVENT_PULL_LANDED wakes this loop when OUR pull side lands a
        # batch of the peer's stream, so the REPLACK below goes out once
        # per covering batch instead of a heartbeat later;
        # EVENT_REPLICA_ACKED wakes it when a REPLACK lands, so a
        # window-paused drain (below) resumes the moment the peer
        # catches up instead of a heartbeat later
        consumer = node.events.new_consumer(
            EVENT_REPLICATED | EVENT_PULL_LANDED | EVENT_REPLICA_ACKED)
        wire_batch = wire_batch_limit(self.app)
        wire_latency = wire_latency_of(self.app)
        wire_compress = wire_compress_of(self.app)
        wire_comp_min = wire_compress_min(self.app)
        # replication flow control (CONSTDB_REPL_WINDOW): stream bytes
        # written to this connection but not yet covered by the peer's
        # REPLACK watermark.  `inflight` holds (cursor_after_flush,
        # nbytes) per aggregated wire flush; entries retire as
        # uuid_i_acked passes their cursor.  When the total passes the
        # window the loop stops DRAINING THE RING for this peer —
        # memory stops growing here and in the transport — and resumes
        # on ack; a long stall degrades to ring eviction, recovered by
        # the certified delta/full resync path on this same connection.
        window = getattr(self.app, "repl_window", None)
        if window is None:
            from ..conf import env_int
            window = env_int("CONSTDB_REPL_WINDOW", 16 << 20)
        from collections import deque
        inflight: deque = deque()
        inflight_bytes = 0
        paused = False
        loop = asyncio.get_running_loop()
        try:
            synced = False  # peer_resume not yet honored
            # lint: pin[cursor] — the send cursor is OWNED by this loop
            # (docstring above: a local cursor confines every advance to
            # this connection); every use re-validates against the live
            # ring via can_resume_from/run_after, so the pre-await value
            # is the intended one, not a stale shared read
            cursor = 0
            last_ack = 0.0
            tab_rev = -1  # slot-table revision last gossiped on this conn
            while True:
                acked = meta.uuid_i_acked
                while inflight and inflight[0][0] <= acked:
                    inflight_bytes -= inflight.popleft()[1]
                self.win_unacked = inflight_bytes
                win_full = bool(window) and synced and \
                    inflight_bytes > window
                if win_full and not paused:
                    paused = self.win_paused = True
                    node.stats.repl_window_pauses += 1
                    log.warning(
                        "push %s: %d unacked stream bytes over "
                        "CONSTDB_REPL_WINDOW=%d; pausing ring drain "
                        "until the peer acks", meta.addr, inflight_bytes,
                        window)
                elif not win_full:
                    paused = self.win_paused = False
                if not paused and \
                        (not synced or
                         not node.repl_log.can_resume_from(cursor)):
                    resume = peer_resume if not synced else cursor
                    if node.repl_log.can_resume_from(resume):
                        # partial replay is always the lossless choice when
                        # the log covers the resume point: delete OPS are
                        # still in the ring even after their tombstones
                        # were physically collected (manager.min_uuid)
                        self._write(writer, encode_msg(Arr([Bulk(PARTSYNC)])))
                        cursor = resume
                    else:
                        # a peer excluded from the GC horizon (needs_full)
                        # whose resume point also fell off the ring may hold
                        # keys whose tombstones we already collected — a
                        # plain snapshot merge cannot delete them, so it
                        # must WIPE before merging (fullsync reset flag)
                        reset = meta.needs_full
                        if reset and not (self._peer_caps
                                          & CAP_FULLSYNC_RESET):
                            # a pre-capability peer would silently merge
                            # WITHOUT wiping — the exact resurrection
                            # scenario the reset flag exists to prevent.
                            # Refuse loudly instead of downgrading; the
                            # dial loop retries with backoff until the
                            # peer upgrades (or an operator intervenes).
                            log.error(
                                "push %s: peer needs a state-clearing "
                                "full resync but did not advertise the "
                                "fullsync-reset capability (mixed-"
                                "version mesh?); refusing to downgrade "
                                "to a non-wiping sync", meta.addr)
                            x = node.stats.extra
                            x["fullsync_reset_refused"] = \
                                x.get("fullsync_reset_refused", 0) + 1
                            writer.close()
                            return
                        # digest-driven partial resync where it is sound:
                        # an ordinary off-ring catch-up (incl. the
                        # mid-stream ring-falloff recovery, which re-enters
                        # this decision) against a CAP_DELTA_SYNC peer.
                        # A state-CLEARING resync must stay a full
                        # snapshot — the peer wipes first, so there is no
                        # surviving state to diff against.  _send_delta
                        # returns None when the negotiation demotes
                        # (threshold, timeout, malformed reply) and the
                        # exact full-sync path runs instead.
                        cursor = None
                        if not reset and \
                                (self._peer_caps & CAP_DELTA_SYNC) and \
                                getattr(self.app, "delta_sync", True):
                            cursor = await self._send_delta(writer)
                            if cursor is None:
                                # EVERY demotion exit counts — threshold,
                                # timeout, malformed reply — so INFO's
                                # repl_delta_demotions matches the
                                # invariant doc and a silently failing
                                # delta path is visible next to the
                                # climbing repl_full_syncs
                                x = node.stats.extra
                                x["repl_delta_demotions"] = \
                                    x.get("repl_delta_demotions", 0) + 1
                        if cursor is None:
                            cursor = await self._send_snapshot(
                                writer, reset=reset)
                    synced = True
                    meta.needs_full = False

                # Drain the log in RUNS, frames aggregated into ONE wire
                # buffer per socket flush (the PR 5 reply-buffer swap, on
                # the push side) under a dual bound: _WIRE_FLUSH_BYTES
                # (backpressure) and the wire latency (bytes keep moving
                # through a long catch-up drain).  An idle cycle always
                # flushes at its end, so a lone write ships immediately
                # with the exact per-frame latency.  Runs of consecutive
                # encodable ops group-encode into REPLBATCH frames when
                # the peer can decode them; everything else — legacy
                # peers, CONSTDB_WIRE_BATCH=1, barriers, demoted runs —
                # is the byte-exact per-frame stream.
                #
                # Broadcast fan-out (round 17): the FIRST loop to drain
                # a run publishes its finished wire bytes in the node's
                # encode-once cache; every other loop at the same cursor
                # and caps-class splices the published bytes instead of
                # re-encoding, so N-peer steady-state encode work is
                # O(ops), not O(N·ops).  The caps-class key pins every
                # knob that changes the bytes: "b"/"bz" for the plain/
                # compressed REPLBATCH stream, "f" for the byte-exact
                # per-frame rendering legacy and demoted peers share.
                batching = wire_batch > 1 and \
                    bool(self._peer_caps & CAP_BATCH_STREAM)
                compressing = batching and \
                    bool(self._peer_caps & CAP_COMPRESS) and \
                    wire_compress
                caps_class = ("bz" if compressing else "b") if batching \
                    else "f"
                cache = node.wire_cache
                if cache.enabled:
                    # ring-eviction coherence: entries below the
                    # resumable horizon can never be read again
                    cache.evict_below(node.repl_log.evicted_up_to)
                out = bytearray()
                t_flush = loop.time()

                def flush_out(buf: bytearray) -> bytearray:
                    # every aggregated stream flush is one window entry:
                    # acked when the peer's REPLACK watermark passes the
                    # cursor the flush ended at
                    nonlocal inflight_bytes
                    inflight.append((cursor, len(buf)))
                    inflight_bytes += len(buf)
                    return self._flush_wire(writer, buf)

                while not paused:
                    hit = None
                    if cache.enabled:
                        # the splice honors the same emission floor
                        # run_after applies (encode_cache.get docstring:
                        # a published-but-not-yet-durable run must not
                        # be emitted through the cache side door)
                        fl = getattr(node.repl_log, "floor", None)
                        hit = cache.get(
                            caps_class, cursor,
                            below=fl() if callable(fl) else None)
                    if hit is not None:
                        # published by another peer's loop at this exact
                        # cursor: splice the finished bytes and republish
                        # the per-send wire counters from the entry
                        out += hit.payload
                        cursor = hit.end
                        self.cache_hits += 1
                        st = node.stats
                        st.repl_encode_cache_hits += 1
                        st.repl_wire_batches_out += hit.batches
                        st.repl_wire_batch_frames_out += hit.batch_frames
                        st.repl_comp_raw_bytes += hit.comp_raw
                        st.repl_comp_wire_bytes += hit.comp_wire
                        self.comp_raw_bytes += hit.comp_raw
                        self.comp_wire_bytes += hit.comp_wire
                    else:
                        # byte-capped runs: the flush bound below must
                        # get a chance to engage BEFORE a backlog of
                        # huge values is encoded into one frame/buffer
                        # (a lone oversized entry still ships whole, as
                        # per-frame always did)
                        run = node.repl_log.run_after(
                            cursor,
                            wire_batch if batching else _RUN_FRAMES,
                            _WIRE_FLUSH_BYTES)
                        if not run:
                            break
                        if run[0].prev_uuid > cursor:
                            # the ring evicted past our cursor while this
                            # loop yielded (the drain below): streaming
                            # the run would hand the peer a gap, blow up
                            # its pull loop (ReplicateCommandsLost) and
                            # force a teardown + redial + snapshot over a
                            # FRESH connection.  Recover IN PLACE
                            # instead: stop here and let the round
                            # decision re-send a full snapshot on this
                            # same stream (eviction past the cursor
                            # implies can_resume_from(cursor) is False).
                            # This is the fallback the module header
                            # documents — the reference leaves the case
                            # unhandled (pull.rs:167-172).
                            log.warning(
                                "push %s: repl_log evicted past send "
                                "cursor mid-stream; resyncing in place",
                                meta.addr)
                            break
                        seg = bytearray()
                        start = cursor
                        if batching:
                            (cursor, nb, nbf, craw,
                             cwire) = self._encode_wire_run(
                                seg, run, cursor, compress=compressing,
                                comp_min=wire_comp_min)
                        else:
                            self._encode_frames(seg, run)
                            cursor = run[-1].uuid
                            nb = nbf = craw = cwire = 0
                        self.comp_raw_bytes += craw
                        self.comp_wire_bytes += cwire
                        if cache.enabled:
                            self.cache_misses += 1
                            node.stats.repl_encode_cache_misses += 1
                            cache.put(caps_class, start, cursor,
                                      bytes(seg), batches=nb,
                                      batch_frames=nbf, comp_raw=craw,
                                      comp_wire=cwire,
                                      readers=self._expected_readers())
                        out += seg
                    if len(out) >= _WIRE_FLUSH_BYTES or \
                            loop.time() - t_flush >= wire_latency:
                        out = flush_out(out)
                        await writer.drain()  # backpressure + yield
                        t_flush = loop.time()
                    if window and inflight_bytes > window:
                        # the window filled MID-drain: stop pulling the
                        # ring now; the top of the loop re-evaluates
                        # (and counts) the pause
                        break
                if out:
                    out = flush_out(out)
                if self._writer is writer:
                    meta.uuid_i_sent = cursor  # observability (INFO)
                if not paused and not node.repl_log.can_resume_from(cursor):
                    # fell off the ring mid-round: resync NOW (top of the
                    # loop) instead of sleeping out a heartbeat first
                    await writer.drain()
                    continue

                cl = node.cluster
                if cl is not None and (self._peer_caps & CAP_CLUSTER) \
                        and cl.rev != tab_rev:
                    # slot-table gossip: once per table CHANGE per
                    # connection (first round includes the initial
                    # table).  Gated on cl.rev, not the epoch: a
                    # per-slot join or a learned address can change the
                    # table without minting a new epoch, and peers need
                    # that news too.  Only to peers that advertised the
                    # capability — a legacy or disabled peer's stream
                    # carries zero cluster bytes (the byte-exact pin).
                    tab_rev = cl.rev
                    self._write(writer, encode_msg(Arr([
                        Bulk(CLUSTERTAB), Int(cl.epoch),
                        Bulk(cl.table.serialize())])))

                now = asyncio.get_running_loop().time()
                # durable-ack cap (persist/oplog.py): the advertised
                # pull watermark and coverage may only name intake
                # frames the op log has made durable — a torn tail must
                # never clip a frame a peer was already TOLD we hold
                # (its GC gates tombstone collection on these values).
                # Without an op log both caps are identity.
                oplog = node.oplog
                ack_val = meta.uuid_he_sent
                if oplog is not None:
                    # clamped to the last advertised value: a reconnect
                    # redelivery re-appends frames BELOW an ack already
                    # sent, but the original copies are in the durable
                    # prefix — regressing the advertisement would only
                    # confuse monotonicity monitors, never durability
                    ack_val = max(oplog.cap_ack(meta.node_id, ack_val),
                                  meta.uuid_he_acked)
                if (ack_val > meta.uuid_he_acked
                        or now - last_ack >= self.app.heartbeat):
                    # coverage is only computed when an ack actually
                    # goes out — it is an O(peers) scan and this loop
                    # wakes per delivered batch under firehose intake
                    coverage = node.replicas.cluster_coverage()
                    if oplog is not None:
                        coverage = oplog.cap_coverage(coverage)
                    # beacon: with the log fully drained, every uuid this
                    # node will EVER stream from now on exceeds its current
                    # HLC — peers may advance their pull watermark to it, so
                    # idle nodes don't pin the cluster GC horizon at 0.
                    # Item 5 is this node's CLUSTER COVERAGE (the uuid it
                    # holds every origin's stream up to) — the peer's GC
                    # gates third-party tombstone collection on it
                    # (manager.min_uuid; legacy receivers ignore extras).
                    drained = cursor >= node.repl_log.last_uuid
                    beacon = node.hlc.current if drained else 0
                    if beacon and oplog is not None:
                        # the beacon is the promise "every uuid I will
                        # EVER mint exceeds B" — with a durable op log,
                        # B is capped at the last group-committed HLC
                        # mark, or a crash could rewind the clock below
                        # an already-sent beacon and peers would dup-
                        # skip the re-minted window forever
                        # (persist/oplog.py beacon_cap)
                        beacon = min(beacon, oplog.beacon_cap)
                    self._write(writer, encode_msg(Arr([
                        Bulk(REPLACK), Int(ack_val), Int(now_ms()),
                        Int(beacon),
                        Int(coverage)])))
                    meta.uuid_he_acked = ack_val
                    last_ack = now
                await writer.drain()
                await consumer.wait(timeout=self.app.heartbeat)
        except (ConnectionError, OSError) as e:
            log.debug("push %s dropped: %s", self.meta.addr, e)
        finally:
            # the window gauges describe THIS connection's in-flight
            # bytes; left set, INFO would report a stale paused window
            # for a link that is reconnecting and not pushing at all
            self.win_unacked = 0
            self.win_paused = False
            consumer.close()

    def _expected_readers(self) -> int:
        """How many OTHER live links may reuse a run encoding published
        at this link's cursor — the encode-once cache's initial
        ref-count.  A heuristic (peers can connect later, classes can
        differ), so the cache's LRU byte bound is the safety net; what
        it guarantees is the cheap case: a single-peer node publishes
        nothing and pays nothing."""
        n = 0
        for m in self.node.replicas.live_peers():
            lk = m.link
            if lk is not None and lk is not self and not lk.closing:
                n += 1
        return n

    def _bulk_compress(self) -> bool:
        """Ship this peer's FULLSYNC/DELTASYNC window as the compressed
        snapshot container?  Negotiated (CAP_COMPRESS) and gated on the
        node-wide kill switch; a legacy or demoted peer gets the exact
        plain byte stream."""
        return bool(self._peer_caps & CAP_COMPRESS) and \
            wire_compress_of(self.app) and \
            not getattr(self.meta, "compress_wire_off", False)

    async def _send_snapshot(self, writer, reset: bool = False) -> int:
        """Fork-free full sync with bounded memory: acquire the node's
        SHARED on-disk dump (produced once, reused by every concurrently
        or subsequently syncing peer while the repl_log still covers its
        watermark — reference server.rs:221-250 reuse + push.rs:34-71
        send_file, minus the fork) and stream the file to the socket in
        fixed-size pieces.  Returns the dump's repl watermark — the push
        loop's new send cursor (the repl_log gap above it streams next,
        which `can_resume_from` guarantees is still present)."""
        # a CAP_COMPRESS peer gets the compressed-container VARIANT of
        # the shared dump — produced once, reused by every capable peer;
        # the receiver's snapshot loader sniffs the container magic, so
        # the FULLSYNC header and download path are unchanged on the
        # wire (and a legacy peer's stream stays byte-exact pre-PR)
        dump = await self.app.shared_dump.acquire(
            compressed=self._bulk_compress())
        self.node.stats.repl_full_syncs += 1
        await self._stream_file(writer, dump.path, encode_msg(Arr([
            Bulk(FULLSYNC), Int(dump.size), Int(dump.repl_last),
            Int(1 if reset else 0)])))
        return dump.repl_last

    async def _stream_file(self, writer, path: str, header: bytes) -> None:
        """`header` + the file's bytes to the socket in fixed-size
        pieces (the FULLSYNC and DELTASYNC transports share this).
        Open + reads off-loop: a resync burst on a loaded disk must not
        hiccup every client (ASYNC-BLOCK; the writes are socket-buffered
        and drain() yields between pieces).  The FIRST piece is read
        BEFORE the header goes out so the stream never shows a header
        with zero payload bytes behind it — the pre-executor code had no
        such window (header + first read happened in one task step) and
        the wire contract keeps it."""
        loop = asyncio.get_running_loop()
        f = await loop.run_in_executor(None, open, path, "rb")
        try:
            async with self._stream_lock:
                piece = await loop.run_in_executor(None, f.read,
                                                   _READ_CHUNK)
                self._write(writer, header)
                while piece:
                    self._write(writer, piece)
                    await writer.drain()
                    piece = await loop.run_in_executor(None, f.read,
                                                       _READ_CHUNK)
        finally:
            f.close()

    # ---------------------------------------------------- delta anti-entropy

    async def _local_digest(self, fanout: int, leaves: int) -> np.ndarray:
        """This node's (fanout, leaves) state digest matrix
        (store/digest.py): plane-aware — a shard-per-core node sums its
        workers' matrices (their keys partition the keyspace), a plain
        node folds its own keyspace after an engine flush."""
        node = self.node
        if node.serve_plane is not None:
            return await node.serve_plane.state_digest(fanout, leaves)
        node.ensure_flushed()
        from ..store.digest import state_digest_matrix
        # the FIRST digest on a long-lived store pays the per-item
        # Python crc32 backlog over every key and member — seconds at
        # north-star scale, which on-loop would stall serving and
        # REPLACK heartbeats past the peer's ack deadline.  Warm the
        # caches off-loop; rows landing mid-warm are picked up by the
        # (now tiny) incremental sync inside the fold below.
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, node.ks.warm_digest_caches)
        node.ensure_flushed()  # re-land anything that arrived mid-warm
        return state_digest_matrix(node.ks, fanout, leaves)

    async def _await_digest_ack(self, token: int, level: int
                                ) -> Optional[bytes]:
        """Next DIGESTACK payload for (token, level), bridged over from
        the pull loop; None on timeout/malformed (the caller demotes to
        a full snapshot).  Acks from abandoned rounds are discarded."""
        q = self._digest_acks
        if q is None:
            return None
        timeout = getattr(self.app, "handshake_timeout", 10.0)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            left = deadline - loop.time()
            if left <= 0:
                return None
            try:
                items = await asyncio.wait_for(q.get(), left)
            except asyncio.TimeoutError:
                return None
            try:
                if as_int(items[1]) == token and as_int(items[2]) == level:
                    return as_bytes(items[3])
            except (CstError, IndexError):
                return None

    async def _refine_keys(self, writer, token: int, fanout: int,
                           leaves: int, mask: np.ndarray):
        """Level-2 refinement: exchange per-crc content stamps for the
        divergent buckets so only keys that actually differ stream —
        the whole-bucket export ships every innocent bystander sharing
        a bucket with a divergent key (~bucket_keys-1 per hit), which
        at the default grain is most of the delta payload.  Returns the
        delta batch, or None to fall back to the whole-bucket export
        (timeout / malformed reply — still a valid delta, just fatter)."""
        from ..store.digest import (KeyStampTable, bucket_key_sel,
                                    masked_key_count)
        node = self.node
        st = node.stats
        sel = bucket_key_sel(node.ks, fanout, leaves, mask)
        if masked_key_count(node.ks, fanout, leaves, mask,
                            key_sel=sel) < \
                getattr(self.app, "delta_stamp_min", 4096):
            # the stamp exchange costs ~12B per listed key; below this
            # scale the whole-bucket export is already small enough that
            # another round can't pay for itself (and may cost MORE than
            # the bytes it saves — pinned by the e2e resync-beats-full
            # assertion at tiny stores).  Gate on the cheap bucket-math
            # count BEFORE building the stamp table: its _key_accum hash
            # pass is O(keyspace), which the common small-divergence
            # delta would pay only to throw away.
            return None
        table = KeyStampTable(node.ks, fanout, leaves, mask, key_sel=sel)
        st.repl_digest_rounds += 1
        self._write(writer, encode_msg(Arr([
            Bulk(DIGEST), Int(token), Int(2), Int(fanout), Int(leaves),
            Bulk(table.crcs.astype("<u4").tobytes()),
            Bulk(table.stamps.astype("<u8").tobytes())])))
        await writer.drain()
        ack = await self._await_digest_ack(token, 2)
        if ack is None:
            log.warning("delta sync %s: no usable key-stamp reply; "
                        "falling back to the whole-bucket delta",
                        self.meta.addr)
            return None
        idx = np.frombuffer(ack, dtype="<i4")
        if len(idx) and (int(idx.min()) < 0 or
                         int(idx.max()) >= len(table.crcs)):
            log.warning("delta sync %s: out-of-range key-stamp reply; "
                        "falling back to the whole-bucket delta",
                        self.meta.addr)
            return None
        log.debug("delta sync %s: %d/%d stamped keys diverged",
                  self.meta.addr, len(idx), len(table.crcs))
        return table.export_batch(node.ks, idx.astype(np.int64))

    async def _send_delta(self, writer) -> Optional[int]:
        """Digest-driven partial resync (the tentpole of the delta
        anti-entropy protocol — see the module header).  Two rounds:
        per-shard rollups, then leaf digests for mismatching shards;
        the divergent buckets stream as a snapshot-format delta file.
        Returns the new send cursor (the delta's watermark), or None
        when the negotiation demoted to a full snapshot."""
        from ..persist.snapshot import NodeMeta, write_snapshot_file
        from ..store.digest import DIGEST_FANOUT, leaves_for
        node = self.node
        app = self.app
        st = node.stats
        meta = self.meta
        if self._digest_acks is None:
            self._digest_acks = asyncio.Queue()
        # watermarks FIRST, digest after: the digested state is then a
        # superset of every op <= repl_last — ops landing in between are
        # in the repl_log above it and replay after the delta, the same
        # redelivery class the shared full-sync dump documents
        # (persist/share.py; coalesced re-applies are idempotent).  The
        # REPLICA RECORDS are part of the same cut: a third-party frame
        # landing during the digest rounds below is in our state but in
        # NO bucket the (already-computed) digests flagged — a record
        # captured after the awaits would claim its origin's watermark
        # anyway, and the receiver's adoption would skip the frame's
        # redelivery forever (found by the chaos harness: one node held
        # a register's stale LWW loser mesh-wide-acked).
        repl_last = getattr(node.repl_log, "landed_last_uuid",
                            node.repl_log.last_uuid)
        records = node.replicas.records()
        fanout = DIGEST_FANOUT
        plane = node.serve_plane
        if plane is not None:
            n_keys = await plane.key_count()
        else:
            n_keys = node.ks.n_keys()
        leaves = leaves_for(n_keys, fanout,
                            max(1, getattr(app, "delta_bucket_keys", 8)))
        self._delta_token += 1
        token = self._delta_token
        matrix = await self._local_digest(fanout, leaves)
        st.repl_digest_rounds += 1
        self._write(writer, encode_msg(Arr([
            Bulk(DIGEST), Int(token), Int(0), Int(fanout), Int(leaves),
            Bulk(matrix.sum(axis=1, dtype=np.uint64)
                 .astype("<u8").tobytes())])))
        await writer.drain()
        ack = await self._await_digest_ack(token, 0)
        if ack is None:
            log.warning("delta sync %s: no usable rollup reply; demoting "
                        "to a full snapshot", meta.addr)
            return None
        shards = np.frombuffer(ack, dtype="<i8")
        buckets = np.zeros(0, dtype=np.int64)
        if len(shards):
            if int(shards.min()) < 0 or int(shards.max()) >= fanout:
                log.warning("delta sync %s: out-of-range shard ids in "
                            "reply; demoting to a full snapshot", meta.addr)
                return None
            shards64 = shards.astype(np.int64)
            st.repl_digest_rounds += 1
            self._write(writer, encode_msg(Arr([
                Bulk(DIGEST), Int(token), Int(1), Int(fanout), Int(leaves),
                Bulk(ack),
                Bulk(matrix[shards64].astype("<u8").tobytes())])))
            await writer.drain()
            ack = await self._await_digest_ack(token, 1)
            if ack is None:
                log.warning("delta sync %s: no usable leaf reply; "
                            "demoting to a full snapshot", meta.addr)
                return None
            buckets = np.frombuffer(ack, dtype="<i8").astype(np.int64)
            if len(buckets) and (int(buckets.min()) < 0 or
                                 int(buckets.max()) >= fanout * leaves):
                log.warning("delta sync %s: out-of-range bucket ids in "
                            "reply; demoting to a full snapshot", meta.addr)
                return None
        # divergence threshold: past this bucket fraction a delta stops
        # paying for itself (the leaf granularity targets ~bucket_keys
        # keys per bucket, so bucket fraction ~ key fraction); demote —
        # and name the shards being demoted, so an operator can see
        # WHERE the mesh diverged
        max_div = getattr(app, "delta_max_divergence", 0.5)
        if len(buckets) > max_div * fanout * leaves:
            dirty_shards = sorted(set((buckets // leaves).tolist()))
            log.warning(
                "delta sync %s: %d/%d buckets diverged (> %.0f%%); "
                "demoting shards %s to a full transfer", meta.addr,
                len(buckets), fanout * leaves, max_div * 100, dirty_shards)
            return None
        mask = np.zeros(fanout * leaves, dtype=bool)
        mask[buckets] = True
        nmeta = NodeMeta(node_id=node.node_id, alias=node.alias,
                         addr=getattr(app, "advertised_addr", ""),
                         repl_last_uuid=repl_last)
        chunk_keys = getattr(app, "snapshot_chunk_keys", 1 << 16)
        level = getattr(app, "snapshot_compress_level", 1)
        if plane is not None:
            # shard-per-core pusher: whole-bucket export via the workers
            # (per-key refinement would need a stamp fan-out RPC; the
            # bucket granularity is already O(divergence) on the wire)
            parts = await plane.export_bucket_payloads(
                fanout, leaves, mask, chunk_keys=chunk_keys)
        else:
            from ..store.digest import export_bucket_batch
            node.ensure_flushed()  # acks were awaited: re-sync the host
            batch = None
            if len(buckets):
                batch = await self._refine_keys(writer, token, fanout,
                                                leaves, mask)
            if batch is None:
                batch = export_bucket_batch(node.ks, fanout, leaves,
                                            mask)
            parts = [batch]
        path = os.path.join(app.work_dir,
                            f"delta.out.{meta.addr.replace(':', '_')}")
        loop = asyncio.get_running_loop()
        # file write off-loop (ASYNC-BLOCK): the captures are already
        # materialized, so the worker thread only encodes + writes
        # negotiated peers receive the delta as the compressed snapshot
        # container (the columnar bucket layout with uuid deltas is
        # highly compressible); the receiver's loader sniffs the magic
        container = getattr(self.app, "bulk_compress_level", 6) \
            if self._bulk_compress() else 0
        size = await loop.run_in_executor(
            None, lambda: write_snapshot_file(
                path, nmeta, records, parts, chunk_keys=chunk_keys,
                compress_level=level, container_level=container))
        if node.oplog is not None and node.oplog.policy != "no":
            # emit-only-durable (persist/oplog.py): every op whose
            # effect is in the captured bucket exports was appended
            # before the capture — group-commit AFTER the capture and
            # BEFORE the stream, so a peer can never hold an op a torn
            # tail could still lose (capture-THEN-commit: a commit
            # taken earlier would not cover ops landing during its own
            # fsync, which the state capture then picks up)
            await node.oplog.ack_barrier()
        try:
            await self._stream_file(writer, path, encode_msg(Arr([
                Bulk(DELTASYNC), Int(size), Int(repl_last),
                Int(len(buckets))])))
        finally:
            try:
                os.unlink(path)
            except OSError:
                pass
        st.repl_delta_syncs += 1
        st.repl_delta_bytes += size
        log.info("delta sync %s: %d/%d buckets diverged, %d bytes "
                 "streamed (watermark %d)", meta.addr, len(buckets),
                 fanout * leaves, size, repl_last)
        return repl_last

    # ----------------------------------------------------------------- pull

    async def _pull_loop(self, reader, writer, parser) -> None:
        """Inbound half (reference pull.rs): coalesce replicate frames
        into columnar micro-batches (replica/coalesce.py) and land them
        through the MergeEngine; non-mergeable frames apply per-key as
        barriers; snapshots load chunk-streamed as before.  `writer` is
        the same full-duplex stream's outbound half: digest questions
        from the peer's push loop are ANSWERED here (frames are encoded
        into single atomic writes, so interleaving with our own push
        loop's frames is safe).

        Flush cadence: the applier enforces the frame-count and latency
        bounds; this loop additionally flushes whenever the stream goes
        IDLE (no complete frame left in the parser) before blocking on
        the socket — a lone write lands with zero added latency, and
        batches only form when frames actually queue up."""
        if self.node.serve_plane is not None:
            # shard-per-core node: intake stays here, frames route to
            # the worker owning their key (server/serve_shards.py)
            applier = self.node.serve_plane.make_applier(
                self.meta,
                max_frames=getattr(self.app, "apply_batch", None),
                max_latency=getattr(self.app, "apply_latency", None),
                now=asyncio.get_running_loop().time)
        else:
            from .coalesce import CoalescingApplier
            applier = CoalescingApplier(
                self.node, self.meta,
                max_frames=getattr(self.app, "apply_batch", None),
                max_latency=getattr(self.app, "apply_latency", None),
                now=asyncio.get_running_loop().time)
        # the applier's intake buffer counts toward the governed memory
        # total for the connection's lifetime (server/overload.py)
        gov = self.node.governor
        src = lambda: applier.pending_bytes  # noqa: E731
        gov.register_source(src)
        try:
            await self._pull_frames(reader, writer, parser, applier)
        finally:
            gov.unregister_source(src)

    async def _pull_frames(self, reader, writer, parser, applier) -> None:
        while True:
            msg = parser.next_msg()
            if msg is None:
                if applier.pending:
                    await applier.aflush()  # stream idle: land now
                data = await reader.read(_READ_CHUNK)
                if not data:
                    raise ConnectionError("EOF")
                self._count_in(len(data))
                parser.feed(data)
                continue
            self.meta.last_seen_ms = now_ms()
            items = msg.items if isinstance(msg, Arr) else None
            if not items:
                raise CstError(f"unexpected frame from {self.meta.addr}: {msg!r}")
            kind = as_bytes(items[0]).lower()
            if kind == REPLICATE:
                await applier.aapply(items)
            elif kind == REPLBATCH:
                # a group-encoded run: per-batch intake (dup/gap/cursor/
                # beacon once), decoded batch straight into the merge
                # engine (replica/coalesce.py apply_wire_batch).  Only
                # negotiated streams carry these — a ShardApplier (which
                # never advertises CAP_BATCH_STREAM) raises the protocol
                # error that tears this link down.
                await applier.aabatch(items)
            elif kind == REPLACK:
                uuid = as_int(items[1])
                if uuid > self.meta.uuid_i_acked:
                    self.meta.uuid_i_acked = uuid
                    self.node.events.trigger(EVENT_REPLICA_ACKED, uuid)
                if len(items) > 4:
                    # peer's cluster coverage (see manager.ReplicaMeta).
                    # LAST REPORT WINS, decreases included: coverage can
                    # legitimately REGRESS (a new peer joins the mesh
                    # and its stream is unpulled; a state wipe), and
                    # clamping upward would gate tombstone collection on
                    # a stale too-high value — the unsoundness this
                    # field exists to close.  Accepting a decrease is
                    # merely conservative (GC pauses until coverage
                    # recovers); a reconnect-overlap race delivering an
                    # old ack late lowers it briefly, same story.
                    self.meta.coverage = as_int(items[4])
                if len(items) > 3 and \
                        self._epoch == self.node.reset_epoch:
                    # peer's stream is complete below its beacon.  The
                    # epoch check drops beacons from a stream installed
                    # BEFORE a local state wipe: those would re-advance
                    # the zeroed pull watermark past ops the wipe
                    # discarded, silently skipping their re-delivery.
                    # The applier gates the advance behind any frames
                    # still pending (watermark-after-land).
                    applier.observe_beacon(as_int(items[3]))
            elif kind == FULLSYNC:
                await applier.aflush()  # barrier: snapshot handling
                #                         moves the watermark out-of-band
                await self._receive_snapshot(
                    reader, parser, size=as_int(items[1]),
                    repl_last=as_int(items[2]),
                    reset=bool(as_int(items[3])) if len(items) > 3 else False)
                applier.resync()
            elif kind == DELTASYNC:
                await applier.aflush()  # barrier, like FULLSYNC
                await self._receive_delta(
                    reader, parser, size=as_int(items[1]),
                    repl_last=as_int(items[2]),
                    buckets=as_int(items[3]) if len(items) > 3 else 0)
                applier.resync()
            elif kind == DIGEST:
                if not getattr(self.app, "delta_sync", True):
                    # CONSTDB_DELTA_SYNC=0 kills the responder leg too:
                    # we did not advertise CAP_DELTA_SYNC, so a
                    # conforming peer never asks — but a nonconforming
                    # one must not make us pay the O(keyspace) fold the
                    # operator switched off (it times out into its full-
                    # snapshot fallback)
                    log.warning("digest question from %s ignored: "
                                "CONSTDB_DELTA_SYNC=0", self.meta.addr)
                    continue
                if self._stream_lock.locked():
                    # our own push loop is mid raw-payload window on this
                    # writer: the answer would be dropped anyway (see
                    # _answer_digest's final check, which still guards
                    # the race where the lock is taken during the flush
                    # below) — skip EARLY, before paying the applier
                    # flush and the O(keyspace) digest fold just to
                    # discard the result
                    log.warning("digest question from %s skipped: local "
                                "push loop is mid-stream (peer will "
                                "demote to full sync)", self.meta.addr)
                    continue
                # the peer's push loop is asking where we diverge: the
                # answer must cover every frame already intaken, so land
                # them first (digest-over-pending would flag buckets the
                # pending flush is about to fix)
                await applier.aflush()
                await self._answer_digest(items, writer)
            elif kind == DIGESTACK:
                # reply to OUR push loop's digest question (bridged)
                if self._digest_acks is not None and len(items) >= 4:
                    self._digest_acks.put_nowait(items)
            elif kind == CLUSTERTAB:
                # slot-table gossip (cluster/slots.py): per-slot JOIN —
                # higher (slot_epoch, gid) wins per slot, so a stale or
                # concurrently-minted table merges instead of clobbering
                # (epoch-gated routing is what keeps a flapped owner
                # from resurrecting a stale assignment).  Only
                # cluster-mode peers send these (we advertised
                # CAP_CLUSTER); a disabled node treats one as the
                # protocol error it is, like any unknown frame.
                cl = self.node.cluster
                if cl is None:
                    raise CstError("clustertab frame on a non-cluster "
                                   "node (capability mismatch)")
                if len(items) > 2:
                    from ..cluster.slots import SlotTable
                    table = SlotTable.deserialize(as_bytes(items[2]))
                    if cl.adopt(table):
                        log.info("adopted slot table epoch %d from %s",
                                 table.epoch, self.meta.addr)
            elif kind == PARTSYNC:
                pass  # stream continues from our requested resume point
            else:
                raise CstError(f"unknown repl frame {kind!r}")

    async def _answer_digest(self, items: list, writer) -> None:
        """Answer one of the peer's digest questions (the puller leg of
        the delta anti-entropy protocol): compare the received digests
        against this node's own and reply with the mismatching shard ids
        (level 0) / flat bucket indices (level 1).  The level-0 matrix is
        CACHED for the round so both levels compare one consistent state
        cut — anything landing in between is either ours (the peer does
        not need to send it) or will redeliver through the stream."""
        from ..store.digest import MAX_BUCKETS
        token = as_int(items[1])
        level = as_int(items[2])
        fanout = as_int(items[3])
        leaves = as_int(items[4])
        if fanout < 1 or leaves < 1 or fanout * leaves > MAX_BUCKETS or \
                len(items) < 6:
            raise CstError(f"bad digest geometry from {self.meta.addr}: "
                           f"{fanout}x{leaves}")
        cache_key = (token, fanout, leaves)
        if level in (0, 1):
            cached = self._digest_cache
            if cached is not None and cached[0] == cache_key:
                matrix = cached[1]
            else:
                matrix = await self._local_digest(fanout, leaves)
                self._digest_cache = (cache_key, matrix)
        if level == 0:
            theirs = np.frombuffer(as_bytes(items[5]), dtype="<u8")
            if len(theirs) != fanout:
                raise CstError(f"digest rollup size mismatch from "
                               f"{self.meta.addr}")
            mine = matrix.sum(axis=1, dtype=np.uint64)
            reply = np.nonzero(mine != theirs)[0].astype("<i8").tobytes()
            if not reply:
                # every rollup matched: the peer skips level 1, so this
                # round is over — release the matrix now instead of
                # pinning up to 32MB on the long-lived link until the
                # next negotiation
                self._digest_cache = None
        elif level == 1 and len(items) >= 7:
            shards = np.frombuffer(as_bytes(items[5]), dtype="<i8")
            sub = np.frombuffer(as_bytes(items[6]), dtype="<u8")
            if len(sub) != len(shards) * leaves or \
                    (len(shards) and (int(shards.min()) < 0 or
                                      int(shards.max()) >= fanout)):
                raise CstError(f"digest refinement shape mismatch from "
                               f"{self.meta.addr}")
            shards64 = shards.astype(np.int64)
            mine = matrix[shards64]
            srow, leaf = np.nonzero(mine != sub.reshape(len(shards),
                                                        leaves))
            reply = (shards64[srow] * leaves + leaf).astype("<i8").tobytes()
            self._digest_cache = None  # matrix rounds complete
        elif level == 2 and len(items) >= 7:
            # per-key stamp refinement: which of the peer's listed keys
            # actually differ here (store/digest.py KeyStampTable)
            crcs = np.frombuffer(as_bytes(items[5]),
                                 dtype="<u4").astype(np.uint64)
            stamps = np.frombuffer(as_bytes(items[6]), dtype="<u8")
            if len(crcs) != len(stamps):
                raise CstError(f"key-stamp table shape mismatch from "
                               f"{self.meta.addr}")
            if self.node.serve_plane is not None:
                # sharded puller: per-key stamps would need a worker
                # fan-out — select every offered key instead (exactly
                # the whole-bucket byte cost, still convergent: the
                # re-merge of an equal key is idempotent)
                sel = np.arange(len(crcs), dtype=np.int64)
            else:
                self.node.ensure_flushed()
                from ..store.digest import stamp_mismatch_indices
                sel = stamp_mismatch_indices(self.node.ks, crcs, stamps)
            reply = sel.astype("<i4").tobytes()
        else:
            raise CstError(f"unknown digest level {level} from "
                           f"{self.meta.addr}")
        if self._stream_lock.locked():
            # our own push loop is mid raw-payload window on this
            # writer.  Blocking here could cross-deadlock two symmetric
            # resyncs (each side streaming, each pull loop parked on its
            # lock, nobody reading); drop the answer instead — the
            # peer's negotiation times out and demotes to a full
            # snapshot, the designed-safe fallback.
            log.warning("digest answer to %s dropped: local push loop "
                        "is mid-stream (peer will demote to full sync)",
                        self.meta.addr)
            return
        self._write(writer, encode_msg(Arr([
            Bulk(DIGESTACK), Int(token), Int(level), Bulk(reply)])))
        # no drain() here ON PURPOSE: the pull loop is this connection's
        # only reader, and parking it on flow control while the peer's
        # pull loop is symmetrically parked on ITS ack (two simultaneous
        # resyncs whose level-2 acks both exceed the socket buffers)
        # deadlocks the pair — neither side reads, neither drain ever
        # completes.  The ack is one bounded frame the negotiating peer
        # reads promptly; the transport buffers it in the meantime.

    async def _receive_snapshot(self, reader, parser, size: int,
                                repl_last: int, reset: bool = False) -> None:
        """Download to a spill file, then stream chunks through the
        MergeEngine, yielding between chunks to keep the loop live
        (reference pull.rs:35-85, at columnar scale).

        `reset`: the pusher excluded us from its GC horizon and our resume
        point fell off its repl_log — tombstones we never saw are gone, so
        a plain merge would let our stale keys resurrect mesh-wide.  Wipe
        local state first (Node.reset_for_full_resync) and rejoin from the
        snapshot like a fresh node."""
        self._spill_seq += 1
        path = os.path.join(
            self.app.work_dir,
            f"snapshot.{self.meta.addr.replace(':', '_')}"
            f".{self._spill_seq}")
        try:
            await self._download_spill(reader, parser, size, path)
            node = self.node
            if reset:
                log.warning("peer %s demands a state-clearing resync (we "
                            "were excluded from its GC horizon past the "
                            "repl_log window); wiping local state",
                            self.meta.addr)
                if node.serve_plane is not None:
                    await node.serve_plane.reset_for_resync(keep_link=self)
                else:
                    node.reset_for_full_resync(keep_link=self)
                # THIS stream stays valid: the snapshot below + the
                # gap-free frames that follow it re-establish our pull
                # position
                self._epoch = node.reset_epoch
            applied_rows, replica_rows = await self._apply_spill_loud(
                path, size)
            self._finish_sync(path, applied_rows, replica_rows, repl_last,
                              "snapshot")
        finally:
            # per-download spill names are never overwritten by a retry,
            # so EVERY exit — a torn download included — must drop the
            # file (ENOENT after the success path's unlink is fine)
            try:
                os.unlink(path)
            except OSError:
                pass

    async def _receive_delta(self, reader, parser, size: int,
                             repl_last: int, buckets: int) -> None:
        """Apply a digest-negotiated delta stream: the divergent
        buckets' whole state in snapshot format, merged through the same
        chunk-streamed path a full snapshot takes (merges are
        idempotent/commutative, so bucket-scoped re-merges are plain
        merges).  Watermark + replica-record adoption follow the same
        snapshot-backed discipline (_finish_sync): after the merge our
        state covers everything the pusher had at `repl_last`, because
        every bucket whose digests disagreed was just streamed and every
        bucket whose digests agreed already held identical state."""
        self._spill_seq += 1
        path = os.path.join(
            self.app.work_dir,
            f"delta.in.{self.meta.addr.replace(':', '_')}"
            f".{self._spill_seq}")
        try:
            await self._download_spill(reader, parser, size, path)
            applied_rows, replica_rows = await self._apply_spill_loud(
                path, size)
            self._finish_sync(path, applied_rows, replica_rows, repl_last,
                              f"delta ({buckets} buckets)")
        finally:
            try:  # see _receive_snapshot: every exit drops the spill
                os.unlink(path)
            except OSError:
                pass

    async def _download_spill(self, reader, parser, size: int,
                              path: str) -> None:
        """Download `size` raw stream bytes to a spill file."""
        loop = asyncio.get_running_loop()
        # spill-file open/close off-loop (ASYNC-BLOCK): close flushes the
        # buffered tail to disk, which on a loaded disk blocks for real;
        # the per-piece writes land in the page cache between awaits
        f = await loop.run_in_executor(None, open, path, "wb")
        try:
            remaining = size
            while remaining > 0:
                got = parser.take_raw(min(remaining, _READ_CHUNK))
                if not got:
                    got = await reader.read(min(remaining, _READ_CHUNK))
                    if not got:
                        raise ConnectionError("EOF during sync download")
                    self._count_in(len(got))
                f.write(got)
                remaining -= len(got)
        finally:
            try:
                await loop.run_in_executor(None, f.close)
            except asyncio.CancelledError:
                f.close()  # teardown path: close inline rather than leak
                raise

    async def _apply_spill_loud(self, path: str, size: int):
        """`_apply_spill` with the compression-demotion discipline: a
        raw window that arrived as a compressed container but failed
        validation demotes THIS peer's compression loudly
        (repl_compress_demotions counting + compress_wire_off, so the
        CAP_COMPRESS invitation disappears from the next handshake and
        the retried window arrives plain).  Deliberately NOT counted
        into repl_wire_demotions: the chaos accounting law ties that
        gauge to injected REPLBATCH corruption, and a window can fail
        validation without any peer malice (e.g. a reconnect-overlap
        race interleaving two downloads) — the demotion is then merely
        conservative (speed, never state).  The watermark is untouched
        either way — `_finish_sync` only runs on success, so the whole
        window redelivers idempotently after the teardown."""
        try:
            return await self._apply_spill(path, size)
        except (InvalidSnapshot, InvalidSnapshotChecksum):
            # head sniff off-loop (ASYNC-BLOCK), like every other spill
            # read on this path
            loop = asyncio.get_running_loop()
            head = b""
            try:
                f = await loop.run_in_executor(None, open, path, "rb")
                try:
                    head = await loop.run_in_executor(None, f.read, 8)
                finally:
                    f.close()
            except OSError:
                pass
            from ..utils.compressio import is_compressed
            if is_compressed(head):
                x = self.node.stats.extra
                x["repl_compress_demotions"] = \
                    x.get("repl_compress_demotions", 0) + 1
                self.meta.compress_wire_off = True
                log.error(
                    "compressed sync window from %s failed validation; "
                    "demoting this peer to plain transfers and retrying "
                    "from the untouched watermark", self.meta.addr)
            raise

    async def _apply_spill(self, path: str, size: int):
        """Merge a downloaded snapshot-format spill file through
        whichever apply machinery this node runs — the serve plane
        (workers ARE the store), the process-parallel sharded ingest, or
        the plain chunk-streamed path.  -> (applied_rows, replica_rows)."""
        node = self.node
        if node.serve_plane is not None:
            # shard-per-core node: sections fan out to the serve workers
            # by key hash (server/serve_shards.py) — they ARE the store
            return await self._apply_snapshot_via_plane(path)
        if (shards := self.app.snapshot_ingest_shards(size)) > 1:
            log.info("sharded snapshot ingest from %s: %d bytes over %d "
                     "shard workers", self.meta.addr, size, shards)
            return await self._apply_snapshot_sharded(path, shards)
        return await self._apply_snapshot_plain(path)

    def _finish_sync(self, path: str, applied_rows: int, replica_rows,
                     repl_last: int, what: str) -> None:
        """Post-apply bookkeeping shared by full and delta syncs: the
        stream just re-based us to the pusher's state at `repl_last`."""
        node = self.node
        if replica_rows:
            # transitive mesh join (reference pull.rs:136-153) + watermark
            # adoption, now that the state backing them is fully merged
            node.replicas.merge_records(replica_rows,
                                        my_addr=self.app.advertised_addr,
                                        adopt_watermarks=True)
        if repl_last > self.meta.uuid_he_sent:
            self.meta.uuid_he_sent = repl_last
        node.hlc.observe(repl_last)
        if node.oplog is not None:
            # bulk-delivered state is NOT in the durable op log: stop
            # persisting watermark records (they would claim coverage
            # the log cannot replay) and schedule a rewrite to re-base
            # the log on a snapshot covering it (persist/oplog.py)
            node.oplog.note_bulk_sync()
        log.info("loaded %s from %s: %d rows", what, self.meta.addr,
                 applied_rows)
        try:
            os.unlink(path)
        except OSError:
            pass

    async def _apply_batches(self, batches) -> int:
        """Merge a stream of columnar batches into the node under the
        grouped-apply cadence: accumulate up to `sync_merge_group` chunks
        and merge them in ONE engine call (Node.merge_batches → engine
        merge_many: aligned groups fold in a fused [R, N] device pass;
        unaligned ones still share one state roundtrip per family —
        reference pull.rs:66-74 batches ≤32 entries per apply for the same
        reason).  Adaptive liveness: if a call overruns the budget the
        group shrinks, then chunks SPLIT (batch_chunks re-chunks any
        batch) so a CPU-engine catch-up never wedges the event loop on
        one 64Ki-key merge.  Shared by the plain snapshot apply AND the
        sharded-ingest consolidation.  Returns rows applied."""
        node = self.node
        applied_rows = 0
        group: list = []
        max_group = max(1, self.app.sync_merge_group)
        budget = self.app.sync_merge_budget
        target = 1
        # ramp UP from small sub-chunks so the first call can never wedge
        # the loop, regardless of engine speed: fast calls first grow the
        # split size to whole chunks, then the group size to max_group;
        # slow calls walk the same ladder back down
        split_keys = max(0, self.app.sync_initial_split)
        did_split = False  # did the CURRENT group actually get sub-chunked?
        loop = asyncio.get_running_loop()

        async def apply_group() -> None:
            nonlocal applied_rows, target, split_keys, did_split
            if not group:
                return
            t0 = loop.time()
            node.merge_batches(group)
            dt = loop.time() - t0
            applied_rows += sum(b.n_rows for b in group)
            if dt > budget:
                if target > 1:
                    target = max(1, target // 2)
                elif split_keys == 0:
                    split_keys = 1 << 15
                else:
                    split_keys = max(1024, split_keys // 2)
            elif dt < budget / 4:
                if split_keys and did_split:
                    # splitting is ACTIVE: widen the sub-chunks first
                    split_keys <<= 1
                    if split_keys >= (1 << 17):
                        split_keys = 0  # chunks applied whole from here on
                elif target < max_group:
                    # chunks already apply whole (stream chunks smaller
                    # than the split, or the split ramped out): grow the
                    # GROUP — doubling an inactive split would burn the
                    # whole ramp budget without changing a single call
                    target = min(max_group, target * 2)
            group.clear()
            did_split = False
            await asyncio.sleep(0)

        for payload in batches:
            if split_keys and payload.n_keys > split_keys:
                for sub in batch_chunks(payload, split_keys):
                    # per sub-chunk, not per payload: apply_group resets
                    # the flag at every group boundary, and the LATER
                    # groups of this payload's sub-chunks must still
                    # classify as split-active (else the controller grows
                    # the group while splitting is still happening,
                    # inverting the documented ramp order)
                    did_split = True
                    group.append(sub)
                    if len(group) >= target:
                        await apply_group()
            else:
                group.append(payload)
            if len(group) >= target:
                await apply_group()
        await apply_group()
        return applied_rows

    async def _apply_snapshot_via_plane(self, path: str):
        """Snapshot apply on a shard-per-core serving node: decoded
        sections fan out to the serve workers by key hash
        (ServeShardPlane.ingest_batches awaits per section, so the loop
        stays live), node/replica sections are handled exactly like the
        plain path."""
        plane = self.node.serve_plane
        f = await asyncio.get_running_loop().run_in_executor(
            None, open, path, "rb")
        demux = SectionDemux(f)
        try:
            applied_rows = await plane.ingest_batches(demux.batches())
        finally:
            f.close()
        self._adopt_peer_id(demux)
        return applied_rows, demux.replica_rows

    def _adopt_peer_id(self, demux: SectionDemux) -> None:
        """Backfill the peer's node id from its snapshot meta (a peer
        met by address only identifies itself here)."""
        if demux.meta is not None and demux.meta.node_id \
                and not self.meta.node_id:
            self.meta.node_id = demux.meta.node_id

    async def _apply_snapshot_plain(self, path: str):
        """Single-keyspace snapshot apply (the default path).  Replica
        records are held until the WHOLE snapshot is applied —
        merge_records adopts the recorded pull watermarks, which are
        only backed by state once every chunk has merged (SectionDemux
        defers them until its generator is exhausted)."""
        # spill-file open off-loop (ASYNC-BLOCK); section reads stay
        # inline — they are small page-cache slices between awaits
        f = await asyncio.get_running_loop().run_in_executor(
            None, open, path, "rb")
        demux = SectionDemux(f)
        try:
            applied_rows = await self._apply_batches(demux.batches())
        finally:
            f.close()
        self._adopt_peer_id(demux)
        return applied_rows, demux.replica_rows

    async def _apply_snapshot_sharded(self, path: str, shards: int):
        """Process-parallel snapshot apply (store/sharded_keyspace.py):
        fan RAW batch sections out by key hash to shard worker processes
        — they decode, hash, and merge in parallel while this loop keeps
        serving — then consolidate each shard's merged (deduplicated)
        state into the serving keyspace through the node's own engine,
        re-chunked through the grouped-apply cadence so no single merge
        wedges the event loop."""
        from ..store.sharded_keyspace import ShardedKeySpace
        node = self.node
        loop = asyncio.get_running_loop()
        from ..conf import env_str
        spec = env_str("CONSTDB_SHARD_ENGINE") or \
            ("tpu" if getattr(node.engine, "name", "") == "tpu" else "cpu")
        sks = ShardedKeySpace(n_shards=shards, mode="process",
                              engine_spec=spec,
                              group=max(1, self.app.sync_merge_group))
        x = node.stats.extra
        x["sharded_ingests"] = x.get("sharded_ingests", 0) + 1
        x["sharded_ingest_workers"] = shards
        applied_rows = 0
        replica_rows: list = []
        try:
            # spill-file open off-loop, like every other blocking step of
            # this path (submit/flush/export below)
            f = await loop.run_in_executor(None, open, path, "rb")
            demux = SectionDemux(f, raw_batches=True)
            try:
                for payload in demux.batches():
                    # submit can block on the pool's bounded in-flight
                    # window — run it off-loop so pulls/acks keep
                    # flowing while completions land
                    await loop.run_in_executor(None, sks.submit_raw,
                                               payload)
            finally:
                f.close()
            self._adopt_peer_id(demux)
            replica_rows = demux.replica_rows
            await loop.run_in_executor(None, sks.flush)
            # consolidation rides the SAME adaptive grouped-apply cadence
            # as the plain path — a whole-shard export through a slow
            # engine must not wedge the loop any more than a snapshot
            # chunk may.  Streamed shard by shard with free=True: the
            # worker's copy of a shard is dropped the moment its export
            # lands, so peak residency is the serving keyspace plus ONE
            # shard, not 2x the whole snapshot.
            applied_rows = 0
            for s in range(shards):
                b = await loop.run_in_executor(
                    None, sks.export_shard_batch, s, True)
                if b.n_rows or b.del_keys:
                    applied_rows += await self._apply_batches(iter([b]))
        finally:
            await loop.run_in_executor(None, sks.close)
        return applied_rows, replica_rows


async def _read_msg(reader: asyncio.StreamReader, parser: RespParser,
                    timeout: Optional[float] = None, count=None):
    """Next complete RESP message from the stream; `count` observes raw
    byte arrivals (replication byte accounting)."""
    while True:
        msg = parser.next_msg()
        if msg is not None:
            return msg
        coro = reader.read(_READ_CHUNK)
        data = await (asyncio.wait_for(coro, timeout) if timeout else coro)
        if not data:
            raise ConnectionError("EOF")
        if count is not None:
            count(len(data))
        parser.feed(data)
