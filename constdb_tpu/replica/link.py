"""Per-peer replication link: dial/adopt, sync handshake, pull+push loops.

Capability parity with the reference's `Replica` link + `Puller`/`Pusher`
state machines (reference src/replica/replica.rs:155-359, pull.rs, push.rs),
redesigned for one asyncio loop instead of tokio IO threads + main thread:
the loop IS the single-writer exec thread, so apply/push steps simply run
inline between awaits.

Wire protocol (RESP frames on one TCP stream, symmetric after handshake):
  dialer:   *[sync, 0, node_id, alias, my_addr, resume_uuid, caps]
  acceptor: *[sync, 1, node_id, alias, my_addr, resume_uuid, caps]
  (`caps` is a capability bitmask — CAP_* below; pre-capability peers
  send 6-item frames and parse as caps=0)
  then each side concurrently pushes its own stream and pulls the peer's:
    *[fullsync, size, repl_last_uuid]  + `size` raw snapshot bytes
    *[partsync]
    *[replicate, origin_nodeid, prev_uuid, uuid, cmd, args...]
    *[replack, uuid, now_ms]

Sync decision (reference push.rs:91-111): partial iff the peer's resume
uuid is still gap-free in my repl_log; re-checked every round AND before
every frame, so a pusher that falls off its own ring mid-stream re-sends a
full snapshot on the SAME connection instead of shipping a gapped frame
and paying a teardown + redial (the reference leaves this case as a TODO —
pull.rs:167-172; regression-tested in tests/test_link_pushloop.py).

Connection ownership: one link per peer address.  The link dials when it
has no live connection; an inbound SYNC for the same address *adopts* its
connection into the link, closing any previous one.  Replication is
idempotent (watermark dup-skip), so a brief double-connection race is
harmless.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
from typing import Optional, TYPE_CHECKING

from ..errors import CstError, ReplicateCommandsLost
from ..persist.snapshot import SectionDemux, batch_chunks
from ..resp.codec import RespParser, encode_msg, make_parser
from ..resp.message import Arr, Bulk, Int, as_bytes, as_int
from ..server.events import EVENT_REPLICA_ACKED, EVENT_REPLICATED
from ..utils.hlc import now_ms
from .manager import ReplicaMeta

if TYPE_CHECKING:
    from ..server.io import ServerApp

log = logging.getLogger(__name__)

SYNC = b"sync"
FULLSYNC = b"fullsync"
PARTSYNC = b"partsync"
REPLICATE = b"replicate"
REPLACK = b"replack"

# Handshake capability bits: items[6] of BOTH sync frames (dialer and
# reply).  A pre-capability peer sends 6-item frames and parses as 0 —
# absence is tolerated, never assumed to mean support (ADVICE.md round
# 5: the FULLSYNC reset flag silently downgraded on mixed-version
# meshes, recreating exactly the resurrection scenario it prevents).
CAP_FULLSYNC_RESET = 1   # honors FULLSYNC's 4th (state-wipe) field
MY_CAPS = CAP_FULLSYNC_RESET

_READ_CHUNK = 1 << 16


class ReplicaLink:
    """Drives replication with one peer.  `start()` begins the dial loop;
    `adopt()` installs an inbound connection."""

    def __init__(self, app: "ServerApp", meta: ReplicaMeta):
        self.app = app
        self.node = app.node
        self.meta = meta
        meta.link = self
        self.closing = False
        self._dial_task: Optional[asyncio.Task] = None
        self._serve_task: Optional[asyncio.Task] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        # node.reset_epoch at connection install; a mismatch marks this
        # stream as pre-dating a local state wipe (see _pull_loop REPLACK)
        self._epoch = 0
        # capability bits the peer advertised in the live connection's
        # handshake (0 = pre-capability peer / no connection yet)
        self._peer_caps = 0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        if self._dial_task is None or self._dial_task.done():
            self._dial_task = asyncio.create_task(self._dial_loop())

    async def stop(self) -> None:
        self.closing = True
        for t in (self._dial_task, self._serve_task):
            if t is not None and not t.done():
                t.cancel()
        await self._close_conn()
        self.meta.link = None

    @property
    def connected(self) -> bool:
        return self._serve_task is not None and not self._serve_task.done()

    # ------------------------------------------------------ byte accounting
    # replication traffic counts into the node's net totals plus dedicated
    # repl_* gauges (reference buf_read.rs:218-236 / buf_write.rs:165-183
    # count every socket byte; a node mid-catch-up is busiest exactly here)

    def _count_in(self, n: int) -> None:
        st = self.node.stats
        st.net_in_bytes += n
        st.repl_in_bytes += n

    def _write(self, writer, data: bytes) -> None:
        st = self.node.stats
        st.net_out_bytes += len(data)
        st.repl_out_bytes += len(data)
        writer.write(data)

    async def _close_conn(self) -> None:
        w, self._writer = self._writer, None
        if w is not None:
            try:
                w.close()
                await w.wait_closed()
            except (ConnectionError, OSError):
                pass

    # ----------------------------------------------------------------- dial

    async def _dial_loop(self) -> None:
        """Reconnect-forever with backoff (reference
        replica/replica.rs:254-271, 5s retry)."""
        while not self.closing and self.meta.alive and \
                not self.meta.dial_suspended:
            if not self.connected:
                try:
                    await self._dial_once()
                except (ConnectionError, OSError, CstError,
                        asyncio.TimeoutError) as e:
                    log.debug("dial %s failed: %s", self.meta.addr, e)
            delay = self.app.reconnect_delay
            await asyncio.sleep(delay * (0.8 + 0.4 * random.random()))

    async def _dial_once(self) -> None:
        host, port = self.meta.addr.rsplit(":", 1)
        epoch0 = self.node.reset_epoch  # watermark snapshot validity fence
        reader, writer = await asyncio.open_connection(host, int(port))
        try:
            self._write(writer, encode_msg(Arr([
                Bulk(SYNC), Int(0), Int(self.node.node_id),
                Bulk(self.node.alias.encode()),
                Bulk(self.app.advertised_addr.encode()),
                Int(self.meta.uuid_he_sent), Int(MY_CAPS)])))
            await writer.drain()
            parser = make_parser()
            msg = await _read_msg(reader, parser,
                                  timeout=self.app.handshake_timeout,
                                  count=self._count_in)
            peer_resume = self._check_sync_reply(msg)
            if self.node.reset_epoch != epoch0:
                # a local state wipe landed mid-handshake: the resume
                # watermark we already sent is PRE-wipe, so the peer would
                # stream nothing and its drained beacon would advance our
                # zeroed watermark past ops the wipe discarded.  Abort;
                # the dial loop retries with the post-wipe watermark.
                raise CstError("local state wiped mid-handshake; redialing")
        except BaseException:
            writer.close()
            raise
        self._install(reader, writer, parser, peer_resume)

    def _check_sync_reply(self, msg) -> int:
        from ..resp.message import Err
        if isinstance(msg, Err) and msg.val.startswith(b"FORGOTTEN"):
            # the peer expelled us (FORGET): stop dialing it.  The flag is
            # cleared when someone re-MEETs us and dials in (adopt()).
            self.meta.dial_suspended = True
            log.info("peer %s rejected sync: forgotten; suspending dial",
                     self.meta.addr)
            raise CstError(f"forgotten by {self.meta.addr}")
        items = msg.items if isinstance(msg, Arr) else None
        if not items or as_bytes(items[0]).lower() != SYNC or \
                as_int(items[1]) != 1:
            raise CstError(f"bad sync reply from {self.meta.addr}: {msg!r}")
        self.meta.node_id = as_int(items[2])
        self.meta.alias = as_bytes(items[3]).decode("utf-8", "replace")
        self._peer_caps = as_int(items[6]) if len(items) > 6 else 0
        return as_int(items[5])

    # ---------------------------------------------------------------- adopt

    def adopt(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
              parser: RespParser, peer_resume: int,
              peer_caps: int = 0) -> None:
        """Install an inbound connection (the passive side of SYNC —
        reference replica.rs:16-40 steals the client's Conn).
        `peer_caps`: capability bits from the peer's SYNC frame (0 = a
        pre-capability peer)."""
        self.meta.dial_suspended = False  # the mesh re-admitted us
        self._peer_caps = peer_caps
        self._install(reader, writer, parser, peer_resume)

    def kick(self) -> None:
        """Drop the live connection (if any) so the dial loop — ours or the
        peer's — re-handshakes from the meta's CURRENT watermarks.  Used
        after a local state wipe (Node.reset_for_full_resync): an existing
        stream's positions describe state that no longer exists."""
        t = self._serve_task
        if t is not None and not t.done():
            t.cancel()
        w, self._writer = self._writer, None
        if w is not None:
            w.close()

    def _install(self, reader, writer, parser, peer_resume: int) -> None:
        self.meta.last_seen_ms = now_ms()
        self._epoch = self.node.reset_epoch
        old_task, old_writer = self._serve_task, self._writer
        self._writer = writer
        self._serve_task = asyncio.create_task(
            self._serve(reader, writer, parser, peer_resume))
        if old_task is not None and not old_task.done():
            old_task.cancel()
        if old_writer is not None:
            old_writer.close()

    # ---------------------------------------------------------------- serve

    async def _serve(self, reader, writer, parser, peer_resume: int) -> None:
        push = asyncio.create_task(self._push_loop(writer, peer_resume))
        try:
            await self._pull_loop(reader, parser)
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            log.debug("link %s dropped: %s", self.meta.addr, e)
        except ReplicateCommandsLost as e:
            log.warning("link %s: %s — forcing full resync", self.meta.addr, e)
        except CstError as e:
            log.warning("link %s protocol error: %s", self.meta.addr, e)
        except asyncio.CancelledError:
            raise
        finally:
            push.cancel()
            if self._writer is writer:
                self._writer = None
            writer.close()

    # ----------------------------------------------------------------- push

    async def _push_loop(self, writer, peer_resume: int) -> None:
        """Outbound half (reference push.rs): full-vs-partial, then stream
        repl_log frames; REPLACK heartbeat.

        The send position is a LOCAL cursor, never read back from the
        shared meta.  During a reconnect/adopt overlap two push loops
        briefly coexist on one meta; with a shared cursor the dying loop
        keeps advancing it while writing to a dead socket, the new loop
        then skips those entries as already-sent, and its drained beacon
        advances the peer's pull watermark straight over the hole —
        silently lost ops mesh-wide (found by the round-5 chaos suite).
        A local cursor confines every advance to the connection it was
        actually written to; meta.uuid_i_sent is only mirrored for
        observability while this connection is still the live one."""
        node = self.node
        meta = self.meta
        consumer = node.events.new_consumer(EVENT_REPLICATED)
        try:
            synced = False  # peer_resume not yet honored
            cursor = 0
            last_ack = 0.0
            while True:
                if not synced or not node.repl_log.can_resume_from(cursor):
                    resume = peer_resume if not synced else cursor
                    if node.repl_log.can_resume_from(resume):
                        # partial replay is always the lossless choice when
                        # the log covers the resume point: delete OPS are
                        # still in the ring even after their tombstones
                        # were physically collected (manager.min_uuid)
                        self._write(writer, encode_msg(Arr([Bulk(PARTSYNC)])))
                        cursor = resume
                    else:
                        # a peer excluded from the GC horizon (needs_full)
                        # whose resume point also fell off the ring may hold
                        # keys whose tombstones we already collected — a
                        # plain snapshot merge cannot delete them, so it
                        # must WIPE before merging (fullsync reset flag)
                        reset = meta.needs_full
                        if reset and not (self._peer_caps
                                          & CAP_FULLSYNC_RESET):
                            # a pre-capability peer would silently merge
                            # WITHOUT wiping — the exact resurrection
                            # scenario the reset flag exists to prevent.
                            # Refuse loudly instead of downgrading; the
                            # dial loop retries with backoff until the
                            # peer upgrades (or an operator intervenes).
                            log.error(
                                "push %s: peer needs a state-clearing "
                                "full resync but did not advertise the "
                                "fullsync-reset capability (mixed-"
                                "version mesh?); refusing to downgrade "
                                "to a non-wiping sync", meta.addr)
                            x = node.stats.extra
                            x["fullsync_reset_refused"] = \
                                x.get("fullsync_reset_refused", 0) + 1
                            writer.close()
                            return
                        cursor = await self._send_snapshot(
                            writer, reset=reset)
                    synced = True
                    meta.needs_full = False

                sent = 0
                while (e := node.repl_log.next_after(cursor)) is not None:
                    if e.prev_uuid > cursor:
                        # the ring evicted past our cursor while this loop
                        # yielded (the drain below): streaming `e` would
                        # hand the peer a gap, blow up its pull loop
                        # (ReplicateCommandsLost) and force a teardown +
                        # redial + snapshot over a FRESH connection.
                        # Recover IN PLACE instead: stop here and let the
                        # round decision re-send a full snapshot on this
                        # same stream (eviction past the cursor implies
                        # can_resume_from(cursor) is False).  This is the
                        # fallback the module header documents — the
                        # reference leaves the case unhandled
                        # (pull.rs:167-172).
                        log.warning(
                            "push %s: repl_log evicted past send cursor "
                            "mid-stream; resyncing in place", meta.addr)
                        break
                    self._write(writer, encode_msg(Arr([
                        Bulk(REPLICATE), Int(node.node_id), Int(e.prev_uuid),
                        Int(e.uuid), Bulk(e.name), *e.args])))
                    cursor = e.uuid
                    sent += 1
                    if sent % 64 == 0:
                        await writer.drain()  # backpressure + yield
                if self._writer is writer:
                    meta.uuid_i_sent = cursor  # observability (INFO)
                if not node.repl_log.can_resume_from(cursor):
                    # fell off the ring mid-round: resync NOW (top of the
                    # loop) instead of sleeping out a heartbeat first
                    await writer.drain()
                    continue

                now = asyncio.get_running_loop().time()
                if (meta.uuid_he_sent > meta.uuid_he_acked
                        or now - last_ack >= self.app.heartbeat):
                    # beacon: with the log fully drained, every uuid this
                    # node will EVER stream from now on exceeds its current
                    # HLC — peers may advance their pull watermark to it, so
                    # idle nodes don't pin the cluster GC horizon at 0
                    drained = cursor >= node.repl_log.last_uuid
                    beacon = node.hlc.current if drained else 0
                    self._write(writer, encode_msg(Arr([
                        Bulk(REPLACK), Int(meta.uuid_he_sent), Int(now_ms()),
                        Int(beacon)])))
                    meta.uuid_he_acked = meta.uuid_he_sent
                    last_ack = now
                await writer.drain()
                await consumer.wait(timeout=self.app.heartbeat)
        except (ConnectionError, OSError) as e:
            log.debug("push %s dropped: %s", self.meta.addr, e)
        finally:
            consumer.close()

    async def _send_snapshot(self, writer, reset: bool = False) -> int:
        """Fork-free full sync with bounded memory: acquire the node's
        SHARED on-disk dump (produced once, reused by every concurrently
        or subsequently syncing peer while the repl_log still covers its
        watermark — reference server.rs:221-250 reuse + push.rs:34-71
        send_file, minus the fork) and stream the file to the socket in
        fixed-size pieces.  Returns the dump's repl watermark — the push
        loop's new send cursor (the repl_log gap above it streams next,
        which `can_resume_from` guarantees is still present)."""
        dump = await self.app.shared_dump.acquire()
        self.node.stats.extra["full_syncs_sent"] = \
            self.node.stats.extra.get("full_syncs_sent", 0) + 1
        # open + reads off-loop: a full-resync burst on a loaded disk
        # must not hiccup every client (ASYNC-BLOCK; the writes are
        # socket-buffered and drain() yields between pieces).  The FIRST
        # piece is read BEFORE the FULLSYNC header goes out so the
        # stream never shows a header with zero payload bytes behind it
        # — the pre-executor code had no such window (header + first
        # read happened in one task step) and the wire contract keeps it
        loop = asyncio.get_running_loop()
        f = await loop.run_in_executor(None, open, dump.path, "rb")
        try:
            piece = await loop.run_in_executor(None, f.read, _READ_CHUNK)
            self._write(writer, encode_msg(Arr([
                Bulk(FULLSYNC), Int(dump.size), Int(dump.repl_last),
                Int(1 if reset else 0)])))
            while piece:
                self._write(writer, piece)
                await writer.drain()
                piece = await loop.run_in_executor(None, f.read, _READ_CHUNK)
        finally:
            f.close()
        return dump.repl_last

    # ----------------------------------------------------------------- pull

    async def _pull_loop(self, reader, parser) -> None:
        """Inbound half (reference pull.rs): coalesce replicate frames
        into columnar micro-batches (replica/coalesce.py) and land them
        through the MergeEngine; non-mergeable frames apply per-key as
        barriers; snapshots load chunk-streamed as before.

        Flush cadence: the applier enforces the frame-count and latency
        bounds; this loop additionally flushes whenever the stream goes
        IDLE (no complete frame left in the parser) before blocking on
        the socket — a lone write lands with zero added latency, and
        batches only form when frames actually queue up."""
        if self.node.serve_plane is not None:
            # shard-per-core node: intake stays here, frames route to
            # the worker owning their key (server/serve_shards.py)
            applier = self.node.serve_plane.make_applier(
                self.meta,
                max_frames=getattr(self.app, "apply_batch", None),
                max_latency=getattr(self.app, "apply_latency", None),
                now=asyncio.get_running_loop().time)
        else:
            from .coalesce import CoalescingApplier
            applier = CoalescingApplier(
                self.node, self.meta,
                max_frames=getattr(self.app, "apply_batch", None),
                max_latency=getattr(self.app, "apply_latency", None),
                now=asyncio.get_running_loop().time)
        while True:
            msg = parser.next_msg()
            if msg is None:
                if applier.pending:
                    await applier.aflush()  # stream idle: land now
                data = await reader.read(_READ_CHUNK)
                if not data:
                    raise ConnectionError("EOF")
                self._count_in(len(data))
                parser.feed(data)
                continue
            self.meta.last_seen_ms = now_ms()
            items = msg.items if isinstance(msg, Arr) else None
            if not items:
                raise CstError(f"unexpected frame from {self.meta.addr}: {msg!r}")
            kind = as_bytes(items[0]).lower()
            if kind == REPLICATE:
                await applier.aapply(items)
            elif kind == REPLACK:
                uuid = as_int(items[1])
                if uuid > self.meta.uuid_i_acked:
                    self.meta.uuid_i_acked = uuid
                    self.node.events.trigger(EVENT_REPLICA_ACKED, uuid)
                if len(items) > 3 and \
                        self._epoch == self.node.reset_epoch:
                    # peer's stream is complete below its beacon.  The
                    # epoch check drops beacons from a stream installed
                    # BEFORE a local state wipe: those would re-advance
                    # the zeroed pull watermark past ops the wipe
                    # discarded, silently skipping their re-delivery.
                    # The applier gates the advance behind any frames
                    # still pending (watermark-after-land).
                    applier.observe_beacon(as_int(items[3]))
            elif kind == FULLSYNC:
                await applier.aflush()  # barrier: snapshot handling
                #                         moves the watermark out-of-band
                await self._receive_snapshot(
                    reader, parser, size=as_int(items[1]),
                    repl_last=as_int(items[2]),
                    reset=bool(as_int(items[3])) if len(items) > 3 else False)
                applier.resync()
            elif kind == PARTSYNC:
                pass  # stream continues from our requested resume point
            else:
                raise CstError(f"unknown repl frame {kind!r}")

    async def _receive_snapshot(self, reader, parser, size: int,
                                repl_last: int, reset: bool = False) -> None:
        """Download to a spill file, then stream chunks through the
        MergeEngine, yielding between chunks to keep the loop live
        (reference pull.rs:35-85, at columnar scale).

        `reset`: the pusher excluded us from its GC horizon and our resume
        point fell off its repl_log — tombstones we never saw are gone, so
        a plain merge would let our stale keys resurrect mesh-wide.  Wipe
        local state first (Node.reset_for_full_resync) and rejoin from the
        snapshot like a fresh node."""
        path = os.path.join(self.app.work_dir,
                            f"snapshot.{self.meta.addr.replace(':', '_')}")
        loop = asyncio.get_running_loop()
        # spill-file open/close off-loop (ASYNC-BLOCK): close flushes the
        # buffered tail to disk, which on a loaded disk blocks for real;
        # the per-piece writes land in the page cache between awaits
        f = await loop.run_in_executor(None, open, path, "wb")
        try:
            remaining = size
            while remaining > 0:
                got = parser.take_raw(min(remaining, _READ_CHUNK))
                if not got:
                    got = await reader.read(min(remaining, _READ_CHUNK))
                    if not got:
                        raise ConnectionError("EOF during snapshot download")
                    self._count_in(len(got))
                f.write(got)
                remaining -= len(got)
        finally:
            try:
                await loop.run_in_executor(None, f.close)
            except asyncio.CancelledError:
                f.close()  # teardown path: close inline rather than leak
                raise
        node = self.node
        if reset:
            log.warning("peer %s demands a state-clearing resync (we were "
                        "excluded from its GC horizon past the repl_log "
                        "window); wiping local state", self.meta.addr)
            if node.serve_plane is not None:
                await node.serve_plane.reset_for_resync(keep_link=self)
            else:
                node.reset_for_full_resync(keep_link=self)
            # THIS stream stays valid: the snapshot below + the gap-free
            # frames that follow it re-establish our pull position
            self._epoch = node.reset_epoch
        if node.serve_plane is not None:
            # shard-per-core node: sections fan out to the serve workers
            # by key hash (server/serve_shards.py) — they ARE the store
            applied_rows, replica_rows = \
                await self._apply_snapshot_via_plane(path)
        elif (shards := self.app.snapshot_ingest_shards(size)) > 1:
            log.info("sharded snapshot ingest from %s: %d bytes over %d "
                     "shard workers", self.meta.addr, size, shards)
            applied_rows, replica_rows = \
                await self._apply_snapshot_sharded(path, shards)
        else:
            applied_rows, replica_rows = \
                await self._apply_snapshot_plain(path)
        if replica_rows:
            # transitive mesh join (reference pull.rs:136-153) + watermark
            # adoption, now that the state backing them is fully merged
            node.replicas.merge_records(replica_rows,
                                        my_addr=self.app.advertised_addr,
                                        adopt_watermarks=True)
        if repl_last > self.meta.uuid_he_sent:
            self.meta.uuid_he_sent = repl_last
        node.hlc.observe(repl_last)
        log.info("loaded snapshot from %s: %d rows", self.meta.addr,
                 applied_rows)
        try:
            os.unlink(path)
        except OSError:
            pass

    async def _apply_batches(self, batches) -> int:
        """Merge a stream of columnar batches into the node under the
        grouped-apply cadence: accumulate up to `sync_merge_group` chunks
        and merge them in ONE engine call (Node.merge_batches → engine
        merge_many: aligned groups fold in a fused [R, N] device pass;
        unaligned ones still share one state roundtrip per family —
        reference pull.rs:66-74 batches ≤32 entries per apply for the same
        reason).  Adaptive liveness: if a call overruns the budget the
        group shrinks, then chunks SPLIT (batch_chunks re-chunks any
        batch) so a CPU-engine catch-up never wedges the event loop on
        one 64Ki-key merge.  Shared by the plain snapshot apply AND the
        sharded-ingest consolidation.  Returns rows applied."""
        node = self.node
        applied_rows = 0
        group: list = []
        max_group = max(1, self.app.sync_merge_group)
        budget = self.app.sync_merge_budget
        target = 1
        # ramp UP from small sub-chunks so the first call can never wedge
        # the loop, regardless of engine speed: fast calls first grow the
        # split size to whole chunks, then the group size to max_group;
        # slow calls walk the same ladder back down
        split_keys = max(0, self.app.sync_initial_split)
        did_split = False  # did the CURRENT group actually get sub-chunked?
        loop = asyncio.get_running_loop()

        async def apply_group() -> None:
            nonlocal applied_rows, target, split_keys, did_split
            if not group:
                return
            t0 = loop.time()
            node.merge_batches(group)
            dt = loop.time() - t0
            applied_rows += sum(b.n_rows for b in group)
            if dt > budget:
                if target > 1:
                    target = max(1, target // 2)
                elif split_keys == 0:
                    split_keys = 1 << 15
                else:
                    split_keys = max(1024, split_keys // 2)
            elif dt < budget / 4:
                if split_keys and did_split:
                    # splitting is ACTIVE: widen the sub-chunks first
                    split_keys <<= 1
                    if split_keys >= (1 << 17):
                        split_keys = 0  # chunks applied whole from here on
                elif target < max_group:
                    # chunks already apply whole (stream chunks smaller
                    # than the split, or the split ramped out): grow the
                    # GROUP — doubling an inactive split would burn the
                    # whole ramp budget without changing a single call
                    target = min(max_group, target * 2)
            group.clear()
            did_split = False
            await asyncio.sleep(0)

        for payload in batches:
            if split_keys and payload.n_keys > split_keys:
                for sub in batch_chunks(payload, split_keys):
                    # per sub-chunk, not per payload: apply_group resets
                    # the flag at every group boundary, and the LATER
                    # groups of this payload's sub-chunks must still
                    # classify as split-active (else the controller grows
                    # the group while splitting is still happening,
                    # inverting the documented ramp order)
                    did_split = True
                    group.append(sub)
                    if len(group) >= target:
                        await apply_group()
            else:
                group.append(payload)
            if len(group) >= target:
                await apply_group()
        await apply_group()
        return applied_rows

    async def _apply_snapshot_via_plane(self, path: str):
        """Snapshot apply on a shard-per-core serving node: decoded
        sections fan out to the serve workers by key hash
        (ServeShardPlane.ingest_batches awaits per section, so the loop
        stays live), node/replica sections are handled exactly like the
        plain path."""
        plane = self.node.serve_plane
        f = await asyncio.get_running_loop().run_in_executor(
            None, open, path, "rb")
        demux = SectionDemux(f)
        try:
            applied_rows = await plane.ingest_batches(demux.batches())
        finally:
            f.close()
        self._adopt_peer_id(demux)
        return applied_rows, demux.replica_rows

    def _adopt_peer_id(self, demux: SectionDemux) -> None:
        """Backfill the peer's node id from its snapshot meta (a peer
        met by address only identifies itself here)."""
        if demux.meta is not None and demux.meta.node_id \
                and not self.meta.node_id:
            self.meta.node_id = demux.meta.node_id

    async def _apply_snapshot_plain(self, path: str):
        """Single-keyspace snapshot apply (the default path).  Replica
        records are held until the WHOLE snapshot is applied —
        merge_records adopts the recorded pull watermarks, which are
        only backed by state once every chunk has merged (SectionDemux
        defers them until its generator is exhausted)."""
        # spill-file open off-loop (ASYNC-BLOCK); section reads stay
        # inline — they are small page-cache slices between awaits
        f = await asyncio.get_running_loop().run_in_executor(
            None, open, path, "rb")
        demux = SectionDemux(f)
        try:
            applied_rows = await self._apply_batches(demux.batches())
        finally:
            f.close()
        self._adopt_peer_id(demux)
        return applied_rows, demux.replica_rows

    async def _apply_snapshot_sharded(self, path: str, shards: int):
        """Process-parallel snapshot apply (store/sharded_keyspace.py):
        fan RAW batch sections out by key hash to shard worker processes
        — they decode, hash, and merge in parallel while this loop keeps
        serving — then consolidate each shard's merged (deduplicated)
        state into the serving keyspace through the node's own engine,
        re-chunked through the grouped-apply cadence so no single merge
        wedges the event loop."""
        from ..store.sharded_keyspace import ShardedKeySpace
        node = self.node
        loop = asyncio.get_running_loop()
        from ..conf import env_str
        spec = env_str("CONSTDB_SHARD_ENGINE") or \
            ("tpu" if getattr(node.engine, "name", "") == "tpu" else "cpu")
        sks = ShardedKeySpace(n_shards=shards, mode="process",
                              engine_spec=spec,
                              group=max(1, self.app.sync_merge_group))
        x = node.stats.extra
        x["sharded_ingests"] = x.get("sharded_ingests", 0) + 1
        x["sharded_ingest_workers"] = shards
        applied_rows = 0
        replica_rows: list = []
        try:
            # spill-file open off-loop, like every other blocking step of
            # this path (submit/flush/export below)
            f = await loop.run_in_executor(None, open, path, "rb")
            demux = SectionDemux(f, raw_batches=True)
            try:
                for payload in demux.batches():
                    # submit can block on the pool's bounded in-flight
                    # window — run it off-loop so pulls/acks keep
                    # flowing while completions land
                    await loop.run_in_executor(None, sks.submit_raw,
                                               payload)
            finally:
                f.close()
            self._adopt_peer_id(demux)
            replica_rows = demux.replica_rows
            await loop.run_in_executor(None, sks.flush)
            # consolidation rides the SAME adaptive grouped-apply cadence
            # as the plain path — a whole-shard export through a slow
            # engine must not wedge the loop any more than a snapshot
            # chunk may.  Streamed shard by shard with free=True: the
            # worker's copy of a shard is dropped the moment its export
            # lands, so peak residency is the serving keyspace plus ONE
            # shard, not 2x the whole snapshot.
            applied_rows = 0
            for s in range(shards):
                b = await loop.run_in_executor(
                    None, sks.export_shard_batch, s, True)
                if b.n_rows or b.del_keys:
                    applied_rows += await self._apply_batches(iter([b]))
        finally:
            await loop.run_in_executor(None, sks.close)
        return applied_rows, replica_rows


async def _read_msg(reader: asyncio.StreamReader, parser: RespParser,
                    timeout: Optional[float] = None, count=None):
    """Next complete RESP message from the stream; `count` observes raw
    byte arrivals (replication byte accounting)."""
    while True:
        msg = parser.next_msg()
        if msg is not None:
            return msg
        coro = reader.read(_READ_CHUNK)
        data = await (asyncio.wait_for(coro, timeout) if timeout else coro)
        if not data:
            raise ConnectionError("EOF")
        if count is not None:
            count(len(data))
        parser.feed(data)
