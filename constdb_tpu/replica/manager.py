"""Replica membership + progress watermarks.

Capability parity with the reference's `ReplicaManager`
(reference src/replica/replica.rs:16-128): membership is itself a CRDT —
an add/del LWW map keyed by peer address — so MEET/FORGET replicate and
merge like any other write, and snapshot REPLICAS sections from different
peers converge.  Each row also carries the four progress watermarks that
drive partial-resync decisions and the GC horizon.

Watermarks (reference ReplicaMeta, replica/replica.rs:131-147):
  uuid_i_sent  — newest entry of MY repl_log I have pushed to this peer
  uuid_i_acked — newest of MY uuids this peer has REPLACKed
  uuid_he_sent — newest of HIS uuids I have applied (my pull progress;
                 doubles as the resume point I request on reconnect)
  uuid_he_acked — newest of his uuids I last REPLACKed back to him

GC horizon: the reference uses min(uuid_he_sent) (replica/replica.rs:87-89),
which only proves peer CLOCKS advanced.  We take
min(uuid_i_acked, uuid_he_sent) per live peer: uuid_i_acked proves the peer
actually holds my stream — including my tombstones — past the horizon, so
physically dropping those tombstones is safe; uuid_he_sent keeps the bound
conservative for tombstones I merged from third parties.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

from ..persist.snapshot import ReplicaRecord


@dataclass
class ReplicaMeta:
    addr: str
    node_id: int = 0
    alias: str = ""
    add_t: int = 0
    del_t: int = 0
    uuid_i_sent: int = 0
    uuid_i_acked: int = 0
    uuid_he_sent: int = 0
    uuid_he_acked: int = 0
    # runtime attachment (not replicated): the live link driving this peer
    link: object = field(default=None, repr=False, compare=False)
    # runtime flag (not replicated): set when this peer rejected our SYNC
    # as "forgotten" — we are the expelled node; stop dialing until an
    # inbound connection (someone re-MET us) clears it.  Kept out of the
    # add_t/del_t LWW so it never corrupts replicated membership.
    dial_suspended: bool = field(default=False, compare=False)
    # runtime liveness (not replicated): wall-ms of the last frame received
    # from this peer; 0 = never.  Drives the GC-horizon retention rule.
    last_seen_ms: int = field(default=0, compare=False)
    # flag (not replicated): this peer was excluded from the GC horizon
    # at least once, so tombstones it never saw may have been physically
    # collected.  While the repl_log still covers its resume point,
    # partial replay redelivers the delete OPS losslessly; past that, the
    # pusher forces a STATE-CLEARING full resync (link.py sends the
    # fullsync reset flag, the peer wipes keyspace + repl_log before the
    # merge) so the peer's stale keys cannot resurrect mesh-wide.
    needs_full: bool = field(default=False, compare=False)
    # runtime flag (not replicated): this peer once sent us a REPLBATCH
    # payload we could not decode (replica/coalesce.py apply_wire_batch)
    # — stop advertising CAP_BATCH_STREAM to it, so every re-handshake
    # delivers the redelivery window (and everything after) as ordinary
    # per-frame REPLICATE frames.  Sticky for the process lifetime: a
    # peer that ships one malformed batch will ship another.
    batch_wire_off: bool = field(default=False, compare=False)
    # runtime flag (not replicated): this peer once sent us a compressed
    # frame (REPLBATCH payload or bulk window) we could not validate
    # (utils/compressio.py) — stop advertising CAP_COMPRESS to it, so
    # the redelivery window (and everything after) arrives as the plain
    # byte stream.  Same loud-demotion discipline as batch_wire_off;
    # sticky for the process lifetime.
    compress_wire_off: bool = field(default=False, compare=False)
    # runtime (not replicated): the peer's self-reported CLUSTER
    # COVERAGE — a uuid L such that the peer holds EVERY origin's ops
    # <= L (REPLACK item 5; -1 = legacy peer, never reported).  Gates
    # the GC horizon for THIRD-PARTY tombstones: uuid_i_acked only
    # proves the peer holds MY stream past the horizon, which says
    # nothing about a tombstone another origin minted — collecting on
    # acks alone lets a peer that is partitioned from that origin adopt
    # my watermarks from a later state transfer and silently skip the
    # delete's op replay forever (found by the chaos harness: the
    # removed member resurrected on exactly one node, mesh-wide
    # watermarks all caught up).
    coverage: int = field(default=-1, compare=False)

    @property
    def alive(self) -> bool:
        return self.add_t >= self.del_t

    def record(self) -> ReplicaRecord:
        return ReplicaRecord(self.addr, self.node_id, self.alias, self.add_t,
                             self.del_t, self.uuid_he_sent, self.uuid_he_acked)


class ReplicaManager:
    def __init__(self) -> None:
        self.peers: dict[str, ReplicaMeta] = {}
        # hook: called with (addr, meta) when a NEW live peer appears through
        # a merge (transitive mesh join — reference pull.rs:136-153)
        self.on_new_peer: Optional[Callable[[ReplicaMeta], None]] = None
        # a peer silent beyond this stops pinning min_uuid (0 = never —
        # the default and the reference's behavior, where one dead peer
        # pins GC forever, replica/replica.rs:87-89).  Opt-in via config;
        # ServerApp wires the value.  An excluded peer is forced through
        # a state-clearing full resync on return (link.py reset flag).
        self.gc_peer_retention_ms: int = 0

    # ------------------------------------------------------------ membership

    def get(self, addr: str) -> Optional[ReplicaMeta]:
        return self.peers.get(addr)

    def add(self, addr: str, uuid: int, node_id: int = 0,
            alias: str = "") -> ReplicaMeta:
        """MEET: (re-)register a peer at time `uuid` (add-side LWW)."""
        m = self.peers.get(addr)
        if m is None:
            from ..utils.hlc import now_ms
            # the retention clock starts at registration: a peer we never
            # hear from gets exactly one retention window before it stops
            # pinning the GC horizon (a 0 stamp would exempt restored-dead
            # peers forever)
            m = ReplicaMeta(addr, node_id=node_id, alias=alias, add_t=uuid,
                            last_seen_ms=now_ms())
            self.peers[addr] = m
        else:
            if uuid > m.add_t:
                m.add_t = uuid
            if node_id:
                m.node_id = node_id
            if alias:
                m.alias = alias
        if m.alive:
            m.dial_suspended = False  # explicit (re-)MEET re-admits
        return m

    def forget(self, addr: str, uuid: int) -> bool:
        """FORGET: tombstone a peer (del-side LWW).  Registered as a real
        command, unlike the reference (replica.rs:77-86 defines `forget` but
        never registers it — SURVEY.md §"Known reference defects")."""
        m = self.peers.get(addr)
        if m is None:
            m = ReplicaMeta(addr)
            self.peers[addr] = m
        if uuid > m.del_t:
            m.del_t = uuid
            return True
        return False

    def live_peers(self) -> list[ReplicaMeta]:
        return [m for m in self.peers.values() if m.alive]

    def merge_records(self, rows: Iterable[ReplicaRecord],
                      my_addr: str = "",
                      adopt_watermarks: bool = False) -> list[ReplicaMeta]:
        """Merge a REPLICAS snapshot section (LWW per addr); returns peers
        that became live-and-new (candidates for transitive MEET).

        `adopt_watermarks=True` additionally max-merges each record's
        PULL WATERMARK (uuid_he_sent).  That is ONLY lossless when the
        caller merges the snapshot's full keyspace state in the same
        operation — ops below the recorded watermark are then already
        reflected locally, so resuming from it skips nothing.  The two
        snapshot-backed call sites (replica/link.py full-sync apply,
        server/io.py boot restore) pass True; a bare membership merge
        (e.g. a future gossip-style exchange) MUST NOT — adopting
        watermarks without the backing state silently skips op
        re-delivery (ADVICE.md round 5: the coupling was previously
        enforced by comment only).  For the snapshot-backed sites,
        adopting is itself a convergence requirement, not merely a
        saving: a cold-restarted node dialing with resume 0 makes peers
        replay their whole ring — re-delivering ADDS whose tombstones
        the mesh already GC-collected, resurrecting deleted members with
        no surviving delete op to kill them again (round-5 chaos
        suite)."""
        fresh = []
        for r in rows:
            if r.addr == my_addr:
                continue
            m = self.peers.get(addr := r.addr)
            if m is None:
                from ..utils.hlc import now_ms
                m = ReplicaMeta(addr, last_seen_ms=now_ms())
                self.peers[addr] = m
                is_new = True
            else:
                is_new = not m.alive
            if r.add_t > m.add_t:
                m.add_t = r.add_t
            if r.del_t > m.del_t:
                m.del_t = r.del_t
            if r.node_id:
                m.node_id = r.node_id
            if r.alias and not m.alias:
                m.alias = r.alias
            if adopt_watermarks and r.uuid_he_sent > m.uuid_he_sent:
                m.uuid_he_sent = r.uuid_he_sent
            if is_new and m.alive:
                fresh.append(m)
        for m in fresh:
            if self.on_new_peer is not None:
                self.on_new_peer(m)
        return fresh

    def records(self) -> list[ReplicaRecord]:
        """Membership dump for the snapshot REPLICAS section."""
        return [m.record() for m in self.peers.values()]

    # -------------------------------------------------------------- horizon

    def min_uuid(self) -> Optional[int]:
        """GC tombstone horizon (see module docstring); None when no live
        peers (standalone nodes collect up to their own clock).

        Retention rule: a live peer SILENT for longer than
        `gc_peer_retention_ms` stops pinning the horizon — otherwise one
        crashed peer freezes tombstone collection mesh-wide forever.  The
        tradeoff is bounded: a returning excluded peer is lossless while
        the repl_log still covers its resume point (delete OPS replay even
        after their tombstones were physically collected); only past BOTH
        windows can its stale keys resurrect (see ReplicaMeta.needs_full)."""
        from ..utils.hlc import now_ms
        live = self.live_peers()
        if not live:
            return None
        retention = self.gc_peer_retention_ms
        now = now_ms()
        pinning = []
        for m in live:
            if retention and now - m.last_seen_ms > retention:
                m.needs_full = True
                continue
            pinning.append(m)
        if not pinning:
            return None
        horizon = None
        for m in pinning:
            pin = min(m.uuid_i_acked, m.uuid_he_sent)
            if m.coverage >= 0:
                # coverage-aware horizon: a third-party tombstone is
                # collectable only once this peer holds EVERY origin's
                # stream past it — the property that makes snapshot/
                # delta watermark ADOPTION sound (see ReplicaMeta.
                # coverage).  Legacy peers (-1) keep the ack-only bound.
                pin = min(pin, m.coverage)
            horizon = pin if horizon is None else min(horizon, pin)
        return horizon

    def cluster_coverage(self) -> int:
        """The uuid L this node may advertise as held across EVERY
        origin stream: min over live peers of the applied pull watermark
        (uuid_he_sent); our own stream is trivially held.  Advertised in
        every REPLACK (replica/link.py) so peers' GC horizons can gate
        third-party tombstone collection on it."""
        live = self.live_peers()
        if not live:
            return 0
        return min(m.uuid_he_sent for m in live)

    # ------------------------------------------------------------- REPLICAS

    def describe(self) -> list[tuple[str, ReplicaMeta]]:
        """Rows for the REPLICAS command (reference
        replica/replica.rs:63-85)."""
        return sorted(self.peers.items())
