"""Coalescing replication applier: batch the steady-state peer stream.

The pull loop used to apply every REPLICATE frame one key at a time on
the event loop (`node.apply_replicated`) — the last large per-key Python
loop on a hot path, and *the* hot path for serving traffic.  Under this
build's op model every steady-state write command is a pure pointwise
CRDT merge (crdt/semantics.py: op application IS the merge function), so
frames from a peer stream may be legally coalesced and applied as ONE
columnar batch through the same fused engine path snapshot ingest rides
(`node.merge_batches` → engine `merge_many`).

Shape of the machinery:

  * intake (`CoalescingApplier.apply`) does only the per-frame minimum —
    dup-skip / gap check / cursor — and buffers `(key, origin, uuid,
    frame)` records grouped by command name.  All decoding happens at
    flush, where the per-command GROUP encoders (server/commands.py
    `COLUMNAR_ENCODERS`) turn each run into columnar rows with C-speed
    list comprehensions, in the exact plane layout the snapshot writer
    serializes (engine/base.py ColumnarBatch).
  * flushes happen under a dual bound — max frames per batch
    (`CONSTDB_APPLY_BATCH`) and max latency (`CONSTDB_APPLY_LATENCY_MS`)
    — and additionally whenever the socket goes idle (no complete frame
    buffered), so a lone write lands with ZERO added latency; the bounds
    only engage under sustained traffic, where batching is the point.
  * non-encodable frames apply on the exact per-key path as BARRIERS.
    Membership ops never touch the keyspace and the key-scoped sweeps
    (collection deletes, expireat, mvwrite) read live rows of exactly
    their first-argument key, so they force a flush only when that key
    has pending rows; anything else non-encodable flushes
    unconditionally.  `CONSTDB_APPLY_BATCH=1` turns every frame into a
    barrier — the exact pre-coalescing path.

Watermark discipline (docs/INVARIANTS.md): `meta.uuid_he_sent` — the
resume point requested on reconnect AND the value the push loop REPLACKs
back — advances ONLY after the covering batch has landed in the store.
A connection that dies with frames still pending simply re-receives them
after reconnect (replication is idempotent); a REPLACK beacon that
arrives while frames are pending is stashed and applied post-flush for
the same reason.  The applier keeps a separate stream CURSOR (dup-skip /
gap detection) that advances at intake — stream continuity is a
transport property, durability is not.

Exactness notes (why coalesced == per-frame, byte for byte):
  * element/register/counter writes: op application == state merge by
    design (semantics.py header), and merges are associative +
    commutative, so folding N frames and merging once equals applying
    them in order.
  * envelope times: the op path's `updated_at` is max(ct, uuid) /
    max(mt, uuid); the engine's envelope merge is the same max.  The one
    conditional case (a LOSING register write skips updated_at) is
    covered by the store invariant ct >= rv_t, which makes the
    unconditional max a no-op exactly then.
  * the element-plane key-delete rule (`sadd`/`hset`/`lins` tombstone
    their members at the key's dt when uuid < dt) reads LIVE store
    state, so it is evaluated at flush time against the then-current dt
    (KeySpace.key_delete_times) — the same values the per-key path
    would have seen, because anything that can raise dt mid-batch
    either flushes first (peer collection deletes on pending keys) or
    interleaves identically (local deletes run on the same loop, and
    scalar peer deletes ride the batch itself).

Deliberate deviations from the per-frame path, both narrow:
  * a cross-stream TYPE CONFLICT (same key, different encodings from
    different origins) is handled with snapshot-merge semantics — log
    and skip the key (engine key resolver) — instead of tearing the
    link down; a poisoned key can no longer wedge replication forever.
  * frames between the landed watermark and the stream cursor are
    redelivered after a reconnect and re-applied.  For every coalesced
    write that is an idempotent merge; for the key-scoped barrier
    sweeps it can re-run an observed-remove against newer state, an
    anomaly class concurrent delivery already exhibits on the per-frame
    path (the sweep reads local state wherever it runs).
"""

from __future__ import annotations

import logging
import time
from itertools import chain
from typing import Callable, Optional

import numpy as np

from ..engine.base import ColumnarBatch
from ..errors import CstError, ReplicateCommandsLost
from ..resp.message import as_bytes, as_int
from ..server.commands import (COLUMNAR_ENCODERS, KEY_SCOPED_BARRIERS,
                               NotColumnar, STATE_FREE_BARRIERS)
from ..server.events import EVENT_PULL_LANDED

_I64 = np.int64

_ENC_ERRORS = (NotColumnar, CstError, IndexError)

log = logging.getLogger(__name__)


def apply_key_delete_rule(ks, b: ColumnarBatch, check) -> None:
    """The element-plane key-delete rule, against the LIVE dt of exactly
    the checked keys: an element add whose uuid predates its key's
    delete time materializes tombstoned (`sadd`/`hset`/`lins` op twin —
    see the module docstring).  `check` is the per-element-row mark the
    add-side encoders leave (None = nothing marked).  Shared by the
    coalescer's flush and the wire-batch decoder (replica/wire.py),
    which must evaluate it against the RECEIVING store."""
    if check is None or not check.any():
        return
    kis = np.unique(b.el_ki[check])
    dts = ks.key_delete_times(list(map(b.keys.__getitem__, kis.tolist())))
    if dts.any():
        dt_by_ki = np.zeros(len(b.keys), dtype=_I64)
        dt_by_ki[kis] = dts
        row_dt = dt_by_ki[b.el_ki]
        kill = check & (b.el_add_t < row_dt)
        if kill.any():
            b.el_del_t = np.where(kill, row_dt, b.el_del_t)


class BatchBuilder:
    """Columnar accumulator the group encoders write into.

    Key rows are ONE PER FRAME (no dedup — the engine's group reductions
    fold repeats, which beats a per-frame dict probe); counter/element
    rows are one per op.  The batch declares
    `rows_unique_per_slot=False`, routing the engine onto its
    duplicate-safe reductions."""

    __slots__ = ("ks", "keys", "enc", "ct", "mt", "dt", "reg_runs",
                 "_dels", "cnt_rows", "el_rows", "tns_rows",
                 "_el_has_vals", "n_rows")

    def __init__(self, ks) -> None:
        self.ks = ks
        self.keys: list[bytes] = []
        self.enc: list[int] = []
        self.ct: list[int] = []
        self.mt: list[int] = []
        self.dt: list[int] = []
        # register writes as (ki0, uuids, nodes, vals) runs — assigned
        # into the key-aligned reg plane by slice at finalize
        self.reg_runs: list[tuple] = []
        self._dels: dict[bytes, int] = {}  # key-level tombstone records
        # per-frame row records, expanded to columns at finalize
        # (np.repeat / chain do the fan-out at C speed):
        #   cnt_rows: (ki, node, total, uuid, base, base_t)
        #   el_rows:  (ki, members, vals-or-None, add_t, add_node,
        #              del_t, dt_check)
        #   tns_rows: (ki, node, uuid, cnt, cfg, payload-bytes)
        self.cnt_rows: list[tuple] = []
        self.el_rows: list[tuple] = []
        self.tns_rows: list[tuple] = []
        self._el_has_vals = False
        self.n_rows = 0

    # ------------------------------------------------------ encoder surface

    def add_keys(self, keys: list, enc: int, uuids: list) -> int:
        """A run of data-write key rows (ct=mt=uuid, dt=0 — the op
        path's get_or_create + updated_at, with repeats folded by the
        engine's envelope max).  Returns the run's first batch index."""
        ki0 = len(self.keys)
        n = len(keys)
        self.keys.extend(keys)
        self.enc.extend([enc] * n)
        self.ct.extend(uuids)
        self.mt.extend(uuids)
        self.dt.extend([0] * n)
        self.n_rows += n
        return ki0

    def add_del_keys(self, keys: list, enc: int, uuids: list) -> int:
        """A run of scalar key-level tombstones (delbytes/delcnt): dt/mt
        advance, ct does NOT (a missing key materializes
        already-tombstoned — ct=0 < dt), and each delete is recorded on
        the batch's del_keys plane so GC/tombstone accounting matches
        the per-key path (KeySpace.record_key_delete via the engine)."""
        ki0 = len(self.keys)
        n = len(keys)
        self.keys.extend(keys)
        self.enc.extend([enc] * n)
        self.ct.extend([0] * n)
        self.mt.extend(uuids)
        self.dt.extend(uuids)
        dels = self._dels
        for k, u in zip(keys, uuids):
            if dels.get(k, -1) < u:
                dels[k] = u
        self.n_rows += n
        return ki0

    def reg_run(self, ki0: int, uuids: list, nodes: list,
                vals: list) -> None:
        self.reg_runs.append((ki0, uuids, nodes, vals))

    # -------------------------------------------------------------- payload

    def finalize(self) -> ColumnarBatch:
        """Materialize the pending rows as one ColumnarBatch.  The
        element-plane key-delete rule is applied HERE, against the live
        store's dt values (see module docstring)."""
        b = ColumnarBatch()
        n = len(self.keys)
        b.keys = self.keys
        b.key_enc = np.fromiter(self.enc, dtype=np.int8, count=n)
        b.key_ct = np.fromiter(self.ct, dtype=_I64, count=n)
        b.key_mt = np.fromiter(self.mt, dtype=_I64, count=n)
        b.key_dt = np.fromiter(self.dt, dtype=_I64, count=n)
        b.key_expire = np.zeros(n, dtype=_I64)
        b.reg_val = [None] * n
        b.reg_t = np.zeros(n, dtype=_I64)
        b.reg_node = np.zeros(n, dtype=_I64)
        for ki0, uuids, nodes, vals in self.reg_runs:
            hi = ki0 + len(vals)
            b.reg_val[ki0:hi] = vals
            b.reg_t[ki0:hi] = uuids
            b.reg_node[ki0:hi] = nodes

        if self.cnt_rows:
            nc = len(self.cnt_rows)
            cols = list(zip(*self.cnt_rows))  # C-speed transpose
            (b.cnt_ki, b.cnt_node, b.cnt_val, b.cnt_uuid, b.cnt_base,
             b.cnt_base_t) = (np.fromiter(c, dtype=_I64, count=nc)
                              for c in cols)

        if self.el_rows:
            recs = self.el_rows
            nr = len(recs)
            cols = list(zip(*recs))
            counts = np.fromiter(map(len, cols[1]), dtype=_I64, count=nr)
            b.el_ki = np.repeat(np.fromiter(cols[0], dtype=_I64, count=nr),
                                counts)
            b.el_member = list(chain.from_iterable(cols[1]))
            ne = len(b.el_member)
            if self._el_has_vals:
                b.el_val = list(chain.from_iterable(
                    v if v is not None else (None,) * int(c)
                    for v, c in zip(cols[2], counts)))
            else:
                b.el_val = [None] * ne
                b.el_has_vals = False
            b.el_add_t = np.repeat(
                np.fromiter(cols[3], dtype=_I64, count=nr), counts)
            b.el_add_node = np.repeat(
                np.fromiter(cols[4], dtype=_I64, count=nr), counts)
            b.el_del_t = np.repeat(
                np.fromiter(cols[5], dtype=_I64, count=nr), counts)
            check = np.repeat(
                np.fromiter(cols[6], dtype=bool, count=nr), counts)
            # the key-delete rule, against the LIVE dt of exactly the
            # checked keys (not the whole batch key list)
            apply_key_delete_rule(self.ks, b, check)
        if self.tns_rows:
            nt = len(self.tns_rows)
            cols = list(zip(*self.tns_rows))
            (b.tns_ki, b.tns_node, b.tns_uuid,
             b.tns_cnt) = (np.fromiter(c, dtype=_I64, count=nt)
                           for c in cols[:4])
            b.tns_cfg = list(cols[4])
            b.tns_payload = list(cols[5])

        if self._dels:
            b.del_keys = list(self._dels.keys())
            b.del_t = np.fromiter(self._dels.values(), dtype=_I64,
                                  count=len(self._dels))
        # raw op stream: keys and slots may repeat across frames — the
        # engine must take its duplicate-safe reductions, not the
        # one-scatter-per-slot bulk placement
        b.rows_unique_per_slot = False
        return b


class CoalescingApplier:
    """Per-connection coalescer driving one peer's replicate stream into
    the node (see module docstring for the discipline)."""

    __slots__ = ("node", "meta", "max_frames", "max_latency", "_now",
                 "cursor", "_epoch", "_buf", "_pending_keys", "_frames",
                 "_first_ts", "_pending_beacon", "_enc_has",
                 "pending_bytes")

    def __init__(self, node, meta, max_frames: Optional[int] = None,
                 max_latency: Optional[float] = None,
                 now: Callable[[], float] = time.monotonic) -> None:
        from ..conf import env_float, env_int
        self.node = node
        self.meta = meta
        self.max_frames = env_int("CONSTDB_APPLY_BATCH", 512) \
            if max_frames is None else max_frames
        self.max_latency = (env_float("CONSTDB_APPLY_LATENCY_MS", 5.0)
                            / 1000.0) if max_latency is None else max_latency
        self._now = now
        # stream cursor: newest uuid RECEIVED gap-free on this connection
        # (dup-skip + gap detection); meta.uuid_he_sent lags it until the
        # covering batch lands
        self.cursor = meta.uuid_he_sent
        self._epoch = node.reset_epoch
        self._buf: dict[bytes, list] = {}   # command -> [(key, origin,
        #                                     uuid, frame items)]
        self._pending_keys: set[bytes] = set()
        self._frames = 0
        self._first_ts = 0.0
        self._pending_beacon = 0
        # received-but-unlanded frame bytes, for the overload governor's
        # accounting (the pull loop registers a source reading this —
        # replica/link.py); approximate (payload bytes + a fixed
        # per-frame overhead), zeroed by every flush
        self.pending_bytes = 0
        # bound C-level membership test for the per-frame dispatch;
        # batch=1 pins the per-frame path by never consulting it
        self._enc_has = COLUMNAR_ENCODERS.__contains__ \
            if self.max_frames > 1 else (lambda _name: False)

    # ------------------------------------------------------------ inspection

    @property
    def pending(self) -> int:
        """Frames received but not yet landed in the store."""
        return self._frames

    # async twins of apply/flush: the pull loop awaits these so one code
    # path drives both this applier and the shard-routing one (which
    # genuinely awaits worker acks — server/serve_shards.py ShardApplier)

    async def aapply(self, items: list) -> None:
        self.apply(items)

    async def aabatch(self, items: list) -> None:
        self.apply_wire_batch(items)

    async def aflush(self) -> None:
        self.flush()

    # --------------------------------------------------------------- intake

    def apply(self, items: list) -> None:
        """One REPLICATE frame (`items` = the full wire frame).  Either
        buffers it for the next coalesced flush or barrier-applies it;
        dup/gap semantics match the per-frame path exactly."""
        cursor = self.cursor
        uuid = as_int(items[3])
        if uuid <= cursor:
            return  # duplicate (reconnect overlap) — idempotent skip
        if as_int(items[2]) > cursor:  # prev_uuid gap check
            # land what we have (gap-free below the cursor) before the
            # teardown: the advanced watermark shrinks the resync replay
            self.flush()
            raise ReplicateCommandsLost(
                f"{self.meta.addr}: gap {cursor} -> {as_int(items[2])}")
        name = as_bytes(items[4])
        if not self._enc_has(name) or len(items) < 6:
            self._barrier(name, items, as_int(items[1]), uuid)
            return
        key = as_bytes(items[5])
        buf = self._buf
        recs = buf.get(name)
        if recs is None:
            recs = buf[name] = []
        f = self._frames
        if not f:
            self._first_ts = self._now()
        recs.append((key, as_int(items[1]), uuid, items))
        self._pending_keys.add(key)
        sz = 48
        for it in items:
            v = getattr(it, "val", None)
            if type(v) is bytes:
                sz += len(v)
        self.pending_bytes += sz
        f += 1
        self._frames = f
        self.cursor = uuid
        # the latency bound is sampled every 32 frames, not every frame:
        # under sustained load (the only regime where the count bound has
        # not fired first) 32 frames pass in well under a millisecond,
        # and a SLOW stream is flushed by the pull loop's idle check
        # before this clause could ever matter
        if f >= self.max_frames or \
                (not f & 31 and
                 self._now() - self._first_ts >= self.max_latency):
            self.flush()

    def apply_wire_batch(self, items: list) -> None:
        """One REPLBATCH frame — a pusher-side group-encoded run of
        consecutive encodable ops (replica/wire.py).  Delivery
        bookkeeping runs ONCE for the whole run: any pending per-frame
        buffer flushes first (stream order), dup/gap checks compare the
        batch header to the cursor, the decoded ColumnarBatch lands
        through `Node.merge_stream_batch`, and the watermark advances
        over the batch only after landing (watermark-after-land).  A
        batch that overlaps the cursor (reconnect redelivery) re-merges
        whole — every op in it is an idempotent merge by the same
        argument the redelivery note in the module docstring makes.

        A payload that fails to decode is LOUD: the link tears down
        (CstError), the peer meta stops advertising CAP_BATCH_STREAM, so
        the redelivery window arrives as ordinary per-frame frames —
        demotion, never silent desync."""
        meta = self.meta
        if len(items) < 6:
            raise CstError(f"{meta.addr}: malformed replbatch frame")
        origin = as_int(items[1])
        first_prev = as_int(items[2])
        last = as_int(items[3])
        n = as_int(items[4])
        payload = as_bytes(items[5])
        if n < 1 or last <= first_prev:
            raise CstError(f"{meta.addr}: bad replbatch header")
        if self._frames:
            self.flush()  # stream order: buffered frames land first
        cursor = self.cursor
        if last <= cursor:
            return  # duplicate batch (reconnect overlap) — idempotent skip
        if first_prev > cursor:
            raise ReplicateCommandsLost(
                f"{meta.addr}: gap {cursor} -> {first_prev}")
        node = self.node
        if node.reset_epoch != self._epoch:
            # a state wipe landed since this stream was installed: these
            # ops describe pre-wipe state (see flush)
            self._pending_beacon = 0
            return
        from . import wire
        from ..utils.compressio import (CompressFormatError,
                                        decompress_bytes, is_compressed)
        try:
            if is_compressed(payload):
                # negotiated stream compression (CAP_COMPRESS): inflate
                # with per-chunk crc validation before the batch codec
                # ever sees a byte — a defect in EITHER layer demotes
                # identically below.  The inflated size is capped at the
                # largest payload an honest pusher can produce (one
                # proto-max value plus batch framing slack): a crafted
                # container cannot bomb the intake past what the plain
                # wire already admits (reject-before-allocate law).
                from ..conf import env_int
                cap = env_int("CONSTDB_PROTO_MAX_BULK", 512 << 20) \
                    + (64 << 20)
                raw = decompress_bytes(payload, max_raw=cap)
                x = node.stats.extra
                x["repl_comp_batches_in"] = \
                    x.get("repl_comp_batches_in", 0) + 1
                payload = raw
            wb = wire.decode_wire_batch(payload, node.ks, origin,
                                        first_prev)
            if wb.n_frames != n:
                raise wire.WireFormatError(
                    f"header says {n} frames, payload holds {wb.n_frames}")
        except CompressFormatError as e:
            st = node.stats
            st.repl_wire_demotions += 1
            x = st.extra
            x["repl_compress_demotions"] = \
                x.get("repl_compress_demotions", 0) + 1
            meta.compress_wire_off = True
            log.error(
                "compressed replbatch from %s is malformed (%s); "
                "demoting this peer's stream to plain delivery and "
                "resyncing from the landed watermark", meta.addr, e)
            raise CstError(
                f"{meta.addr}: malformed compressed replbatch") from None
        except wire.WireFormatError as e:
            st = node.stats
            st.repl_wire_demotions += 1
            meta.batch_wire_off = True
            log.error(
                "replbatch from %s is malformed (%s); demoting this "
                "peer's stream to per-frame delivery and resyncing from "
                "the landed watermark", meta.addr, e)
            raise CstError(
                f"{meta.addr}: malformed replbatch payload") from None
        st = node.stats
        st.cmds_replicated += n
        st.repl_wire_batches_in += 1
        st.repl_wire_batch_frames_in += n
        node.hlc.observe(last)
        node.merge_stream_batch(wb, n)
        if node.oplog is not None:
            # the (decompressed) payload IS the columnar wire encoding
            # and was just crc-validated whole: splice it into the
            # durable op log verbatim — zero re-encode (persist/oplog.py)
            node.oplog.append_batch(origin, first_prev, last, n, payload)
        self.cursor = last
        self._advance(last, wake=True)

    def observe_beacon(self, beacon: int) -> None:
        """REPLACK drained-stream beacon: may only advance the pull
        watermark once every frame it covers has LANDED — with frames
        pending it is stashed and applied by the covering flush."""
        if self._frames:
            if beacon > max(self.cursor, self._pending_beacon):
                self._pending_beacon = beacon
                self.node.hlc.observe(beacon)
        elif beacon > self.meta.uuid_he_sent:
            self.meta.uuid_he_sent = beacon
            if beacon > self.cursor:
                self.cursor = beacon
            self.node.hlc.observe(beacon)

    def resync(self) -> None:
        """Re-anchor after an out-of-band watermark move on this SAME
        connection (FULLSYNC apply, possibly with a state wipe).  Only
        valid with nothing pending — snapshot frames are barriers."""
        self.cursor = self.meta.uuid_he_sent
        self._pending_beacon = 0
        self._epoch = self.node.reset_epoch

    # ---------------------------------------------------------------- land

    def flush(self) -> None:
        """Group-encode the buffered frames, land them through the merge
        engine, and advance the watermark over them (the load-bearing
        ORDER: merge first, watermark after — docs/INVARIANTS.md).

        A run whose group encoder rejects it (malformed frame, in-batch
        type conflict) is retried frame by frame — the builder is
        untouched on failure (parse-then-mutate contract) — and the
        leftovers replay on the exact per-key path after the merge
        (legal by commutativity), raising the exact op-path error."""
        buf, self._buf = self._buf, {}
        frames, self._frames = self._frames, 0
        self.pending_bytes = 0
        if not frames:
            return
        self._pending_keys.clear()
        node = self.node
        if node.reset_epoch != self._epoch:
            # a state wipe landed between intake and flush (another
            # link's reset snapshot): these frames describe pre-wipe
            # state and the zeroed watermark must not re-advance —
            # drop them; the wiped store is re-seeded by the resync
            self._pending_beacon = 0
            return
        if node.oplog is not None:
            # mirror the frames this flush LANDS, in uuid order, before
            # the merge: appended-but-unlanded on a crash replays as an
            # idempotent superset, while land-without-append could lose
            # an acked-upstream op (persist/oplog.py)
            allrecs = sorted(
                (r[2], r[1], name, r[3])
                for name, recs in buf.items() for r in recs)
            for uuid, origin, name, items in allrecs:
                node.oplog.append_frame(origin, uuid, name,
                                        list(items[5:]))
        bb = BatchBuilder(node.ks)
        failures: list = []
        for name, recs in buf.items():
            enc = COLUMNAR_ENCODERS[name]
            try:
                enc(bb, recs)
            except _ENC_ERRORS:
                for r in recs:
                    try:
                        enc(bb, [r])
                    except _ENC_ERRORS:
                        failures.append((name, r))
        # per-flush bookkeeping, not per-frame (hot path): the stats
        # total matches the per-frame path's per-apply bumps, and the
        # clock observes the batch's newest uuid exactly when its
        # effects land — the coalesced analog of observe-at-apply
        node.stats.cmds_replicated += frames - len(failures)
        node.hlc.observe(self.cursor)
        node.merge_stream_batch(bb, frames - len(failures))
        if failures:
            failures.sort(key=lambda f: f[1][2])  # uuid order
            for name, r in failures:
                # the exact per-key path raises the exact op-path error;
                # a raise here leaves the watermark at the previous
                # flush, so the whole window redelivers on reconnect
                # (idempotent) and the bad frame fails again — the
                # per-frame path's behavior for malformed frames
                node.stats.repl_apply_barriers += 1
                node.apply_replicated(name, r[3][5:], r[1], r[2])
        self._advance(self.cursor, wake=frames - len(failures) >= 2)

    def _barrier(self, name: bytes, items: list, origin: int,
                 uuid: int) -> None:
        """Non-encodable frame: the exact per-key path (reference
        pull.rs:184-235 apply_his_replicates).  The pending batch
        flushes first ONLY when the frame can actually observe it:
        membership ops never touch the keyspace, and the key-scoped
        sweeps (collection deletes / expireat / mvwrite) read live rows
        of exactly their first-argument key — with that key untouched by
        the batch, the frame commutes with every pending row and may
        apply in place.  A non-flushing barrier advances only the stream
        CURSOR; the watermark keeps waiting for the covering flush
        (re-applying such a frame after a crash-replay converges — see
        the module docstring's redelivery note)."""
        node = self.node
        if self._frames:
            scoped = name in KEY_SCOPED_BARRIERS and len(items) > 5 and \
                as_bytes(items[5]) not in self._pending_keys
            if not (scoped or name in STATE_FREE_BARRIERS):
                self.flush()
        node.stats.repl_apply_barriers += 1
        node.apply_replicated(name, items[5:], origin, uuid)
        if node.oplog is not None:
            node.oplog.append_frame(origin, uuid, name, list(items[5:]))
        self.cursor = uuid
        if not self._frames:
            self._advance(uuid)

    def _advance(self, uuid: int, wake: bool = False) -> None:
        """Watermark-after-land.  `wake`: this land covered a genuine
        BATCH (a multi-frame flush or a wire batch) — wake the push loop
        to REPLACK it now, one ack per covering batch.  Single-frame
        lands (barriers, trickle traffic) do NOT wake: their acks ride
        the heartbeat exactly as before, because a per-land wake there
        IS an ack per frame — the cadence this satellite removes — and
        each wake costs every link a scheduler round trip."""
        beacon, self._pending_beacon = self._pending_beacon, 0
        w = max(uuid, beacon)
        if w > self.meta.uuid_he_sent:
            self.meta.uuid_he_sent = w
            if wake:
                self.node.events.trigger(EVENT_PULL_LANDED)
        if beacon > self.cursor:
            self.cursor = beacon
