"""Columnar replication wire codec: REPLBATCH payloads.

The steady-state peer stream used to ship one RESP REPLICATE frame per
repl-log entry; the receiver paid ~8-12µs of irreducible per-frame
Python intake (parse → dup/gap → buffer → group-encode) before the
batched merge engine ever saw a row.  Op-based CRDT replication is a
stream of commuting rewrites (PAPERS.md: Semidirect Products; Approaches
to CRDTs §op-based delivery), so a RUN of consecutive encodable entries
may travel as ONE frame with per-batch delivery bookkeeping:

    *[replbatch, origin, first_prev_uuid, last_uuid, n, payload]

The payload is the run group-encoded ONCE on the pusher through the
exact machinery the receiving coalescer would have used —
server/commands.py `COLUMNAR_ENCODERS` into a `replica/coalesce.py`
`BatchBuilder` — then packed into a compact columnar byte layout.  The
receiver validates, reconstructs the ColumnarBatch with vectorized
`np.frombuffer` reads, and hands it straight to
`Node.merge_stream_batch`: no per-frame RESP parse, no per-op re-plan,
no re-encode.

Exactness: the builder rows the registered encoders produce are fully
determined by (key, uuid, origin, frame args) under five fixed patterns
— add/delete key rows, register values, cntset/delcnt counter rows,
add/remove element records, tensor rows — so the payload stores only
the irreducible content (keys, uuid deltas, values, members) and the
decoder re-derives every envelope column from the SAME rules the
encoders apply.  A builder row outside the patterns (a future encoder
the codec does not know) makes `build_wire_batch` return None and the
pusher demotes that run to ordinary per-frame REPLICATE frames — the
wire format can lag the encoder table without ever lying about it.

The element-plane key-delete rule stays RECEIVER-side: add rows carry
their dt-check mark and `WireBatch.finalize()` evaluates it against the
receiving store's live dt columns (store/coalesce semantics, byte for
byte) — a pusher-side evaluation would read the WRONG store.

Integrity: the payload opens with a crc32 of its body.  Any truncation,
bit flip, or trailing garbage raises `WireFormatError` — the receiver
never advances its cursor over a batch it could not fully decode; it
tears the link down loudly and stops advertising CAP_BATCH_STREAM to
that peer, so the redelivery window arrives per-frame
(replica/coalesce.py `apply_wire_batch`).
"""

from __future__ import annotations

import zlib
from typing import Optional

import numpy as np

from ..crdt import semantics as S
from ..errors import CstError
from ..server.commands import COLUMNAR_ENCODERS, NotColumnar
from .coalesce import BatchBuilder, apply_key_delete_rule

_I64 = np.int64

# payload magic + format version; bump on any layout change — a decoder
# seeing an unknown version demotes (WireFormatError), never guesses
MAGIC = b"CWB1"

# builder-level encoder failures that demote a run to per-frame frames
# (replica/coalesce.py _ENC_ERRORS plus the malformed-args classes the
# stub-frame construction itself can raise)
_ENC_ERRORS = (NotColumnar, CstError, IndexError, TypeError, ValueError,
               KeyError)

# key encodings the registered columnar encoders can produce; anything
# else in a payload is malformed by construction
_WIRE_ENCS = frozenset((S.ENC_COUNTER, S.ENC_BYTES, S.ENC_DICT, S.ENC_SET,
                        S.ENC_LIST, S.ENC_TENSOR))

# hard ceilings: a crafted header must not make the decoder allocate
# unboundedly before validation catches up
_MAX_ROWS = 1 << 20


class WireFormatError(CstError):
    """Malformed/corrupt REPLBATCH payload (receiver side)."""


class _PatternError(Exception):
    """Builder row outside the wire patterns (pusher side): demote."""


# ------------------------------------------------------------ primitives
# Adaptive-width columns: one width byte + the values in the smallest
# dtype covering the range.  Everything decodes with one np.frombuffer.

def _pack_ints(out: bytearray, arr: np.ndarray) -> None:
    if len(arr) == 0:
        out.append(8)
        return
    lo, hi = int(arr.min()), int(arr.max())
    for w in (1, 2, 4, 8):
        lim = 1 << (8 * w - 1)
        if -lim <= lo and hi < lim:
            out.append(w)
            out += arr.astype(f"<i{w}").tobytes()
            return
    raise _PatternError("int column out of i64 range")


_WIRE_NATIVE_CACHE: list = []


def _native_wire():
    """(pack, unpack) blob-column entry points from the C extension, or
    None.  Gated separately from the other native tiers: a prebuilt
    cst_ext.so from before native/wire.cpp existed must degrade to the
    pure packers, not AttributeError mid-stream."""
    if not _WIRE_NATIVE_CACHE:
        from ..utils.native_tables import load_ext
        mod = load_ext()
        pack = getattr(mod, "wire_pack_blobs", None)
        unpack = getattr(mod, "wire_unpack_blobs", None)
        _WIRE_NATIVE_CACHE.append((pack, unpack) if pack and unpack
                                  else None)
    return _WIRE_NATIVE_CACHE[0]


def _pack_blobs(out: bytearray, items) -> None:
    """Length-prefixed byte blobs; None entries use the width's max value
    as a sentinel (so a length can never alias it — widths widen first).
    C fast path when the extension is built (native/wire.cpp) — it
    DECLINES any shape off the happy path (non-list, non-bytes rows,
    over-wide blobs), so the pure packer below keeps the reference
    behavior, including the _PatternError demotes, byte for byte."""
    nat = _native_wire()
    if nat is not None and nat[0](out, items):
        return
    n = len(items)
    lens = np.fromiter((len(b) if b is not None else -1 for b in items),
                       dtype=_I64, count=n)
    mx = int(lens.max()) if n else 0
    for w in (1, 2, 4):
        if mx < (1 << (8 * w)) - 1:
            break
    else:
        raise _PatternError("blob too large for the wire")
    sentinel = (1 << (8 * w)) - 1
    out.append(w)
    out += np.where(lens < 0, sentinel, lens).astype(f"<u{w}").tobytes()
    out += b"".join(b for b in items if b is not None)


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: memoryview):
        self.buf = buf
        self.pos = 0

    def take(self, n: int) -> memoryview:
        if n < 0 or self.pos + n > len(self.buf):
            raise WireFormatError("truncated replbatch payload")
        mv = self.buf[self.pos:self.pos + n]
        self.pos += n
        return mv

    def u8(self) -> int:
        return self.take(1)[0]

    def u32(self) -> int:
        return int.from_bytes(self.take(4), "little")

    def ints(self, n: int) -> np.ndarray:
        w = self.u8()
        if w not in (1, 2, 4, 8):
            raise WireFormatError("bad int column width")
        return np.frombuffer(self.take(n * w), dtype=f"<i{w}").astype(_I64)

    def blobs(self, n: int) -> list:
        # C fast path (native/wire.cpp): one call slices the whole
        # column.  A decline (bad width, truncation) falls through to
        # the pure reader, which raises the reference WireFormatError.
        nat = _native_wire()
        if nat is not None:
            res = nat[1](self.buf, self.pos, n)
            if res is not None:
                self.pos = res[1]
                return res[0]
        w = self.u8()
        if w not in (1, 2, 4):
            raise WireFormatError("bad blob length width")
        lens = np.frombuffer(self.take(n * w), dtype=f"<u{w}").astype(_I64)
        sentinel = (1 << (8 * w)) - 1
        none = lens == sentinel
        sizes = np.where(none, 0, lens)
        blob = bytes(self.take(int(sizes.sum())))
        out = []
        pos = 0
        for ln, nn in zip(sizes.tolist(), none.tolist()):
            if nn:
                out.append(None)
            else:
                out.append(blob[pos:pos + ln])
                pos += ln
        return out


# -------------------------------------------------------------- encoding

def _stub_items(entry) -> tuple:
    """Synthetic wire-frame items for the group encoders: they index
    items[5] (key) and items[6:] (args), exactly `[.., key, *rest]`."""
    return (None, None, None, None, None, *entry.args)


def build_wire_batch(entries: list, origin: int) -> Optional[bytes]:
    """Group-encode a run of consecutive ENCODABLE repl-log entries into
    one REPLBATCH payload.  Returns None when any entry rejects its
    encoder or any builder row falls outside the wire patterns — the
    caller demotes the whole run to ordinary per-frame frames (loudly;
    this never raises on bad input)."""
    from ..resp.message import as_bytes
    bb = BatchBuilder(None)
    buf: dict[bytes, list] = {}
    try:
        for e in entries:
            key = as_bytes(e.args[0])
            recs = buf.get(e.name)
            if recs is None:
                recs = buf[e.name] = []
            recs.append((key, origin, e.uuid, _stub_items(e)))
        for name, recs in buf.items():
            COLUMNAR_ENCODERS[name](bb, recs)
        return _encode_builder(bb, origin, entries[0].prev_uuid)
    except (_PatternError, *_ENC_ERRORS):
        return None


def _encode_builder(bb: BatchBuilder, origin: int, base: int) -> bytes:
    """Serialize a filled builder, verifying every row against the wire
    patterns (raises _PatternError on any deviation — the decoder
    re-derives envelope columns from these patterns, so a row they do
    not cover MUST NOT ship)."""
    n = len(bb.keys)
    mt = bb.mt
    # np.array over the builder lists, not fromiter: same values, ~2x
    # less fixed cost per column — this runs once per REPLBATCH run AND
    # once per durable-op-log batch record (persist/oplog.py)
    uuids = np.array(mt, dtype=_I64)
    ct = np.array(bb.ct, dtype=_I64)
    dt = np.array(bb.dt, dtype=_I64)
    del_mask = dt != 0
    if not np.array_equal(np.where(del_mask, 0, uuids), ct) or \
            not np.array_equal(np.where(del_mask, uuids, 0), dt):
        raise _PatternError("key envelope outside add/del patterns")
    du = uuids - base
    if n and int(du.min()) < 1:
        raise _PatternError("non-increasing uuid in run")

    reg_val: list = [None] * n
    for ki0, us, nodes, vals in bb.reg_runs:
        hi = ki0 + len(vals)
        if list(us) != mt[ki0:hi] or any(nd != origin for nd in nodes) \
                or bool(del_mask[ki0:hi].any()):
            raise _PatternError("register run outside the wire pattern")
        reg_val[ki0:hi] = vals

    c_ki, c_node, c_kind, c_pay = [], [], [], []
    for ki, node, val, u_, base_, bt in bb.cnt_rows:
        ku = mt[ki]
        if u_ == ku and base_ == 0 and bt == S.NEUTRAL_T:
            c_kind.append(0)
            c_pay.append(val)
        elif val == 0 and u_ == S.NEUTRAL_T and bt == ku:
            c_kind.append(1)
            c_pay.append(base_)
        else:
            raise _PatternError("counter row outside the wire patterns")
        c_ki.append(ki)
        c_node.append(node - origin)

    e_ki, e_flags, e_cnt, e_members, e_vals = [], [], [], [], []
    for ki, members, vals, at, an, dlt, chk in bb.el_rows:
        ku = mt[ki]
        if at == ku and an == origin and dlt == 0 and chk:
            flags = 1 | (2 if vals is not None else 0)
            if vals is not None:
                e_vals.extend(vals)
        elif at == 0 and an == 0 and dlt == ku and not chk \
                and vals is None:
            flags = 0
        else:
            raise _PatternError("element record outside the wire patterns")
        if not members:
            raise _PatternError("empty element record")
        e_ki.append(ki)
        e_flags.append(flags)
        e_cnt.append(len(members))
        e_members.extend(members)

    t_ki, t_cnt, t_cfg, t_pay = [], [], [], []
    for ki, node, u_, cnt, cfg, payload in bb.tns_rows:
        if node != origin or u_ != mt[ki]:
            raise _PatternError("tensor row outside the wire pattern")
        t_ki.append(ki)
        t_cnt.append(cnt)
        t_cfg.append(cfg)
        t_pay.append(payload)

    body = bytearray()
    body += n.to_bytes(4, "little")
    body += len(c_ki).to_bytes(4, "little")
    body += len(e_ki).to_bytes(4, "little")
    body += len(t_ki).to_bytes(4, "little")
    _pack_blobs(body, bb.keys)
    _pack_ints(body, np.array(bb.enc, dtype=_I64))
    _pack_ints(body, del_mask.astype(_I64))
    _pack_ints(body, du)
    _pack_blobs(body, reg_val)
    for col in (c_ki, c_node, c_kind, c_pay):
        _pack_ints(body, np.array(col, dtype=_I64))
    for col in (e_ki, e_flags, e_cnt):
        _pack_ints(body, np.array(col, dtype=_I64))
    _pack_blobs(body, e_members)
    _pack_blobs(body, e_vals)
    for col in (t_ki, t_cnt):
        _pack_ints(body, np.array(col, dtype=_I64))
    _pack_blobs(body, t_cfg)
    _pack_blobs(body, t_pay)
    return MAGIC + zlib.crc32(body).to_bytes(4, "little") + bytes(body)


# -------------------------------------------------------------- decoding

class WireBatch:
    """A decoded REPLBATCH payload, bound to the RECEIVING keyspace.
    Mirrors the builder surface `Node.merge_stream_batch` consumes:
    `finalize()` applies the element-plane key-delete rule against the
    live store (replica/coalesce.py semantics) and returns the batch."""

    __slots__ = ("ks", "batch", "check", "n_frames")

    def __init__(self, ks, batch, check, n_frames: int):
        self.ks = ks
        self.batch = batch
        self.check = check
        self.n_frames = n_frames

    @property
    def n_rows(self) -> int:
        return self.batch.n_rows

    def finalize(self):
        apply_key_delete_rule(self.ks, self.batch, self.check)
        return self.batch


def decode_wire_batch(payload: bytes, ks, origin: int,
                      base: int) -> WireBatch:
    """Validate + decode one REPLBATCH payload against the receiving
    keyspace.  Raises WireFormatError on ANY defect — truncation, crc
    mismatch, out-of-range index, trailing bytes — so a batch either
    decodes whole or advances nothing."""
    try:
        return _decode(payload, ks, origin, base)
    except WireFormatError:
        raise
    except (ValueError, IndexError, OverflowError, TypeError) as e:
        raise WireFormatError(f"malformed replbatch payload: {e}") from None


def _decode(payload: bytes, ks, origin: int, base: int) -> WireBatch:
    from ..engine.base import ColumnarBatch
    if len(payload) < 8 or payload[:4] != MAGIC:
        raise WireFormatError("bad replbatch magic/version")
    crc = int.from_bytes(payload[4:8], "little")
    body = memoryview(payload)[8:]
    if zlib.crc32(body) != crc:
        raise WireFormatError("replbatch payload crc mismatch")
    r = _Reader(body)
    n = r.u32()
    nc = r.u32()
    ne = r.u32()
    nt = r.u32()
    if not (0 < n <= _MAX_ROWS) or nc > _MAX_ROWS or ne > _MAX_ROWS \
            or nt > _MAX_ROWS:
        raise WireFormatError("replbatch row counts out of range")

    b = ColumnarBatch()
    b.keys = r.blobs(n)
    if any(k is None for k in b.keys):
        raise WireFormatError("null key in replbatch")
    enc = r.ints(n)
    if not set(enc.tolist()) <= _WIRE_ENCS:
        raise WireFormatError("unknown key encoding in replbatch")
    b.key_enc = enc.astype(np.int8)
    del_mask = r.ints(n)
    if not set(del_mask.tolist()) <= {0, 1}:
        raise WireFormatError("bad key-row kind in replbatch")
    del_mask = del_mask.astype(bool)
    du = r.ints(n)
    if int(du.min()) < 1:
        raise WireFormatError("non-positive uuid delta in replbatch")
    uuid = base + du
    b.key_ct = np.where(del_mask, 0, uuid)
    b.key_mt = uuid
    b.key_dt = np.where(del_mask, uuid, 0)
    b.key_expire = np.zeros(n, dtype=_I64)
    b.reg_val = r.blobs(n)
    has_reg = np.fromiter((v is not None for v in b.reg_val),
                          dtype=bool, count=n)
    if bool((has_reg & del_mask).any()):
        raise WireFormatError("register value on a delete row")
    b.reg_t = np.where(has_reg, uuid, 0)
    b.reg_node = np.where(has_reg, origin, 0)

    c_ki = r.ints(nc)
    c_node = r.ints(nc)
    c_kind = r.ints(nc)
    c_pay = r.ints(nc)
    if nc:
        if int(c_ki.min()) < 0 or int(c_ki.max()) >= n or \
                not set(c_kind.tolist()) <= {0, 1}:
            raise WireFormatError("counter rows out of range")
        kind0 = c_kind == 0
        b.cnt_ki = c_ki
        b.cnt_node = c_node + origin
        b.cnt_val = np.where(kind0, c_pay, 0)
        b.cnt_uuid = np.where(kind0, uuid[c_ki], S.NEUTRAL_T)
        b.cnt_base = np.where(kind0, 0, c_pay)
        b.cnt_base_t = np.where(kind0, S.NEUTRAL_T, uuid[c_ki])

    e_ki = r.ints(ne)
    e_flags = r.ints(ne)
    e_cnt = r.ints(ne)
    check = None
    if ne:
        if int(e_ki.min()) < 0 or int(e_ki.max()) >= n or \
                not set(e_flags.tolist()) <= {0, 1, 3} or \
                int(e_cnt.min()) < 1 or int(e_cnt.sum()) > _MAX_ROWS:
            raise WireFormatError("element records out of range")
    n_members = int(e_cnt.sum()) if ne else 0
    members = r.blobs(n_members)
    if any(m is None for m in members):
        raise WireFormatError("null element member")
    has_vals = (e_flags & 2) != 0
    n_vals = int(e_cnt[has_vals].sum()) if ne else 0
    vals = r.blobs(n_vals)
    if any(v is None for v in vals):
        raise WireFormatError("null element value in a valued record")
    if ne:
        add_mask = (e_flags & 1) != 0
        b.el_ki = np.repeat(e_ki, e_cnt)
        add_rows = np.repeat(add_mask, e_cnt)
        row_uuid = uuid[b.el_ki]
        b.el_add_t = np.where(add_rows, row_uuid, 0)
        b.el_add_node = np.where(add_rows, origin, 0)
        b.el_del_t = np.where(add_rows, 0, row_uuid)
        check = add_rows
        b.el_member = members
        if n_vals:
            out_vals: list = []
            pos = 0
            for cnt, hv in zip(e_cnt.tolist(), has_vals.tolist()):
                if hv:
                    out_vals.extend(vals[pos:pos + cnt])
                    pos += cnt
                else:
                    out_vals.extend([None] * cnt)
            b.el_val = out_vals
        else:
            b.el_val = [None] * n_members
            b.el_has_vals = False

    t_ki = r.ints(nt)
    t_cnt = r.ints(nt)
    t_cfg = r.blobs(nt)
    t_pay = r.blobs(nt)
    if nt:
        if int(t_ki.min()) < 0 or int(t_ki.max()) >= n or \
                any(c is None for c in t_cfg) or \
                any(p is None for p in t_pay):
            raise WireFormatError("tensor rows out of range")
        b.tns_ki = t_ki
        b.tns_node = np.full(nt, origin, dtype=_I64)
        b.tns_uuid = uuid[t_ki]
        b.tns_cnt = t_cnt
        b.tns_cfg = t_cfg
        b.tns_payload = t_pay

    if r.pos != len(body):
        raise WireFormatError("trailing bytes after replbatch payload")

    if bool(del_mask.any()):
        dels: dict[bytes, int] = {}
        for k, u_, dm in zip(b.keys, uuid.tolist(), del_mask.tolist()):
            if dm and dels.get(k, -1) < u_:
                dels[k] = u_
        b.del_keys = list(dels.keys())
        b.del_t = np.fromiter(dels.values(), dtype=_I64, count=len(dels))

    b.rows_unique_per_slot = False
    return WireBatch(ks, b, check, n)
