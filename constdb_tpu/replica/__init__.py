"""Replication: mesh membership, per-peer links, sync state machines."""

from .manager import ReplicaManager, ReplicaMeta

__all__ = ["ReplicaManager", "ReplicaMeta"]
