"""Cluster-membership commands: MEET / FORGET / REPLICAS.

Capability parity with the reference's replica ops (reference
src/replica.rs:16-93).  Differences, both deliberate:
  * MEET and FORGET are replicating writes — membership changes ride the
    normal op stream in addition to snapshot REPLICAS sections, so the
    transitive mesh join does not depend on a full sync happening.
  * FORGET is actually registered (the reference defines it but never adds
    it to the COMMANDS table — SURVEY.md §"Known reference defects").

`SYNC` has no handler here: it is a connection upgrade, intercepted by the
IO layer before dispatch (server/io.py), mirroring the reference's
sync_command stealing the client connection (replica.rs:16-40).
"""

from __future__ import annotations

import asyncio

from ..resp.message import Arr, Bulk, Err, Int, OK
from ..server.commands import CMD_READONLY, CMD_WRITE, register


def _app(node):
    return getattr(node, "app", None)


@register("meet", CMD_WRITE, families=())
def meet_command(node, ctx, args):
    """(reference replica.rs:49-75)"""
    addr = args.next_str()
    if ":" not in addr:
        return Err(b"address must be host:port")
    app = _app(node)
    if app is not None and addr == app.advertised_addr:
        return OK  # my own address: peers still learn it via replication
    meta = node.replicas.add(addr, ctx.uuid)
    if app is not None:
        app.ensure_link(meta)
    return OK


@register("forget", CMD_WRITE, families=())
def forget_command(node, ctx, args):
    """(reference replica.rs:77-86, unregistered there)"""
    addr = args.next_str()
    app = _app(node)
    if app is not None and addr == app.advertised_addr:
        return OK  # cannot forget myself; the rest of the mesh will
    changed = node.replicas.forget(addr, ctx.uuid)
    meta = node.replicas.get(addr)
    if changed and app is not None and meta is not None and meta.link is not None:
        asyncio.ensure_future(app.drop_link(meta))
    return Int(1 if changed else 0)


@register("replicas", CMD_READONLY)
def replicas_command(node, ctx, args):
    """(reference replica/replica.rs:63-85 generate_replicas_reply)"""
    rows = []
    for addr, m in node.replicas.describe():
        rows.append(Arr([
            Bulk(addr.encode()), Int(m.node_id), Bulk(m.alias.encode()),
            Bulk(b"alive" if m.alive else b"forgotten"),
            Int(m.uuid_i_sent), Int(m.uuid_i_acked),
            Int(m.uuid_he_sent), Int(m.uuid_he_acked)]))
    return Arr(rows)
