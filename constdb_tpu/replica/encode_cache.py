"""Encode-once run cache: N push loops, one wire encoding.

A master-master node with N peers runs N independent push loops that
each drain the SAME repl-log runs; before this cache every loop
re-encoded the run — per-frame RESP or a REPLBATCH columnar payload —
so steady-state replication CPU scaled O(N·ops) when the encode work is
O(ops).  The first loop to drain a run now publishes the finished wire
bytes here; the other loops at the same cursor splice them into their
own socket buffer, so their per-peer work drops to dup/window
bookkeeping plus the write itself.

Keying (the "caps-class" law, docs/INVARIANTS.md "Broadcast plane"):
an entry is (caps_class, cursor) -> (end_cursor, bytes, counters).
`caps_class` captures EVERYTHING that changes the bytes a peer may
legally receive — "b" (REPLBATCH plain), "bz" (REPLBATCH with
negotiated CAP_COMPRESS framing), "f" (the byte-exact per-frame
rendering legacy and demoted peers get — so one legacy peer does not
reintroduce O(N) encode for everyone sharing its cursor range).  Two
peers in different classes never share bytes; two peers in the same
class at the same cursor always may, because the encoding is a pure
function of (class, cursor, log tail) and node-level knobs the class
pins.

Coherence with ring eviction: entries are immutable copies of the run's
bytes, so they stay CORRECT even after the ring evicts the entries they
were built from — but no new reader can ever be at a cursor below
`evicted_up_to` (the push loop's `can_resume_from` forces a resync
first), so such entries are dead weight and are swept.

Bounding: byte-capped LRU (CONSTDB_ENCODE_CACHE_MB; 0 disables) plus
ref-counting — an entry is published with the number of OTHER live
links expected to read it and is dropped the moment the last expected
reader consumes it (or immediately not cached when there are none, so a
single-peer node pays zero overhead).  The resident bytes are a
registered `used_memory` source for the overload governor
(server/overload.py "accounting completeness").
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional


class CachedRun:
    """One published wire encoding of a drained run."""

    __slots__ = ("end", "payload", "batches", "batch_frames",
                 "comp_raw", "comp_wire", "refs")

    def __init__(self, end: int, payload: bytes, batches: int,
                 batch_frames: int, comp_raw: int,
                 comp_wire: int, refs: int):
        self.end = end                  # cursor after the run
        self.payload = payload          # finished wire bytes
        self.batches = batches          # REPLBATCH frames inside
        self.batch_frames = batch_frames  # ops they cover
        self.comp_raw = comp_raw        # compression accounting
        self.comp_wire = comp_wire
        self.refs = refs                # expected remaining readers


class RunEncodeCache:
    """Bounded, ref-counted (caps_class, cursor) -> CachedRun map."""

    def __init__(self, cap_bytes: int = 16 << 20):
        self.cap_bytes = cap_bytes
        self._map: OrderedDict[tuple, CachedRun] = OrderedDict()
        self.bytes = 0

    def configure(self, cap_bytes: int) -> None:
        self.cap_bytes = cap_bytes
        self._shrink()

    @property
    def enabled(self) -> bool:
        return self.cap_bytes > 0

    def used_bytes(self) -> int:
        """Governed residency (overload-governor source)."""
        return self.bytes

    def __len__(self) -> int:
        return len(self._map)

    # ---------------------------------------------------------------- ops

    def get(self, caps_class: str, cursor: int,
            below: Optional[int] = None) -> Optional[CachedRun]:
        """The published encoding starting exactly after `cursor`, or
        None (the caller encodes and `put`s).  Consuming the last
        expected reader's reference drops the entry.  (Hit/miss GAUGES
        live on NodeStats — repl_encode_cache_hits/misses, counted by
        the push loop per DRAINED run, not per empty poll.)

        `below`: the caller's emission floor (repl-log floor
        discipline) — an entry whose run reaches at/past it is NOT
        handed out (and its refs are untouched: the caller will be
        back once the floor clears).  Load-bearing for the durable op
        log's emit-only-durable law: the serve path publishes a run's
        encoding at flush time, BEFORE its group commit lands, and an
        ungated splice would emit ops a torn tail could still lose
        (persist/oplog.py; caught by the chaos everysec cell)."""
        e = self._map.get((caps_class, cursor))
        if e is None:
            return None
        if below is not None and e.end >= below:
            return None
        e.refs -= 1
        if e.refs <= 0:
            self._drop((caps_class, cursor))
        else:
            self._map.move_to_end((caps_class, cursor))
        return e

    def put(self, caps_class: str, cursor: int, end: int, payload: bytes,
            batches: int = 0, batch_frames: int = 0,
            comp_raw: int = 0, comp_wire: int = 0,
            readers: int = 0) -> None:
        """Publish a finished encoding.  `readers`: how many OTHER links
        are expected to drain this range — <= 0 skips caching entirely
        (nobody to share with)."""
        if not self.enabled or readers <= 0 or not payload:
            return
        key = (caps_class, cursor)
        if key in self._map:
            self._drop(key)
        self._map[key] = CachedRun(end, payload, batches, batch_frames,
                                   comp_raw, comp_wire, readers)
        self.bytes += len(payload)
        self._shrink()

    def evict_below(self, evicted_up_to: int) -> None:
        """Ring-eviction sweep: entries whose start cursor fell below
        the resumable horizon can never be read again (no peer can
        legally sit at that cursor — it would resync instead)."""
        if not self._map:
            return
        dead = [k for k in self._map if k[1] < evicted_up_to]
        for k in dead:
            self._drop(k)

    def clear(self) -> None:
        self._map.clear()
        self.bytes = 0

    # ------------------------------------------------------------ internal

    def _drop(self, key: tuple) -> None:
        e = self._map.pop(key, None)
        if e is not None:
            self.bytes -= len(e.payload)

    def _shrink(self) -> None:
        while self.bytes > self.cap_bytes and self._map:
            key = next(iter(self._map))  # LRU head
            self._drop(key)
