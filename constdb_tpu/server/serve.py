"""Coalesced client serving: pipelined RESP chunks ride the merge engine.

The connection loop (server/io.py) used to execute every client command
one at a time through the full dispatch stack — the same per-message
Python shape PR 4 eliminated on the replication intake.  Under pipelined
load a single read chunk carries dozens of commands; this module plans
the chunk instead: contiguous runs of group-encodable write commands
(`server/commands.py SERVE_PLANNERS`) are translated into their
replication rewrites, group-encoded into ONE ColumnarBatch by the same
COLUMNAR_ENCODERS the replication coalescer uses, and landed through
`node.merge_serve_batch` (vectorized host micro-merge,
engine/hostbatch.py).  The run's repl_log entries append in one pass
(`ReplLog.push_many`).

Ordering discipline (docs/INVARIANTS.md "Client-serving coalescing"):

  * replies are produced strictly in request order.  Planned replies are
    computed at plan time from the landed store overlaid with the
    pending run's tracked per-key deltas — byte-identical to what the
    per-command path would have replied, because the whole chunk runs
    synchronously on the single-writer loop (nothing can interleave) and
    every command that could OBSERVE pending rows is a barrier.
  * runs of plannable key-scoped READS (commands.SERVE_READS —
    get/scnt/sismember/smembers/hget/hgetall/lrange/llen) become ONE
    planned read batch instead of N barriers: keys resolve via one
    batched native index call, the device flush narrows to exactly the
    families the run observes (READ_FLUSH_FAMILIES → ensure_flushed_for
    — a clean resident plane serves the batch with zero downloads),
    values gather vectorized per family (store/keyspace.py
    register_get_batch / counter_sum_batch / elem_live_rows_batch /
    elem_probe_batch), and finished reply bytes are served from —
    and fill — the versioned hot-key reply cache (server/read_cache.py,
    CONSTDB_READ_CACHE_MB).  A run stays open across interleaved
    commands that provably commute with it — KEY-CONFINED data commands
    whose first-arg key the run does not read (their replies buffer and
    splice back in exact request order; their HLC ticks and state
    effects happen at their exact positions, as do the reads' own
    ticks, minted at append time) — so a 90:10 pipeline plans
    chunk-sized read batches instead of write-fragmented slivers.
    Read-your-writes is structural: any command touching a run key
    closes the run first, a read batch lands the pending write run
    first iff one of its keys has pending rows (serve_read_flushes),
    and anything unusual (expiry-armed key, type conflict, odd arity)
    demotes to the exact per-command path at its exact position in the
    run.
  * other reads, non-plannable writes, and admin commands are ordered
    BARRIERS: the pending run flushes (lands + logs) first, then the
    command executes on the exact per-command path.  Read-your-writes
    within a pipeline is therefore free, and the reply socket write
    already sits at end-of-chunk, after the covering flush.  Two
    refinements keep barriers from fragmenting runs: a key-scoped READ
    of a key with no pending rows commutes with the whole run and
    executes WITHOUT flushing it (SERVE_KEY_SCOPED_READS), and a
    barrier invalidates only the cached state it could actually have
    changed — the key in its first argument (_invalidate_after) — so
    the chunk's bulk-seeded probe caches (_preprobe) survive.
  * a chunk that yields a single message takes the per-command path
    untouched — a lone command pays ZERO added latency and no
    micro-merge overhead.  `CONSTDB_SERVE_BATCH=1` pins every
    connection to the exact per-command path (server/io.py never
    constructs a coalescer).
  * the run NEVER spans chunks: replies must reach the socket at
    end-of-chunk, so the chunk epilogue always flushes.  Between chunks
    the loop runs (peer streams, other clients), so all per-chunk state
    caches reset at chunk entry.

Exactness notes (why planned == per-command, byte for byte):
  * every plannable command's local apply equals applying its own
    replication rewrite — and PR 4 established that the rewrites'
    columnar GROUP encoding through the merge engine is byte-identical
    to the per-key op path (replica/coalesce.py module docstring).
  * replies: `set` wins its LWW against any landed state (the HLC has
    observed every landed write, so a fresh client uuid is strictly
    newer) and against earlier pending writes (smaller uuids) — the
    planner still runs the exact comparison.  Counter replies derive
    from one landed-state probe per key per run plus tracked deltas;
    element replies from one landed-row probe per (key, member) plus
    tracked visibility flips.
  * uuid parity: planners mint one HLC write-tick per planned command,
    demotions mint none — the uuid sequence is identical to the
    per-command path's, which makes a coalesced node's canonical export
    byte-identical to a CONSTDB_SERVE_BATCH=1 node's under the same
    deterministic workload (tests/test_serve_coalesce.py).
"""

from __future__ import annotations

import time

import numpy as np

from ..errors import CstError
from ..resp.codec import encode_into
from ..resp.message import (Arr, Bulk, Int, NIL, NoReply, OK, as_bytes,
                            as_int)
from ..replica.coalesce import BatchBuilder
from ..crdt import semantics as S
from ..store.keyspace import KeySpace
from .commands import (CMD_CTRL, CMD_READONLY, COMMANDS, SERVE_ENCODERS,
                       SERVE_KEY_SCOPED_READS, SERVE_PLANNERS,
                       SERVE_READS)
from .events import EVENT_REPLICATED

_I64 = np.int64


def _enc1(msg) -> bytes:
    b = bytearray()
    encode_into(b, msg)
    return bytes(b)


# pre-encoded constant replies the read planner emits without building
# message objects (absent keys / empty ranges)
_NIL_BYTES = _enc1(NIL)
_INT0_BYTES = _enc1(Int(0))
_EMPTY_ARR_BYTES = _enc1(Arr([]))
# the reply cache's stamp-verify reads host env columns only
_ENV_FAMS = ("env",)

# pre-probe extraction tables (_preprobe): which argument positions of a
# plannable command name state the planners will ask for
_PP_REG = frozenset((b"set",))
_PP_CNT = frozenset((b"incr", b"decr"))
_PP_EL = {b"sadd": (S.ENC_SET, 1), b"srem": (S.ENC_SET, 1),
          b"hset": (S.ENC_DICT, 2), b"hdel": (S.ENC_DICT, 1)}
_PP_ANY = _PP_REG | _PP_CNT | frozenset(_PP_EL)
# below this many plannable commands the batch calls cost more than the
# per-command probes they replace
_PREPROBE_MIN = 16

# demotion sentinel returned by ServeCoalescer.resolve_key on a type
# conflict: the command re-executes per-command and raises the exact
# op-path error (planners compare with `is`)
CONFLICT = object()

# ---------------------------------------------------------- native intake
# Opcode numbering emitted by native/intake.cpp intake_scan — part of the
# extension ABI (the NATIVE-INTAKE-TABLE marker block there names the
# commands; analysis/rules.py NATIVE-CONTRACT pins it against the
# SERVE_PLANNERS / SERVE_READS registries).  run_native_chunk consumes
# these without ever constructing message objects for the plannable set.
_OP_SET, _OP_INCR1, _OP_INCR, _OP_DECR1, _OP_DECR = 1, 2, 3, 4, 5
_OP_SADD, _OP_SREM, _OP_HSET, _OP_HDEL = 6, 7, 8, 9
_OP_GET, _OP_SCNT, _OP_SISMEMBER, _OP_SMEMBERS = 10, 11, 12, 13
_OP_HGET, _OP_HGETALL, _OP_LLEN, _OP_HLEN = 14, 15, 16, 17
_FIRST_READ_OP = _OP_GET

_OP_NAME = {_OP_SET: b"set", _OP_INCR1: b"incr", _OP_INCR: b"incr",
            _OP_DECR1: b"decr", _OP_DECR: b"decr", _OP_SADD: b"sadd",
            _OP_SREM: b"srem", _OP_HSET: b"hset", _OP_HDEL: b"hdel",
            _OP_GET: b"get", _OP_SCNT: b"scnt",
            _OP_SISMEMBER: b"sismember", _OP_SMEMBERS: b"smembers",
            _OP_HGET: b"hget", _OP_HGETALL: b"hgetall", _OP_LLEN: b"llen",
            _OP_HLEN: b"hlen"}
# shared command-head Bulks for demote-time message materialization
# (handlers only ever read them)
_OP_HEAD = {op: Bulk(nm) for op, nm in _OP_NAME.items()}
# CMD_DENYOOM members of the native write set (the maxmemory shed gate;
# srem/hdel free memory and keep riding the run, like the pure path)
_OOM_OPS = frozenset((_OP_SET, _OP_INCR1, _OP_INCR, _OP_DECR1, _OP_DECR,
                      _OP_SADD, _OP_HSET))
# read opcode -> (SERVE_READS spec, canonical lowercase name): the same
# (spec, name) pair _planner_of resolves per message
_NOP_READ = {op: (SERVE_READS[_OP_NAME[op]], _OP_NAME[op])
             for op in range(_FIRST_READ_OP, _OP_HLEN + 1)}
# element-family write opcodes that share one planner body
_NOP_ELEM = {_OP_SADD: (b"sadd", S.ENC_SET, True),
             _OP_SREM: (b"srem", S.ENC_SET, False),
             _OP_HDEL: (b"hdel", S.ENC_DICT, False)}
# pre-encoded planned replies (reply bytes are emitted directly — the
# pure planners' OK/_INT0/Int(n) objects encode to exactly these)
_OK_BYTES = _enc1(OK)
_INT_BYTES = [b":%d\r\n" % i for i in range(1024)]


def _nat_msg(op: int, pl):
    """Materialize the full message for a natively-scanned command —
    only ever on the cold paths (lone command, demotion, OOM shed,
    barrier) where the pure path would hold a parsed message."""
    if op == 0:
        return pl
    if op < _FIRST_READ_OP:
        return Arr([_OP_HEAD[op]] + pl[0])
    return Arr([_OP_HEAD[op]] + [Bulk(x) for x in pl])


def _materialize_msg(m):
    """A read-run slot holds either a parsed message (pure intake) or a
    native `(op, raws)` marker — the message is built only if the read
    demotes to the per-command path."""
    if type(m) is not tuple:
        return m
    op, raw = m
    return Arr([_OP_HEAD[op]] + [Bulk(x) for x in raw])


class ServeCoalescer:
    """Per-connection planner driving pipelined client chunks into the
    node (see module docstring for the discipline)."""

    CONFLICT = CONFLICT

    __slots__ = ("node", "max_run", "nodeid", "ks", "regs", "cnts", "els",
                 "tns", "_keys", "_pending_keys", "_buf", "_log",
                 "_pending", "_planned", "_lat_pending", "_sample_every",
                 "_now", "_cur_uuid", "client")

    def __init__(self, node, max_run: int = 512,
                 sample_every: int | None = None,
                 now=time.monotonic, client=None) -> None:
        from ..conf import env_int
        self.node = node
        # the connection's ClientConn (server/tracking.py): demoted
        # per-command executions carry it into ExecCtx, and planned
        # reads feed note_read for default-mode tracking subscribers
        self.client = client
        self.max_run = max_run
        self.nodeid = node.node_id
        self.ks = node.ks
        # per-chunk overlay caches: landed-state probes (seeded in bulk
        # by _preprobe) overlaid with the pending run's own writes.
        # Reset at chunk entry; a mid-chunk barrier invalidates only the
        # key it touched (_invalidate_after) — everything else it could
        # not have changed stays warm.
        self._keys: dict = {}   # key -> (kid, enc); kid -1 = run-created
        self.regs: dict = {}    # key -> (rv_t, rv_node)
        self.cnts: dict = {}    # key -> [visible_sum, my_slot_total]
        self.els: dict = {}     # key -> {member -> visible?}
        self.tns: dict = {}     # key -> packed cfg of run-created tensors
        # the pending run
        self._pending_keys: set = set()  # keys with un-landed rows
        self._buf: dict = {}    # rewrite name -> encoder recs
        self._log: list = []    # (uuid, name, args) for push_many
        self._pending = 0
        self._planned = 0
        self._lat_pending: list = []
        self._sample_every = env_int("CONSTDB_SERVE_LAT_SAMPLE", 32) \
            if sample_every is None else sample_every
        self._now = now
        # pre-minted HLC uuid for the command currently being planned
        # (shard-per-core serving: the parent process is the clock
        # authority and mints at route time — see run_chunk `uuids`).
        # None = mint locally via node.hlc (the shards=1 path).
        self._cur_uuid = None

    # -------------------------------------------------------------- chunk

    def run_chunk(self, msgs: list, out: bytearray, uuids: list = None,
                  spans: list = None) -> None:
        """Plan and execute one drained chunk of client messages,
        appending every reply to `out` in request order.  The pending
        run always lands before this returns.

        `uuids`: pre-minted HLC uuids, one per message, assigned by the
        shard-routing parent (server/serve_shards.py) with the exact
        tick(is_write) discipline the local paths apply — planners and
        demoted per-command executions consume the message's assigned
        uuid instead of ticking.  `spans`: when given, receives
        `len(out)` after each message — the parent slices per-command
        replies out for in-order reassembly across shards."""
        self._reset_caches()
        if len(msgs) == 1:
            # lone command: the exact per-command path, zero overhead
            # (no invalidation needed — the next chunk resets anyway)
            if uuids is not None:
                self._cur_uuid = uuids[0]
            self._exec(msgs[0], out, count_barrier=False,
                       invalidate=False)
            self._cur_uuid = None
            if spans is not None:
                spans.append(len(out))
            return
        plan = [self._planner_of(m) for m in msgs]
        gov = self.node.governor
        if gov.maxmemory and gov.shed_writes(weight=len(msgs)):
            # maxmemory shed: data-growing writes must NOT be planned —
            # they fall through to _exec, where execute() returns the
            # exact -OOM error without applying, logging, or
            # replicating anything.  Exempt planners (srem/hdel free
            # memory) keep riding the run; reads (tuple plans) are
            # never shed.
            plan = [None if callable(fn) and self._oom_gated(m) else fn
                    for fn, m in zip(plan, msgs)]
        cl = self.node.cluster
        if cl is not None:
            # slot routing (cluster/slots.py): a planned command on a
            # slot this group does not serve must NOT ride the run —
            # demote it to the exact per-command path, where execute()
            # returns (and counts) the byte-exact MOVED/ASK redirect.
            # Keys come from the same first-arg confinement the
            # planners ride (KEY-CONFINED).
            for i, fn in enumerate(plan):
                if fn is None:
                    continue
                if type(fn) is tuple:
                    key, wr = fn[2], False  # read plans serve through
                    #                         an ASK window (write law)
                else:
                    it = msgs[i].items
                    key = it[1].val if len(it) > 1 and \
                        type(it[1]) is Bulk else None
                    wr = True  # callable planners are all write planners
                if key is not None and cl.needs_redirect(key, wr):
                    plan[i] = None
        n = len(msgs)
        n_plannable = sum(callable(f) for f in plan)
        if n_plannable >= _PREPROBE_MIN:
            self._preprobe(msgs, plan)
        max_run = self.max_run
        tick = self.node.hlc.tick
        read_run: list = []
        run_keys: set = set()   # keys the open read run observes
        deferred: list = []     # (msg_index, reply_bytes) executed while
        #                         the run stayed open (disjoint keys)
        for i, msg in enumerate(msgs):
            fn = plan[i]
            if type(fn) is tuple:
                # runs of plannable key-scoped reads become ONE planned
                # read batch (batched key resolution + vectorized family
                # gathers + the versioned reply cache) instead of N
                # per-command barriers.  The run's HLC tick is minted
                # HERE — at the read's exact stream position — so the
                # uuid stream is the per-command path's even though the
                # gathers run later.
                pre = uuids[i] if uuids is not None else tick(False)
                read_run.append((i, msg) + fn + (pre,))
                run_keys.add(fn[2])
                continue
            if read_run:
                # a read run stays open across interleaved commands that
                # provably commute with every read in it: a registered
                # data command confined to a first-arg key OUTSIDE the
                # run's key set (KEY-CONFINED — the same convention the
                # planners and the reply cache ride).  Anything else —
                # a write/read of a run key, CTRL, membership, unknown —
                # closes the run first, so each read still gathers the
                # state of its exact stream position.
                key = self._confined_key(msg)
                if key is None or key in run_keys:
                    self._run_read_batch(read_run, out, spans, deferred)
                    read_run = []
                    run_keys = set()
                    deferred = []
            if uuids is not None:
                self._cur_uuid = uuids[i]
            sink = out
            if read_run:
                # reply bytes buffer until the run closes (replies are
                # emitted strictly in request order); state effects
                # happen NOW, at this command's exact position
                sink = bytearray()
            isolated = False
            handled = False
            # a plannable command opens a run only when it has company
            # (an open run, or a plannable successor) — an isolated
            # write between barriers is cheaper per-command than as a
            # one-row micro-merge
            if fn is not None:
                if self._pending or \
                        (i + 1 < n and callable(plan[i + 1])):
                    reply = fn(self, msg.items)
                    if reply is not None:
                        encode_into(sink, reply)
                        handled = True
                    # else: demoted — a real barrier (exact op error)
                else:
                    isolated = True  # per-command by CHOICE, not a barrier
            if not handled:
                if self._pending and not self._scoped_read_commutes(msg):
                    self.flush()
                self._exec(msg, sink, count_barrier=not isolated)
            if sink is out:
                if spans is not None:
                    spans.append(len(out))
            else:
                deferred.append((i, bytes(sink)))
            if handled and self._pending >= max_run:
                self.flush()
        if read_run:
            self._run_read_batch(read_run, out, spans, deferred)
        self._cur_uuid = None
        if self._pending:
            self.flush()

    def run_native_chunk(self, ops: bytes, payloads: list,
                         out: bytearray) -> None:
        """Plan and execute one natively-scanned chunk (`ops`/`payloads`
        from native/intake.cpp intake_scan, via resp/codec.py
        native_drain).  Control flow mirrors run_chunk exactly; native
        opcodes skip message construction, classification, and planner
        dispatch, but share every stateful primitive (tick /
        resolve_key / count_elem_flips / add / flush / _exec), so
        replies, uuid streams, planes, and repl_log entries stay
        byte-identical to the pure path (tests/test_resp_fuzz.py pins
        the differential).  Never used on the sharded plane — io.py
        builds a coalescer only when no plane is active — so there are
        no pre-minted uuids or reply spans here."""
        self._reset_caches()
        n = len(ops)
        if n == 1:
            # lone command: the exact per-command path, zero overhead
            self._exec(_nat_msg(ops[0], payloads[0]), out,
                       count_barrier=False, invalidate=False)
            return
        # plan[i]: a native opcode int, or _planner_of's result for an
        # OP_OTHER message (callable / read-spec tuple / None)
        plan = [op if op else self._planner_of(payloads[i])
                for i, op in enumerate(ops)]
        gov = self.node.governor
        if gov.maxmemory and gov.shed_writes(weight=n):
            plan = [None if (type(fn) is int and fn in _OOM_OPS) or
                    (callable(fn) and self._oom_gated(pl)) else fn
                    for fn, pl in zip(plan, payloads)]
        cl = self.node.cluster
        if cl is not None:
            # slot routing, native intake: same demotion as run_chunk —
            # a native opcode IS a registered key-confined data command
            # (write key = first raw arg, read key = pl[0]), so demoting
            # it to _exec lands on the SAME execute() redirect and the
            # reply bytes stay byte-identical to the pure drain
            # (tests/test_native_intake.py redirect differential)
            for i, fn in enumerate(plan):
                if fn is None:
                    continue
                if type(fn) is int:
                    pl = payloads[i]
                    if fn < _FIRST_READ_OP:
                        key, wr = pl[1][0], True
                    else:
                        key, wr = pl[0], False
                elif type(fn) is tuple:
                    key, wr = fn[2], False
                else:
                    it = payloads[i].items
                    key = it[1].val if len(it) > 1 and \
                        type(it[1]) is Bulk else None
                    wr = True  # callable planners are all write planners
                if key is not None and cl.needs_redirect(key, wr):
                    plan[i] = None
        n_plannable = sum(1 for fn in plan if callable(fn) or
                          (type(fn) is int and fn < _FIRST_READ_OP))
        if n_plannable >= _PREPROBE_MIN:
            reg_keys: list = []
            cnt_keys: list = []
            el_cmds: list = []
            for fn, pl in zip(plan, payloads):
                if type(fn) is int:
                    if fn >= _FIRST_READ_OP:
                        continue
                    raw = pl[1]
                    if fn == _OP_SET:
                        reg_keys.append(raw[0])
                    elif fn <= _OP_DECR:
                        cnt_keys.append(raw[0])
                    elif fn == _OP_HSET:
                        el_cmds.append((raw[0], S.ENC_DICT, None,
                                        raw[1::2]))
                    else:  # sadd / srem / hdel
                        ent = _NOP_ELEM[fn]
                        el_cmds.append((raw[0], ent[1], None, raw[1:]))
                elif callable(fn):
                    self._pp_classify(pl.items, reg_keys, cnt_keys,
                                      el_cmds)
            self._preprobe_core(reg_keys, cnt_keys, el_cmds)
        max_run = self.max_run
        tick = self.node.hlc.tick
        read_run: list = []
        run_keys: set = set()
        deferred: list = []
        for i in range(n):
            fn = plan[i]
            pl = payloads[i]
            if type(fn) is int and fn >= _FIRST_READ_OP:
                # native plannable read: the (spec, name, key, extra,
                # parsed) tuple comes from constant tables; a message is
                # built only if the batch executor demotes it
                spec_name = _NOP_READ[fn]
                if len(pl) > 1:  # sismember / hget carry a member arg
                    extra = parsed = pl[1]
                else:
                    extra, parsed = b"", None
                pre = tick(False)
                read_run.append((i, (fn, pl), spec_name[0], spec_name[1],
                                 pl[0], extra, parsed, pre))
                run_keys.add(pl[0])
                continue
            if type(fn) is tuple:
                pre = tick(False)
                read_run.append((i, pl) + fn + (pre,))
                run_keys.add(fn[2])
                continue
            op = ops[i]
            if read_run:
                # same commutes-with-the-run gate as run_chunk: a native
                # opcode IS a registered key-confined data command, so
                # its confined key is its first payload byte-string
                # (write: first raw arg; read: pl[0] — a slot-demoted
                # native read reaches here with fn=None but op set)
                if op:
                    key = pl[1][0] if op < _FIRST_READ_OP else pl[0]
                else:
                    key = self._confined_key(pl)
                if key is None or key in run_keys:
                    self._run_read_batch(read_run, out, None, deferred)
                    read_run = []
                    run_keys = set()
                    deferred = []
            sink = out
            if read_run:
                sink = bytearray()
            isolated = False
            handled = False
            if fn is not None:
                nxt = plan[i + 1] if i + 1 < n else None
                if self._pending or callable(nxt) or \
                        (type(nxt) is int and nxt < _FIRST_READ_OP):
                    if type(fn) is int:
                        handled = self._nplan_native(fn, pl, sink)
                    else:
                        reply = fn(self, pl.items)
                        if reply is not None:
                            encode_into(sink, reply)
                            handled = True
                else:
                    isolated = True
            if not handled:
                msg = _nat_msg(op, pl)
                if self._pending and not self._scoped_read_commutes(msg):
                    self.flush()
                self._exec(msg, sink, count_barrier=not isolated)
            if sink is not out:
                deferred.append((i, bytes(sink)))
            if handled and self._pending >= max_run:
                self.flush()
        if read_run:
            self._run_read_batch(read_run, out, None, deferred)
        self._cur_uuid = None
        if self._pending:
            self.flush()

    def _nplan_native(self, op: int, pl: tuple, sink: bytearray) -> bool:
        """Plan one native write opcode from its raw payload — each
        branch is the exact planner body (commands.py _plan_set /
        _plan_counter_step / _plan_elem_update / _plan_hset) minus the
        message objects, emitting pre-encoded reply bytes.  Returns
        False to demote: the caller re-executes per-command, identical
        to a pure planner returning None."""
        bulks, raw = pl
        key = raw[0]
        if op == _OP_SET:
            kid = self.resolve_key(key, S.ENC_BYTES)
            if kid is CONFLICT:
                return False
            uuid = self.tick()
            st = self.regs.get(key)
            if st is None:
                st = (int(self.ks.keys.rv_t[kid]),
                      int(self.ks.keys.rv_node[kid])) if kid >= 0 \
                    else (0, 0)
            won = not S.lww_wins(st[0], st[1], uuid, self.nodeid)
            if won:
                self.regs[key] = (uuid, self.nodeid)
            self.add(b"set", (key, uuid, raw[1]), bulks)
            sink += _OK_BYTES if won else _INT0_BYTES
            return True
        if op <= _OP_DECR:  # the incr/decr family
            if op == _OP_INCR1:
                delta = 1
            elif op == _OP_DECR1:
                delta = -1
            else:
                try:
                    delta = as_int(bulks[1])
                except CstError:
                    return False  # non-integer delta: exact op error
                if op == _OP_DECR:
                    delta = -delta
            kid = self.resolve_key(key, S.ENC_COUNTER)
            if kid is CONFLICT:
                return False
            uuid = self.tick()
            st = self.cnts.get(key)
            if st is None:
                ks = self.ks
                st = [ks.counter_sum(kid),
                      ks.counter_slot_total(kid, self.nodeid)] \
                    if kid >= 0 else [0, 0]
                self.cnts[key] = st
            st[0] += delta
            st[1] += delta
            self.node.undo.record(uuid, key, delta)
            self.add(b"cntset", (key, uuid, st[1]),
                     [bulks[0], Int(st[1])])
            v = st[0]
            sink += _INT_BYTES[v] if 0 <= v < 1024 else b":%d\r\n" % v
            return True
        if op == _OP_HSET:
            fields = list(raw[1::2])
            kid = self.resolve_key(key, S.ENC_DICT)
            if kid is CONFLICT:
                return False
            uuid = self.tick()
            cnt = self.count_elem_flips(key, kid, fields, True)
            self.add(b"hset", (key, uuid, fields, list(raw[2::2])), bulks)
            sink += _INT_BYTES[cnt] if cnt < 1024 else b":%d\r\n" % cnt
            return True
        name, enc, add = _NOP_ELEM[op]  # sadd / srem / hdel
        members = list(raw[1:])
        kid = self.resolve_key(key, enc)
        if kid is CONFLICT:
            return False
        uuid = self.tick()
        cnt = self.count_elem_flips(key, kid, members, add)
        self.add(name, (key, uuid, members), bulks)
        sink += _INT_BYTES[cnt] if cnt < 1024 else b":%d\r\n" % cnt
        return True

    @staticmethod
    def _oom_gated(msg) -> bool:
        """Is this (already known-plannable) command a data-growing
        write the maxmemory soft watermark sheds (CMD_DENYOOM)?"""
        from .commands import CMD_DENYOOM
        name = msg.items[0].val
        cmd = COMMANDS.get(name) or COMMANDS.get(name.lower())
        return cmd is not None and bool(cmd.flags & CMD_DENYOOM)

    @staticmethod
    def _planner_of(msg):
        """One classification pass per message: a SERVE_PLANNERS
        callable (plannable write), a read spec TUPLE `(spec, name,
        key, extra, parsed)` for an exact-arity key-scoped read the
        batch executor can serve (commands.SERVE_READS), or None for
        everything else — which falls back to the scoped-read / barrier
        machinery, raising the exact arity/coercion error on the
        per-command path."""
        if type(msg) is not Arr or not msg.items:
            return None
        items = msg.items
        head = items[0]
        if type(head) is not Bulk:
            return None
        name = head.val
        fn = SERVE_PLANNERS.get(name)
        if fn is not None:
            return fn
        spec = SERVE_READS.get(name)
        if spec is None:
            if name in COMMANDS:
                return None
            # mirror the dispatch table's lazy lowercase fallback
            name = name.lower()
            fn = SERVE_PLANNERS.get(name)
            if fn is not None:
                return fn
            spec = SERVE_READS.get(name)
            if spec is None:
                return None
        if len(items) != spec.arity or type(items[1]) is not Bulk:
            return None
        kind = spec.kind
        if kind in ("elemget", "ismember"):
            try:
                extra = as_bytes(items[2])
            except CstError:
                return None
            return (spec, name, items[1].val, extra, extra)
        if kind == "lrange":
            try:
                rng = (as_int(items[2]), as_int(items[3]))
            except CstError:
                return None
            return (spec, name, items[1].val, b"%d:%d" % rng, rng)
        return (spec, name, items[1].val, b"", None)

    def _preprobe(self, msgs: list, plan: list) -> None:
        """Seed the run caches for a whole chunk with BATCHED index
        probes: one native key lookup for every plannable command's key,
        one counter-slot batch, one member-interner batch, one element
        combo batch — replacing the per-command (and per-member) hash
        probes the planners would otherwise pay.  Seeds are exactly the
        values the first per-command probe would read (the store cannot
        change between here and the plans — the chunk runs synchronously
        and everything mutation-capable resets the caches), so planner
        behavior is byte-identical with or without this pass.  Commands
        whose arguments do not parse are simply not seeded — their
        planner demotes them as usual."""
        reg_keys: list = []
        cnt_keys: list = []
        el_cmds: list = []   # (key, want_enc, member item step, items)
        for i, fn in enumerate(plan):
            if not callable(fn):
                continue  # None, or a read-spec tuple (reads resolve
                #           through their own batched path)
            self._pp_classify(msgs[i].items, reg_keys, cnt_keys, el_cmds)
        self._preprobe_core(reg_keys, cnt_keys, el_cmds)

    @staticmethod
    def _pp_classify(items: list, reg_keys: list, cnt_keys: list,
                     el_cmds: list) -> None:
        """Sort one plannable command's probe-able arguments into the
        pre-probe buckets (the message-based extraction half of
        _preprobe; run_native_chunk feeds _preprobe_core directly from
        raw payloads instead)."""
        if len(items) < 2:
            return
        k = items[1]
        if type(k) is not Bulk:
            return
        nm = items[0].val
        if nm not in _PP_ANY:
            nm = nm.lower()
        if nm in _PP_REG:
            reg_keys.append(k.val)
        elif nm in _PP_CNT:
            cnt_keys.append(k.val)
        else:
            ent = _PP_EL.get(nm)
            if ent is None:
                return
            # member extraction is deferred until the key batch shows
            # the key exists with the right encoding — new keys (and
            # demotion-bound conflicts) never pay it
            el_cmds.append((k.val, ent[0], ent[1], items))

    def _preprobe_core(self, reg_keys: list, cnt_keys: list,
                       el_cmds: list) -> None:
        """The batched index probes behind _preprobe.  `el_cmds` rows
        are `(key, want_enc, step, seq)`: step > 0 slices member items
        out of a message item list (`seq[2::step]`, Bulk-gated); step
        None means `seq` already holds raw member byte-strings (the
        native intake path pre-slices its payloads)."""
        node = self.node
        # narrow barrier: the probes below read the key/reg/cnt/el
        # planes only — resident TENSOR payload pools stay put (their
        # stamps are host-authoritative and nothing here reads payloads)
        node.ensure_flushed_for(("env", "reg", "cnt", "el"))
        ks = self.ks
        all_keys = reg_keys + cnt_keys + [e[0] for e in el_cmds]
        if not all_keys:
            return
        kids = ks.key_index.lookup_batch(all_keys).tolist()
        enc_col = ks.keys.enc
        keys_cache = self._keys
        pos = 0
        if reg_keys:
            regs = self.regs
            rv_t, rv_n = ks.keys.rv_t, ks.keys.rv_node
            for key in reg_keys:
                kid = kids[pos]
                pos += 1
                if kid >= 0 and key not in keys_cache:
                    e = int(enc_col[kid])
                    keys_cache[key] = (kid, e)
                    if e == S.ENC_BYTES:
                        regs[key] = (int(rv_t[kid]), int(rv_n[kid]))
        if cnt_keys:
            cnts = self.cnts
            probe: list = []
            for key in cnt_keys:
                kid = kids[pos]
                pos += 1
                if kid >= 0 and key not in keys_cache:
                    e = int(enc_col[kid])
                    keys_cache[key] = (kid, e)
                    if e == S.ENC_COUNTER and key not in cnts:
                        probe.append((key, kid))
            if probe:
                kid_arr = np.fromiter((p[1] for p in probe), dtype=_I64,
                                      count=len(probe))
                rows = ks.cnt_rows_lookup(ks.rank_of(self.nodeid), kid_arr)
                vals = np.where(rows >= 0, ks.cnt.val[rows], 0).tolist()
                sums = ks.keys.cnt_sum[kid_arr].tolist()
                for (key, _kid), sm, tot in zip(probe, sums, vals):
                    cnts[key] = [sm, tot]
        if el_cmds:
            els = self.els
            flat_kids: list = []
            flat_members: list = []
            seed: list = []  # per-key member dict aligned w/ flat_members
            for key, want, step, seq in el_cmds:
                kid = kids[pos]
                pos += 1
                if kid < 0:
                    continue
                if key not in keys_cache:
                    keys_cache[key] = (kid, int(enc_col[kid]))
                if keys_cache[key][1] != want:
                    continue  # the planner demotes this command
                d = els.get(key)
                if d is None:
                    d = els[key] = {}
                if step is None:  # native payload: members are raw bytes
                    for mv in seq:
                        flat_kids.append(kid)
                        flat_members.append(mv)
                        seed.append(d)
                    continue
                for m in seq[2::step]:
                    if type(m) is Bulk:
                        flat_kids.append(kid)
                        flat_members.append(m.val)
                        seed.append(d)
            if flat_members:
                mids = ks.member_index.lookup_batch(flat_members)
                combos = (np.fromiter(flat_kids, dtype=_I64,
                                      count=len(flat_kids))
                          << KeySpace.MEMBER_BITS) | mids
                rows = ks.el_index.lookup_batch(combos)
                rows[mids < 0] = -1
                hit = rows >= 0
                alive = np.zeros(len(rows), dtype=bool)
                if hit.any():
                    hr = rows[hit]
                    alive[hit] = ks.el.add_t[hr] >= ks.el.del_t[hr]
                for d, m, a in zip(seed, flat_members, alive.tolist()):
                    if m not in d:
                        d[m] = a

    def _reset_caches(self) -> None:
        self._keys.clear()
        self.regs.clear()
        self.cnts.clear()
        self.els.clear()
        self.tns.clear()
        self.ks = self.node.ks
        self.nodeid = self.node.node_id

    def _scoped_read_commutes(self, msg) -> bool:
        """True iff `msg` is a key-scoped read whose key has no pending
        rows (see commands.SERVE_KEY_SCOPED_READS) — it then commutes
        with the whole pending run and executes without flushing it."""
        if type(msg) is not Arr or len(msg.items) < 2:
            return False
        head = msg.items[0]
        if type(head) is not Bulk:
            return False
        name = head.val
        if name not in SERVE_KEY_SCOPED_READS and \
                name.lower() not in SERVE_KEY_SCOPED_READS:
            return False
        key = msg.items[1]
        return type(key) is Bulk and key.val not in self._pending_keys

    # ------------------------------------------------------ read planning

    def _confined_key(self, msg):
        """The first-arg key a registered DATA command's effects are
        confined to (the KEY-CONFINED convention the planners, the reply
        cache, and the shard router already rely on), or None for
        anything whose effects cannot be scoped to one key — CTRL
        (subcommands, not keys), membership (cluster state), unknown
        commands, non-Bulk keys.  None tells run_chunk a deferred read
        run cannot stay open across this command."""
        if type(msg) is not Arr:
            return None
        items = msg.items
        if len(items) < 2 or type(items[0]) is not Bulk or \
                type(items[1]) is not Bulk:
            return None
        name = items[0].val
        cmd = COMMANDS.get(name)
        if cmd is None:
            cmd = COMMANDS.get(name.lower())
            if cmd is None:
                return None
        if cmd.flags & CMD_CTRL:
            return None
        if not cmd.families and not (cmd.flags & CMD_READONLY):
            return None  # membership: meet/forget touch cluster state
        return items[1].val

    def _run_read_batch(self, specs: list, out: bytearray, spans,
                        extras=None) -> None:
        """Serve one planned read run as a batch — replies
        byte-identical to the per-command path, emitted strictly in
        request order (see the module docstring's read plane section).
        `specs`: `(msg_index, msg, spec, name, key, extra, parsed,
        uuid)` tuples from run_chunk (`uuid` pre-minted at the read's
        stream position).  `extras`: reply bytes of commands executed
        while the run stayed open — `(msg_index, payload)`, spliced
        back at their exact positions."""
        node = self.node
        st = node.stats
        cl = self.client
        if cl is not None and cl.tracking == 1:
            # default-mode client tracking (server/tracking.py): every
            # read in the batch is a key this connection observes — the
            # tap covers cache hits, planned gathers, AND demotions
            # (the demoted re-execute records again; note_read is
            # idempotent per key)
            trk = node.tracking
            for sp in specs:
                trk.note_read(cl, sp[4])
        # read-your-writes: the run must land first iff a read observes
        # a key with pending rows; reads of un-pending keys commute
        # with the whole pending run (the batched twin of
        # SERVE_KEY_SCOPED_READS)
        if self._pending:
            pend = self._pending_keys
            if any(sp[4] in pend for sp in specs):
                self.flush()
                st.serve_read_flushes += 1
        ks = self.ks
        rc = node.read_cache
        use_cache = rc.enabled
        n = len(specs)
        if use_cache and len(rc):
            # probe BEFORE any key resolution: a hit needs nothing but
            # its stamp verify (the entry carries its kid), so hot-key
            # batches skip the resolution/envelope machinery entirely.
            # env must be host-fresh for the verify; probing is pure,
            # so running it before the ticks cannot affect uuid parity.
            node.ensure_flushed_for(_ENV_FAMS)
            hits = rc.get_batch([(sp[3], sp[4], sp[5]) for sp in specs],
                                ks)
        else:
            if use_cache:
                rc.misses += n
            hits = [None] * n
        miss = [j for j in range(n) if hits[j] is None]
        if not miss:
            # the hot steady state: every reply spliced from the cache
            # (ticks were minted at append time), stats batched
            st.cmds_processed += n
            st.serve_reads_coalesced += n
            if extras:
                self._emit_merged(specs, hits, extras, out, spans)
                return
            for payload in hits:
                out += payload
                if spans is not None:
                    spans.append(len(out))
            return
        resolved: dict = {}
        env: dict = {}
        if miss:
            # narrow device flush: only the families the MISSES observe
            # (a clean resident plane serves the batch with zero flush
            # downloads)
            fams: set = set()
            for j in miss:
                fams.update(specs[j][2].families)
            node.ensure_flushed_for(tuple(fams))
            keys_cache = self._keys
            # batched key resolution: one native index call for every
            # missing key not already probed this chunk.  Entries
            # created by the pending run (kid == -1) re-resolve — a
            # flush above (or earlier in the chunk) may have landed
            # them.
            fresh: list = []
            seen: set = set()
            for j in miss:
                key = specs[j][4]
                ent = keys_cache.get(key)
                if (ent is None or ent[0] < 0) and key not in seen:
                    seen.add(key)
                    fresh.append(key)
            if fresh:
                kids = ks.key_index.lookup_batch(fresh).tolist()
                enc_col = ks.keys.enc
                for key, kid in zip(fresh, kids):
                    if kid >= 0:
                        keys_cache[key] = (kid, int(enc_col[kid]))
            # one envelope gather over the misses (alive / expiry-
            # demote decisions) — scalar below the vectorization floor
            for j in miss:
                resolved[j] = keys_cache.get(specs[j][4], (-1, -1))
            keys_t = ks.keys
            if not keys_t.n:  # empty keyspace: every read is absent
                for j in miss:
                    env[j] = (0, 0, 0)
            elif len(miss) < 16:
                ct_c, dt_c, exp_c = keys_t.ct, keys_t.dt, keys_t.expire
                for j in miss:
                    kid = resolved[j][0]
                    env[j] = (int(ct_c[kid]), int(dt_c[kid]),
                              int(exp_c[kid])) if kid >= 0 else (0, 0, 0)
            else:
                kid_arr = np.fromiter((resolved[j][0] for j in miss),
                                      dtype=_I64, count=len(miss))
                safe = np.maximum(kid_arr, 0)
                ct_l = keys_t.ct[safe].tolist()
                dt_l = keys_t.dt[safe].tolist()
                exp_l = keys_t.expire[safe].tolist()
                for x, j in enumerate(miss):
                    env[j] = (ct_l[x], dt_l[x], exp_l[x])
        # the ordered walk: demotions, hit emits, and miss bucketing
        # happen in request order (ticks were already minted at append
        # time, so the HLC stream is exactly the per-command path's)
        slots: list = [None] * n
        cacheable: list = [False] * n
        miss_scan: list = []   # el-family full scans (members/pairs/...)
        miss_probe: list = []  # el-family combo probes (hget/sismember)
        miss_cnt: list = []    # counter totals (one cnt_sum gather)
        miss_reg: list = []    # register blobs
        planned = 0  # stats batched after the walk (the walk is hot)
        for j, sp in enumerate(specs):
            payload = hits[j]
            if payload is not None:
                planned += 1
                slots[j] = payload
                continue
            i, msg, spec, name, key, extra, parsed, pre = sp
            kid, enc = resolved[j]
            ct_j, dt_j, exp_j = env[j]
            alive = kid >= 0 and ct_j >= dt_j
            kind = spec.kind
            if kid >= 0 and exp_j:
                demote = True  # expiry-armed: time-dependent visibility
            elif kind == "get":
                demote = alive and enc not in (S.ENC_BYTES, S.ENC_COUNTER)
            elif kind in ("lrange", "llen"):
                demote = alive and enc != spec.enc
            else:
                demote = kid >= 0 and enc != spec.enc
            if demote:
                # the exact per-command path raises the exact op error
                # (InvalidType) / applies the exact lazy expiry; only
                # ever its OWN key's state, so the batched gathers
                # below stay coherent (expiry-armed keys never gather).
                # The pre-minted uuid keeps tick parity: execute() skips
                # its own tick and sees the exact per-command uuid.
                self._cur_uuid = pre
                buf = bytearray()
                self._exec(_materialize_msg(msg), buf)
                self._cur_uuid = None
                slots[j] = bytes(buf)
                continue
            # planned: the reply comes from the batched gathers (the
            # read's tick already happened at its stream position)
            planned += 1
            const = None
            if kind == "get":
                if not alive:
                    const = _NIL_BYTES
                elif enc == S.ENC_COUNTER:
                    slots[j] = ("cnt", len(miss_cnt))
                    miss_cnt.append(kid)
                else:
                    slots[j] = ("reg", len(miss_reg))
                    miss_reg.append(kid)
            elif kind in ("elemget", "ismember"):
                if kid < 0:
                    const = _NIL_BYTES if kind == "elemget" \
                        else _INT0_BYTES
                else:
                    slots[j] = ("probe", len(miss_probe))
                    miss_probe.append((j, kid, extra))
            else:  # members / pairs / card / lrange / llen scans
                if kid < 0:
                    const = {"members": _NIL_BYTES,
                             "pairs": _NIL_BYTES,
                             "card": _INT0_BYTES,
                             "lrange": _EMPTY_ARR_BYTES,
                             "llen": _INT0_BYTES}[kind]
                elif kind in ("lrange", "llen") and not alive:
                    const = _EMPTY_ARR_BYTES if kind == "lrange" \
                        else _INT0_BYTES
                else:
                    slots[j] = ("scan", len(miss_scan))
                    miss_scan.append((j, kid))
            if const is not None:
                # fixed reply (absent or dead key): cacheable like any
                # other — absence/deadness is part of the stamp
                slots[j] = const
                if use_cache:
                    rc.put(name, key, extra, kid, ks, const,
                           env=(ct_j, dt_j))
            elif use_cache:
                cacheable[j] = True
        st.cmds_processed += planned
        st.serve_reads_coalesced += planned
        # ---- vectorized family gathers for the misses
        scan_rows: list = []
        if miss_scan:
            scan_rows = ks.elem_live_rows_batch([m[1] for m in miss_scan])
        probe_rows = probe_alive = None
        if miss_probe:
            probe_rows, probe_alive = ks.elem_probe_batch(
                np.fromiter((m[1] for m in miss_probe), dtype=_I64,
                            count=len(miss_probe)),
                [m[2] for m in miss_probe])
        cnt_vals: list = []
        if miss_cnt:
            cnt_vals = ks.counter_sum_batch(
                np.fromiter(miss_cnt, dtype=_I64, count=len(miss_cnt)))
        reg_vals: list = []
        if miss_reg:
            reg_vals = ks.register_get_batch(miss_reg)
        # ---- stitch: encode miss replies, emit everything in order
        # (splicing deferred non-read replies back at their exact
        # positions), fill the cache from the just-encoded bytes
        el_member, el_val = ks.el_member, ks.el_val
        ei, ne = 0, len(extras) if extras else 0
        for j, sp in enumerate(specs):
            while ei < ne and extras[ei][0] < sp[0]:
                out += extras[ei][1]
                if spans is not None:
                    spans.append(len(out))
                ei += 1
            slot = slots[j]
            if type(slot) is tuple:
                kind, ref = slot
                spec = sp[2]
                if kind == "cnt":
                    reply = Int(cnt_vals[ref])
                elif kind == "reg":
                    v = reg_vals[ref]
                    reply = Bulk(v if v is not None else b"")
                elif kind == "probe":
                    row = int(probe_rows[ref])
                    ok = row >= 0 and bool(probe_alive[ref])
                    if spec.kind == "ismember":
                        reply = Int(1 if ok else 0)
                    else:
                        v = el_val[row] if ok else None
                        reply = Bulk(v) if v is not None else NIL
                else:  # scan
                    rows = scan_rows[ref].tolist()
                    k2 = spec.kind
                    if k2 == "members":
                        reply = Arr([Bulk(el_member[r]) for r in rows])
                    elif k2 == "card":
                        reply = Int(len(rows))
                    elif k2 == "llen":
                        reply = Int(len(rows))
                    elif k2 == "pairs":
                        reply = Arr([Arr([Bulk(el_member[r]),
                                          Bulk(el_val[r]
                                               if el_val[r] is not None
                                               else b"")])
                                     for r in rows])
                    else:  # lrange — the handler's sort + slice, exactly
                        live = sorted((el_member[r], el_val[r])
                                      for r in rows)
                        start, stop = sp[6]
                        nv = len(live)
                        if start < 0:
                            start += nv
                        if stop < 0:
                            stop += nv
                        start = max(0, start)
                        if stop < start:
                            reply = Arr([])
                        else:
                            reply = Arr([Bulk(v if v is not None else b"")
                                         for _m, v in
                                         live[start:stop + 1]])
                pos = len(out)
                encode_into(out, reply)
                if cacheable[j]:
                    e = env[j]
                    rc.put(sp[3], sp[4], sp[5], resolved[j][0], ks,
                           bytes(out[pos:]), env=(e[0], e[1]))
            else:
                out += slot
            if spans is not None:
                spans.append(len(out))
        while ei < ne:
            out += extras[ei][1]
            if spans is not None:
                spans.append(len(out))
            ei += 1

    def _emit_merged(self, specs: list, hits: list, extras: list,
                     out: bytearray, spans) -> None:
        """All-hit emission with deferred replies spliced back in
        request order (the fast-path twin of the stitch loop's merge)."""
        ei, ne = 0, len(extras)
        for sp, payload in zip(specs, hits):
            while ei < ne and extras[ei][0] < sp[0]:
                out += extras[ei][1]
                if spans is not None:
                    spans.append(len(out))
                ei += 1
            out += payload
            if spans is not None:
                spans.append(len(out))
        while ei < ne:
            out += extras[ei][1]
            if spans is not None:
                spans.append(len(out))
            ei += 1

    def _exec(self, msg, out: bytearray, count_barrier: bool = True,
              invalidate: bool = True) -> None:
        """Exact per-command execution inside a chunk.  `count_barrier`
        keeps the INFO stat to its documented meaning (reads,
        non-plannable writes, demotions, admin) — an isolated plannable
        write executed per-command by CHOICE is not a barrier, but its
        mutation still invalidates its key's cached probes."""
        node = self.node
        reply = node.execute(msg, client=self.client, uuid=self._cur_uuid)
        if not isinstance(reply, NoReply):
            encode_into(out, reply)
        if count_barrier:
            node.stats.serve_barriers += 1
        if invalidate:
            self._invalidate_after(msg)

    def _invalidate_after(self, msg) -> None:
        """Drop exactly the cached state a just-executed barrier could
        have changed.  Every registered command's keyspace effects are
        confined to the key in its FIRST argument (data commands; the
        differential suite would catch a violation) — commands with
        empty `families` (membership) and READONLY commands touch no
        cached state at all (a read's lazy-expiry dt bump affects none
        of the cached planes).  Anything unclassifiable drops the whole
        cache."""
        node = self.node
        self.nodeid = node.node_id
        self.ks = node.ks
        items = msg.items if type(msg) is Arr else None
        if not items:
            return
        head = items[0]
        name = head.val if type(head) is Bulk else None
        cmd = COMMANDS.get(name) if name is not None else None
        if cmd is None and name is not None:
            cmd = COMMANDS.get(name.lower())
        if cmd is None:
            return  # unknown command: Err reply, nothing executed
        if cmd.flags & CMD_CTRL:
            # control commands take subcommands, not keys (NODE ID even
            # changes the identity the counter overlays are tracked
            # under) — drop everything rather than mis-scope
            self._reset_caches()
            return
        if cmd.flags & CMD_READONLY or not cmd.families:
            return
        if len(items) > 1 and type(items[1]) is Bulk:
            key = items[1].val
            self._keys.pop(key, None)
            self.regs.pop(key, None)
            self.cnts.pop(key, None)
            self.els.pop(key, None)
            self.tns.pop(key, None)
            return
        self._reset_caches()

    # ------------------------------------------------------ planner surface

    def tick(self) -> int:
        if self._cur_uuid is not None:
            return self._cur_uuid
        return self.node.hlc.tick(True)

    def resolve_key(self, key: bytes, enc: int):
        """kid for an existing key, -1 for a key this run (or this batch)
        creates, CONFLICT on an encoding mismatch (the planner demotes —
        the per-command path raises the exact InvalidType)."""
        ent = self._keys.get(key)
        if ent is not None:
            kid, e = ent
            return kid if e == enc else CONFLICT
        node = self.node
        # narrow barrier (see _preprobe): key resolution reads the key
        # table only — tensor payload pools stay resident
        node.ensure_flushed_for(("env", "reg", "cnt", "el"))
        ks = self.ks
        kid = ks.lookup(key)
        if kid >= 0:
            e = ks.enc_of(kid)
            self._keys[key] = (kid, e)
            return kid if e == enc else CONFLICT
        self._keys[key] = (-1, enc)
        return -1

    def count_elem_flips(self, key: bytes, kid: int, members: list,
                         add: bool) -> int:
        """How many of `members` flip visibility under this add/remove —
        the sadd/srem/hset/hdel reply — against landed rows overlaid
        with the run's pending flips."""
        d = self.els.get(key)
        if d is None:
            d = self.els[key] = {}
        ks = self.ks
        el = ks.el
        cnt = 0
        for m in members:
            alive = d.get(m)
            if alive is None:
                if kid >= 0:
                    row = ks.el_row(kid, m)
                    alive = row >= 0 and S.elem_alive(
                        int(el.add_t[row]), int(el.del_t[row]))
                else:
                    alive = False
            if alive != add:
                cnt += 1
            d[m] = add
        return cnt

    def add(self, name: bytes, rec: tuple, args: list) -> None:
        """Commit one planned command: buffer its pre-parsed record
        (`rec[0]` = key, `rec[1]` = uuid — see commands.SERVE_ENCODERS
        for the per-command tails) for the flush-time group encoders,
        queue its repl_log entry, account it."""
        buf = self._buf
        recs = buf.get(name)
        if recs is None:
            recs = buf[name] = []
        recs.append(rec)
        self._pending_keys.add(rec[0])
        self._log.append((rec[1], name, args))
        self._pending += 1
        self.node.stats.cmds_processed += 1
        samp = self._sample_every
        if samp and self._planned % samp == 0:
            self._lat_pending.append(self._now())
        self._planned += 1

    # ---------------------------------------------------------------- land

    def flush(self) -> None:
        """Land the pending run: group-encode into one ColumnarBatch,
        merge through the engine seam, append the run to the repl_log in
        one pass, wake the pushers once."""
        buf, self._buf = self._buf, {}
        n, self._pending = self._pending, 0
        if not n:
            return
        self._pending_keys.clear()
        log, self._log = self._log, []
        node = self.node
        bb = BatchBuilder(node.ks)
        nodeid = self.nodeid
        for name, recs in buf.items():
            # planner-built records are pre-parsed and well-formed by
            # construction (demotion happens at plan time) — encoding is
            # pure list comprehension and cannot reject
            SERVE_ENCODERS[name](bb, recs, nodeid)
        prev_uuid = node.repl_log.last_uuid  # the run's chain base
        node.merge_serve_batch(bb, n)
        node.repl_log.push_many(log)
        if node.oplog is not None:
            # mirror the run as ONE columnar batch record whose payload
            # is the exact REPLBATCH wire encoding — serialized straight
            # from this flush's builder, no re-encode — and publish the
            # finished frame into the encode-once cache so the peer
            # fan-out splices these very bytes (persist/oplog.py)
            node.oplog.append_local_run(log, prev_uuid, builder=bb)
        node.events.trigger(EVENT_REPLICATED, log[-1][0])
        lat = self._lat_pending
        if lat:
            now = self._now()
            ring = node.stats.serve_lat
            ring.extend(now - t for t in lat)
            lat.clear()
