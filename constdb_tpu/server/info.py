"""INFO command: Redis-style sections over node + process + device metrics.

Capability parity with the reference's stats layer (reference src/stats.rs:
global atomics folded into `Metrics`, INFO sections Server/Clients/Memory/
Stats/Replication/CPU/Keyspace, stats.rs:287-305).  The reference's
allocator-integrated memory gauge (jemalloc wrapper, lib.rs:63-78) maps here
to host RSS plus the JAX device HBM accounting (`device.memory_stats()`) —
the TPU-native equivalent called out in SURVEY.md §2.1.
"""

from __future__ import annotations

import os
import resource
import sys
import time

import numpy as np

from ..crdt import semantics as S
from ..resp.message import Bulk
from .commands import CMD_READONLY, register


def _section_server(node, out):
    out.append(("node_id", node.node_id))
    out.append(("node_alias", node.alias))
    app = getattr(node, "app", None)
    if app is not None:
        out.append(("tcp_addr", app.advertised_addr))
    out.append(("process_id", os.getpid()))
    up = time.time() - (node.stats.start_time or time.time())
    out.append(("uptime_in_seconds", int(up)))
    out.append(("current_uuid", node.hlc.current))


def _section_clients(node, out):
    out.append(("connected_clients", node.stats.current_clients))
    out.append(("total_connections_received", node.stats.connections_accepted))
    # client-assisted caching (server/tracking.py): live tracked
    # subscriptions on this node
    tr = getattr(node, "tracking", None)
    out.append(("tracking_clients", tr.n_clients if tr is not None else 0))


def _current_rss_bytes():
    """CURRENT resident set size from /proc/self/status VmRSS (the
    reference reports live allocator bytes, stats.rs:253-260 — a gauge
    that can go DOWN; `ru_maxrss` is the high-water mark and never does).
    Falls back to the peak on non-procfs platforms."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return _peak_rss_bytes()


def _peak_rss_bytes():
    # ru_maxrss is KB on Linux but BYTES on Darwin
    ru = resource.getrusage(resource.RUSAGE_SELF)
    return ru.ru_maxrss if sys.platform == "darwin" else ru.ru_maxrss * 1024


def _section_memory(node, out):
    rss = _current_rss_bytes()
    # governed accounting (server/overload.py): the byte total the
    # maxmemory watermarks are enforced against — store live rows +
    # blob/tensor payloads + repl log + device pools + applier buffers.
    # A shard-per-core node's workers each govern their slice; the
    # parent sums their last-acked gauges (serve_shard<i>_used_bytes).
    plane = getattr(node, "serve_plane", None)
    if plane is not None:
        x = node.stats.extra
        used = node.governor.used_memory() + sum(
            x.get(f"serve_shard{i}_used_bytes", 0)
            for i in range(plane.n_shards))
    else:
        used = node.governor.used_memory()
    out.append(("used_memory", used))
    out.append(("maxmemory", node.governor.maxmemory))
    out.append(("maxmemory_soft", node.governor.soft_bytes))
    out.append(("overload_state", node.governor.state_name))
    out.append(("used_memory_rss", rss))
    # ru_maxrss lags the live gauge by kernel sampling granularity; clamp
    # so the reported peak is never below the reported current
    out.append(("used_memory_peak", max(_peak_rss_bytes(), rss)))
    try:
        dev = node.engine._devices[0]
        ms = dev.memory_stats() or {}
        if "bytes_in_use" in ms:
            out.append(("device_hbm_in_use", ms["bytes_in_use"]))
        if "bytes_limit" in ms:
            out.append(("device_hbm_limit", ms["bytes_limit"]))
        out.append(("device", str(dev)))
    except (AttributeError, RuntimeError, IndexError):
        pass
    # store-exact accounting (reference src/lib.rs:63-78 exposes the
    # allocator gauge; the columnar numeric plane is exactly countable)
    for name, val in node.ks.memory_report().items():
        out.append((f"store_{name}", val))


def _section_stats(node, out):
    st = node.stats
    out.append(("total_commands_processed", st.cmds_processed))
    out.append(("total_commands_replicated", st.cmds_replicated))
    out.append(("total_net_input_bytes", st.net_in_bytes))
    out.append(("total_net_output_bytes", st.net_out_bytes))
    out.append(("repl_net_input_bytes", st.repl_in_bytes))
    out.append(("repl_net_output_bytes", st.repl_out_bytes))
    out.append(("repl_frames_coalesced", st.repl_frames_coalesced))
    out.append(("repl_coalesce_flushes", st.repl_coalesce_flushes))
    out.append(("repl_apply_barriers", st.repl_apply_barriers))
    # batch wire protocol (replica/wire.py REPLBATCH): aggregated
    # steady-state stream bytes out, group-encoded runs sent/received
    # (with the op frames they covered), and receiver-side payload
    # decode failures — each one pins that peer to per-frame delivery
    out.append(("repl_wire_bytes_out", st.repl_wire_bytes_out))
    out.append(("repl_wire_batches_out", st.repl_wire_batches_out))
    out.append(("repl_wire_batch_frames_out",
                st.repl_wire_batch_frames_out))
    out.append(("repl_wire_batches_in", st.repl_wire_batches_in))
    out.append(("repl_wire_batch_frames_in", st.repl_wire_batch_frames_in))
    out.append(("repl_wire_demotions", st.repl_wire_demotions))
    # broadcast plane (replica/encode_cache.py + CAP_COMPRESS): push-
    # loop fan-out reuse of published wire encodings (hits/misses over
    # drained runs, live resident bytes), and the outbound stream
    # compression's raw-vs-wire ratio (1.0 = nothing compressed yet)
    out.append(("repl_encode_cache_hits", st.repl_encode_cache_hits))
    out.append(("repl_encode_cache_misses", st.repl_encode_cache_misses))
    wire_cache = getattr(node, "wire_cache", None)
    out.append(("repl_encode_cache_bytes",
                wire_cache.used_bytes() if wire_cache is not None else 0))
    out.append(("repl_compress_ratio",
                round(st.repl_comp_raw_bytes / st.repl_comp_wire_bytes, 3)
                if st.repl_comp_wire_bytes else 1.0))
    # anti-entropy resyncs this node pushed: digest-negotiated deltas
    # vs full snapshots (replica/link.py; the demotion counter rides
    # `extra` as repl_delta_demotions, with shard ids in the log)
    out.append(("repl_delta_syncs", st.repl_delta_syncs))
    out.append(("repl_delta_bytes", st.repl_delta_bytes))
    out.append(("repl_full_syncs", st.repl_full_syncs))
    out.append(("repl_digest_rounds", st.repl_digest_rounds))
    # replica-link connections re-established after a drop (the backoff
    # ladder's success count — per-peer state/attempts ride the
    # Replication section's repl_link_state / replica<i> rows)
    out.append(("repl_reconnects", st.repl_reconnects))
    # client-serving coalescing (server/serve.py), mirroring the repl_*
    # trio above; the latency percentiles come from the sampled
    # plan→land ring (CONSTDB_SERVE_LAT_SAMPLE)
    out.append(("serve_msgs_coalesced", st.serve_msgs_coalesced))
    out.append(("serve_flushes", st.serve_flushes))
    out.append(("serve_barriers", st.serve_barriers))
    # the coalesced read plane (server/serve.py read planner +
    # server/read_cache.py).  Counters are node totals — a sharded node
    # folds worker deltas into them per ack (server/serve_shards.py) —
    # while the bytes gauge sums the parent cache with the per-shard
    # worker gauges (a shard worker's cache lives in its process)
    out.append(("serve_reads_coalesced", st.serve_reads_coalesced))
    out.append(("serve_read_flushes", st.serve_read_flushes))
    # native intake stage (native/intake.cpp + server/io.py): chunks the
    # C scanner split+classified, and the frames it emitted as opcodes.
    # Both stay zero with CONSTDB_NATIVE_INTAKE=0 / CONSTDB_NO_NATIVE=1
    # — the oracle for "the native leg actually engaged" (scripts/ci.sh)
    out.append(("native_intake_chunks", st.native_intake_chunks))
    out.append(("native_intake_msgs", st.native_intake_msgs))
    rc = node.read_cache
    x = st.extra
    rc_bytes = rc.used_bytes() + sum(
        v for k, v in x.items()
        if k.startswith("serve_shard") and k.endswith("_cache_bytes"))
    out.append(("read_cache_hits", rc.hits))
    out.append(("read_cache_misses", rc.misses))
    out.append(("read_cache_bytes", rc_bytes))
    out.append(("read_cache_invalidations", rc.invalidations))
    # overload governance (server/overload.py): client writes shed at
    # the maxmemory soft watermark, hard-watermark reclaim sweeps,
    # slow-reader disconnects at the outbuf cap, and push loops paused
    # on a full per-peer replication window
    out.append(("oom_shed_writes", st.oom_shed_writes))
    out.append(("oom_hard_reclaims", st.oom_hard_reclaims))
    out.append(("client_outbuf_disconnects", st.client_outbuf_disconnects))
    out.append(("repl_window_pauses", st.repl_window_pauses))
    # client-assisted caching (server/tracking.py): invalidation keys
    # pushed to tracked connections, the push frames carrying them, and
    # over-outbuf trackers demoted to untracked (each one a loud
    # disconnect — the reconnect-flush law restores correctness)
    out.append(("tracking_invalidations_sent", st.tracking_invalidations_sent))
    out.append(("tracking_pushes", st.tracking_pushes))
    out.append(("tracking_demotions", st.tracking_demotions))
    if st.serve_lat:
        lat_ms = np.fromiter(st.serve_lat, dtype=np.float64) * 1000.0
        out.append(("serve_lat_p50_ms",
                    round(float(np.percentile(lat_ms, 50)), 3)))
        out.append(("serve_lat_p99_ms",
                    round(float(np.percentile(lat_ms, 99)), 3)))
    out.append(("merge_batches", st.merges))
    out.append(("merge_rows", st.merge_rows))
    out.append(("merge_seconds_total", round(st.merge_secs, 6)))
    if st.merges and st.merge_secs:
        out.append(("merge_rows_per_sec",
                    int(st.merge_rows / st.merge_secs)))
    out.append(("flush_seconds_total", round(st.flush_secs, 6)))
    fam = getattr(node.engine, "family_secs", None)
    if fam:
        for name, secs in sorted(fam.items()):
            out.append((f"merge_{name}_seconds", round(secs, 6)))
    folds = getattr(node.engine, "folds", None)
    if folds is not None:
        out.append(("merge_folds", folds))
    rebuilds = getattr(node.engine, "mirror_rebuilds", None)
    if rebuilds is not None:
        for name, cnt in sorted(rebuilds.items()):
            out.append((f"mirror_rebuilds_{name}", cnt))
    # device-transfer accounting (engine/tpu.py): cumulative host<->device
    # bytes, steady-state micro rounds merged in place against resident
    # planes vs routed to the host fallback, and the dirty-row flush
    # downloads vs their whole-plane equivalent — the residency metrics
    # the bench legs and the v5e acceptance round read
    if getattr(node.engine, "bytes_h2d", None) is not None:
        out.append(("dev_upload_bytes", node.engine.bytes_h2d))
        out.append(("dev_download_bytes", node.engine.bytes_d2h))
    for gauge in ("dev_rounds_resident", "host_micro_rounds",
                  "flush_rows_downloaded", "flush_rows_full_equiv"):
        v = getattr(node.engine, gauge, None)
        if v is not None:
            out.append((gauge, v))
    # tensor-register family (crdt/tensor.py): merge routing counts +
    # device payload-pool residency; per-strategy merge wins and the
    # host payload gauge live in the Keyspace section
    for gauge in ("tns_dev_rows", "tns_host_rows"):
        v = getattr(node.engine, gauge, None)
        if v is not None:
            out.append((gauge, v))
    v = getattr(node.engine, "_tns_bytes", None)
    if v is not None:
        out.append(("tns_pool_bytes", v))
    out.append(("engine", node.engine.name))
    degraded = getattr(node.engine, "degraded", None)
    if degraded:
        # conf.build_engine fell back from a requested accelerator — make
        # the orders-of-magnitude merge slowdown visible to operators, not
        # just a boot-log line (advisor round-4 finding)
        out.append(("engine_degraded", degraded))
    out.append(("gc_freed", st.gc_freed))
    for k, v in sorted(st.extra.items()):
        out.append((k, v))


def _section_cpu(node, out):
    """(reference src/stats.rs CPU section)"""
    ru = resource.getrusage(resource.RUSAGE_SELF)
    out.append(("used_cpu_user", round(ru.ru_utime, 3)))
    out.append(("used_cpu_sys", round(ru.ru_stime, 3)))
    rc = resource.getrusage(resource.RUSAGE_CHILDREN)
    out.append(("used_cpu_user_children", round(rc.ru_utime, 3)))
    out.append(("used_cpu_sys_children", round(rc.ru_stime, 3)))
    try:
        out.append(("voluntary_ctx_switches", ru.ru_nvcsw))
        out.append(("involuntary_ctx_switches", ru.ru_nivcsw))
    except AttributeError:  # pragma: no cover
        pass


def _section_durability(node, out):
    """Durable op log (persist/oplog.py): enablement, size, group-commit
    health, compaction, and what the last boot recovery found."""
    lg = getattr(node, "oplog", None)
    out.append(("aof_enabled", int(lg is not None)))
    x = node.stats.extra
    if lg is None:
        src = x.get("aof_recovery_source")
        if src:  # recovered once, then disabled mid-run (tests)
            out.append(("aof_recovery_source", src))
        return
    out.append(("aof_fsync_policy", lg.policy))
    out.append(("aof_size_bytes", lg.size_bytes()))
    out.append(("aof_base_size_bytes", lg.base_size))
    out.append(("aof_generation", lg.generation))
    out.append(("aof_segments", lg.n_segments))
    out.append(("aof_appended_ops", lg.appended_ops))
    out.append(("aof_spliced_batches", lg.spliced_batches))
    out.append(("aof_encoded_batches", lg.encoded_batches))
    out.append(("aof_fsyncs", lg.fsyncs))
    out.append(("aof_last_fsync_lag_ms", lg.last_fsync_lag_ms))
    out.append(("aof_rewrites", lg.rewrites))
    out.append(("aof_rewrite_in_progress", int(lg._rewriting)))
    out.append(("aof_tail_truncated", lg.tail_truncated))
    out.append(("aof_pending_floor", lg.durable_floor() or 0))
    out.append(("aof_recovery_source",
                x.get("aof_recovery_source", "empty")))
    out.append(("aof_recovered_ops", x.get("aof_recovered_ops", 0)))


def _section_recovery(node, out):
    """Fast-restart observability (persist/oplog.py): how the last boot
    recovery ran (wall time, landing strategy, replay concurrency) and
    the incremental-checkpoint cut the NEXT restart will replay from."""
    x = node.stats.extra
    out.append(("recovery_wall_s", x.get("recovery_wall_s", 0)))
    out.append(("recovery_mode", x.get("recovery_mode", "")))
    out.append(("recovery_shards", x.get("recovery_shards", 0)))
    out.append(("recovery_merge_rounds",
                x.get("recovery_merge_rounds", 0)))
    if "digest_warm_s" in x:
        out.append(("digest_warm_s", x["digest_warm_s"]))
    if "recovery_restore_to" in x:
        out.append(("recovery_restore_to", x["recovery_restore_to"]))
        out.append(("recovery_restore_skipped",
                    x.get("recovery_restore_skipped", 0)))
    lg = getattr(node, "oplog", None)
    if lg is not None:
        out.append(("checkpoint_secs", lg.checkpoint_secs))
        out.append(("checkpoint_last_uuid", lg.checkpoint_uuid))
        out.append(("checkpoint_age_s",
                    round(time.time() - lg.checkpoint_ts, 3)
                    if lg.checkpoint_ts else -1))


def _section_replication(node, out):
    peers = node.replicas.describe() if node.replicas else []
    live = [m for _, m in peers if m.alive]
    out.append(("connected_replicas", sum(
        1 for m in live if m.link is not None and m.link.connected)))
    out.append(("known_replicas", len(peers)))
    rl = node.repl_log
    out.append(("repl_log_entries", len(rl)))
    out.append(("repl_log_bytes", rl.total_bytes))
    out.append(("repl_log_first_uuid", rl.first_uuid))
    out.append(("repl_log_last_uuid", rl.last_uuid))
    horizon = node.replicas.min_uuid() if node.replicas else None
    out.append(("gc_horizon_uuid", horizon if horizon is not None else ""))
    states = []
    for i, (addr, m) in enumerate(peers):
        link = m.link
        if link is not None and getattr(link, "state", None) is not None:
            # live link: the backoff ladder's own view (connected /
            # dialing / backoff:N / suspended — replica/link.py)
            state = link.state
        else:
            state = "alive" if m.alive else "forgotten"
        states.append(f"{addr}={state}")
        recon = getattr(link, "reconnects", 0) if link is not None else 0
        win = getattr(link, "win_unacked", 0) if link is not None else 0
        win_p = int(getattr(link, "win_paused", False)) \
            if link is not None else 0
        # broadcast-plane per-peer wire observability (replica/link.py):
        # bytes written to this peer, the negotiated compression's
        # raw/wire ratio on its stream, encode-cache reuse counts
        bytes_out = getattr(link, "bytes_out", 0) if link is not None \
            else 0
        craw = getattr(link, "comp_raw_bytes", 0) if link is not None \
            else 0
        cwire = getattr(link, "comp_wire_bytes", 0) if link is not None \
            else 0
        ratio = round(craw / cwire, 3) if cwire else 1.0
        hits = getattr(link, "cache_hits", 0) if link is not None else 0
        misses = getattr(link, "cache_misses", 0) \
            if link is not None else 0
        out.append((f"replica{i}",
                    f"addr={addr},node_id={m.node_id},state={state},"
                    f"reconnects={recon},"
                    f"win_unacked={win},win_paused={win_p},"
                    f"bytes_out={bytes_out},compressed_ratio={ratio},"
                    f"cache_hits={hits},cache_misses={misses},"
                    f"i_sent={m.uuid_i_sent},i_acked={m.uuid_i_acked},"
                    f"he_sent={m.uuid_he_sent},he_acked={m.uuid_he_acked}"))
    if states:
        out.append(("repl_link_state", ";".join(states)))


def _section_keyspace(node, out):
    plane = getattr(node, "serve_plane", None)
    if plane is not None:
        # shard-per-core node: the serve workers hold the keyspace; the
        # per-shard gauges come from the latest worker acks (slightly
        # stale by at most one in-flight chunk), so imbalance across the
        # shard map is observable without a worker round-trip
        x = node.stats.extra
        per = [x.get(f"serve_shard{i}_keys", 0)
               for i in range(plane.n_shards)]
        out.append(("keys", sum(per)))
        out.append(("serve_shards", plane.n_shards))
        for i, n in enumerate(per):
            out.append((f"shard{i}_keys", n))
        return
    ks = node.ks
    n = ks.keys.n
    out.append(("keys", n))
    if n:
        counts = np.bincount(ks.keys.enc[:n].astype(np.int64), minlength=16)
        out.append(("counters", int(counts[S.ENC_COUNTER])))
        out.append(("registers", int(counts[S.ENC_BYTES])))
        out.append(("dicts", int(counts[S.ENC_DICT])))
        out.append(("sets", int(counts[S.ENC_SET])))
        out.append(("multivalues", int(counts[S.ENC_MV])))
        out.append(("lists", int(counts[S.ENC_LIST])))
        out.append(("tensors", int(counts[S.ENC_TENSOR])))
    out.append(("counter_slots", ks.cnt.n))
    out.append(("element_rows", ks.el.n - ks.el_dead))
    out.append(("tensor_slots", ks.tns.n))
    out.append(("tensor_payload_bytes", ks.tns_bytes))
    for name, cnt in sorted(ks.tns_merges_by_strat.items()):
        out.append((f"tensor_merges_{name.replace('-', '_')}", cnt))
    out.append(("pending_tombstones", len(ks.garbage)))


def _section_cluster(node, out) -> None:
    """Slot ownership + migration observability (constdb_tpu/cluster).
    cluster_enabled:0 is the whole story on a non-cluster node — the
    section shape stays stable either way, so dashboards need no
    probing."""
    cl = node.cluster
    if cl is None:
        out.append(("cluster_enabled", 0))
        return
    out.extend(cl.info_pairs())


SECTIONS = {
    "server": _section_server,
    "clients": _section_clients,
    "memory": _section_memory,
    "stats": _section_stats,
    "cpu": _section_cpu,
    "durability": _section_durability,
    "recovery": _section_recovery,
    "replication": _section_replication,
    "keyspace": _section_keyspace,
    "cluster": _section_cluster,
}


@register("info", CMD_READONLY)
def info_command(node, ctx, args):
    """(reference stats.rs:287-305)"""
    want = args.next_str().lower() if args.has_more else None
    lines = []
    for name, fn in SECTIONS.items():
        if want is not None and name != want:
            continue
        lines.append(f"# {name.capitalize()}")
        rows: list = []
        fn(node, rows)
        lines.extend(f"{k}:{v}" for k, v in rows)
        lines.append("")
    return Bulk("\r\n".join(lines).encode())
