"""Overload governor: bounded memory with convergence-preserving shedding.

Every buffer and byte a node holds is accounted here and compared
against two watermarks derived from `CONSTDB_MAXMEMORY`:

  soft (`CONSTDB_MAXMEMORY_SOFT_PCT`, default 85%) — client DATA writes
      shed with a clean `-OOM …` error.  Reads, admin, deletes/expiry
      (they free memory), and **all replication intake** stay admitted.
  hard (100%) — additionally: flush device-resident merge state, drop
      warm-path caches (digest crc caches, device tensor pools), and
      force a GC sweep (which compacts the element table when dead rows
      dominate) — rate-limited so a node pinned at the ceiling is not
      re-flushing per write.

The admission asymmetry is the convergence-soundness law
(docs/INVARIANTS.md "Degradation laws"): shedding happens at the CLIENT
edge, before an op is applied, logged, or replicated — a shed write
simply never existed, so the delivered-set the mesh must converge on is
unchanged.  Shedding *replication* intake instead would hold back ops
the origin already considers delivered, and the mesh would diverge
(or stall its GC horizon forever).  Replicated ops always land.

Accounting sources (`used_memory`):
  * the keyspace — live numeric rows + incrementally-tracked blob and
    tensor payload bytes (`KeySpace.used_bytes`; BlobList keeps the
    blob gauge exact through every engine path)
  * the repl-log ring (`total_bytes`; a MergedReplLog sums segments)
  * device pools — the engine's pinned win-value and tensor payload
    bytes (`_pool_bytes`/`_tns_bytes`)
  * the encode-once run cache (`node.wire_cache` — the broadcast
    plane's published wire encodings, replica/encode_cache.py)
  * registered extra sources (per-connection applier buffers register a
    callable here, as does the shared-dump compression writer's working
    buffer; they unregister on teardown)

The check is cheap (a few dozen attribute reads) but not free, so the
gate caches its verdict for `check_every` writes; the server cron calls
`tick()` each interval so a quiet node still observes pressure changes.
The watermark is therefore an approximation by design — a handful of
writes may land past the exact byte boundary — but every *shed* write
produced exactly one clean error and zero state, which is the invariant
the chaos oracle certifies.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

log = logging.getLogger(__name__)

# the exact error reply a shed write receives (Redis-style leading code;
# chaos/resource.py asserts shed replies byte-match this)
OOM_ERR = (b"OOM write rejected: node over CONSTDB_MAXMEMORY soft "
           b"watermark (reads, deletes, and replication stay admitted)")

STATE_OK, STATE_SOFT, STATE_HARD = 0, 1, 2
_STATE_NAMES = {STATE_OK: "ok", STATE_SOFT: "soft", STATE_HARD: "hard"}

# min seconds between hard-watermark reclaim sweeps (flush + cache drop
# + GC): a node pinned at the ceiling must not re-flush per check
_HARD_ACTION_PERIOD = 1.0


class OverloadGovernor:
    """Per-node memory accounting + watermark decisions (module doc)."""

    __slots__ = ("node", "maxmemory", "soft_pct", "soft_bytes", "sources",
                 "check_every", "reclaim_gc", "_state", "_countdown",
                 "_used", "_last_hard", "_now")

    def __init__(self, node, maxmemory: Optional[int] = None,
                 soft_pct: Optional[float] = None,
                 now: Callable[[], float] = time.monotonic) -> None:
        self.node = node
        self.sources: list[Callable[[], int]] = []
        self.check_every = 64
        # may the hard-watermark reclaim run this node's OWN gc()?
        # False on shard-worker nodes (parallel/serve_pool.py): a
        # worker's ReplicaManager has no peers, so Node.gc_horizon()
        # would fall back to the local clock and collect tombstones no
        # peer has acked — the resurrection class the coverage-gated
        # horizon (docs/INVARIANTS.md) exists to prevent.  Worker GC is
        # parent-driven with the real cluster horizon (the cron's
        # serve_plane.gc), so the reclaim only skips the sweep, not
        # collection itself.
        self.reclaim_gc = True
        self._state = STATE_OK
        self._countdown = 0
        self._used = 0
        self._last_hard = 0.0
        self._now = now
        if maxmemory is None or soft_pct is None:
            from ..conf import env_float, env_int
            if maxmemory is None:
                maxmemory = env_int("CONSTDB_MAXMEMORY", 0)
            if soft_pct is None:
                soft_pct = env_float("CONSTDB_MAXMEMORY_SOFT_PCT", 85.0)
        self.configure(maxmemory, soft_pct)

    def configure(self, maxmemory: Optional[int] = None,
                  soft_pct: Optional[float] = None) -> None:
        """(Re)set the cap — ServerApp overrides the env defaults, shard
        workers install their per-shard slice of the node cap."""
        if maxmemory is not None:
            self.maxmemory = max(0, int(maxmemory))
        if soft_pct is not None:
            self.soft_pct = float(soft_pct)
        self.soft_bytes = int(self.maxmemory * self.soft_pct / 100.0)
        self._countdown = 0

    # ---------------------------------------------------------- accounting

    def register_source(self, fn: Callable[[], int]) -> None:
        self.sources.append(fn)

    def unregister_source(self, fn: Callable[[], int]) -> None:
        try:
            self.sources.remove(fn)
        except ValueError:
            pass

    def used_memory(self) -> int:
        """Governed total, from the incrementally-maintained gauges —
        O(sources), no table walks."""
        node = self.node
        eng = node.engine
        # getattr: a serve worker's repl_log is the plane's _TapLog
        # (drained into the parent's segments per ack — the parent's
        # MergedReplLog accounts those bytes)
        wire_cache = getattr(node, "wire_cache", None)
        read_cache = getattr(node, "read_cache", None)
        total = node.ks.used_bytes() \
            + (getattr(node.repl_log, "total_bytes", 0) or 0) \
            + (getattr(eng, "_pool_bytes", 0) or 0) \
            + (getattr(eng, "_tns_bytes", 0) or 0) \
            + (wire_cache.used_bytes() if wire_cache is not None else 0) \
            + (read_cache.used_bytes() if read_cache is not None else 0)
        for fn in self.sources:
            total += fn()
        return total

    # ----------------------------------------------------------- decisions

    @property
    def state(self) -> int:
        return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self._state]

    @property
    def last_used(self) -> int:
        """used_memory at the last refresh (INFO; 0 until one ran)."""
        return self._used

    def shed_writes(self, weight: int = 1) -> bool:
        """The write-path gate (commands.execute / the serve planners):
        True = shed this client data write with OOM_ERR.  Re-evaluates
        the watermarks every `check_every` WRITES of pressure; stale
        verdicts in between are the documented approximation.  `weight`:
        how many writes this one decision covers — the serve coalescer
        gates once per pipelined CHUNK, so it weighs the whole chunk
        (an unweighted per-chunk decrement would stretch the refresh
        window to check_every * chunk_size writes; on a shard worker,
        which has no cron tick, pressure could go unseen for tens of
        thousands of writes)."""
        if not self.maxmemory:
            return False
        self._countdown -= weight
        if self._countdown < 0:
            self._refresh()
        return self._state != STATE_OK

    def tick(self) -> None:
        """Cron hook: re-evaluate now (a quiet node must still see
        pressure from replication intake / pool growth) and run the
        hard-watermark reclaim if due."""
        if self.maxmemory:
            self._refresh()

    def _refresh(self) -> None:
        used = self._used = self.used_memory()
        self._countdown = self.check_every
        prev = self._state
        if used >= self.maxmemory:
            self._state = STATE_HARD
            self._on_hard()
        elif used >= self.soft_bytes:
            self._state = STATE_SOFT
        else:
            self._state = STATE_OK
        if self._state != prev:
            lvl = logging.WARNING if self._state else logging.INFO
            log.log(lvl, "overload state %s -> %s (used_memory=%d, "
                    "maxmemory=%d, soft=%d)", _STATE_NAMES[prev],
                    self.state_name, used, self.maxmemory, self.soft_bytes)
            x = self.node.stats.extra
            x["oom_state_changes"] = x.get("oom_state_changes", 0) + 1

    def _on_hard(self) -> None:
        """Hard-watermark reclaim: flush device-resident state down to
        the host, release device pools, drop rebuildable warm caches,
        and force a GC sweep (which compacts the element table when dead
        rows dominate).  Rate-limited; never touches live CRDT state, so
        it degrades speed, never convergence."""
        now = self._now()
        if now - self._last_hard < _HARD_ACTION_PERIOD:
            return
        self._last_hard = now
        node = self.node
        st = node.stats
        st.oom_hard_reclaims += 1
        node.ensure_flushed()
        eng = node.engine
        release = getattr(eng, "release_device_pools", None)
        if release is not None:
            release(node.ks)
        node.ks.release_warm_caches()
        wire_cache = getattr(node, "wire_cache", None)
        if wire_cache is not None:
            # the encode-once cache is exactly a rebuildable warm cache:
            # dropping it costs re-encodes, never correctness
            wire_cache.clear()
        read_cache = getattr(node, "read_cache", None)
        if read_cache is not None:
            # likewise the reply cache: dropping it costs re-reads only
            read_cache.clear()
        if self.reclaim_gc:
            # gc() re-flushes (a no-op now) and compacts when dead rows
            # dominate; collection is bounded by the cluster horizon
            # (shard workers skip this — see reclaim_gc above)
            node.gc()
        log.warning("hard watermark: flushed + dropped warm caches "
                    "(used_memory=%d, maxmemory=%d)",
                    self.used_memory(), self.maxmemory)
