"""Client-assisted caching: RESP3 invalidation push tracking.

The PR 15 reply cache already computes a precise invalidation stream —
every mutation intake (per-op execute, replicated frames, coalesced
runs, delta/snapshot ingest, oplog replay) names exactly the keys whose
cached replies die.  This module forwards that stream over the wire to
RESP3 clients that opted in (`CLIENT TRACKING on`), so a client-side
near-cache (client/near_cache.py) can serve hot reads with zero server
round-trips while the key is quiet.

Two modes, mirroring Redis 6 server-assisted caching:

  * default: the server remembers which keys each tracked connection
    has READ (note_read — fed by commands.execute and the serve
    planner's read batches) and sends a one-shot invalidation push the
    first time such a key mutates.  The per-connection key set is
    capped (CONSTDB_TRACKING_MAX_KEYS): past the cap the server sends a
    flush-all push and starts the set over — bounded memory, never
    silently stale.
  * BCAST: no per-read bookkeeping; every mutation's key is broadcast
    to every subscriber whose prefix list matches.  The frame for a
    given flush is encoded ONCE per prefix class and shared across all
    subscribers in it through the PR 13 encode-once cache
    (node.wire_cache) — N subscribers cost one encode, like the
    replication fan-out.

Push frames are the RESP3 invalidation shape:

    >2\r\n $10\r\n invalidate\r\n *N\r\n $.. key ...   (keys)
    >2\r\n $10\r\n invalidate\r\n $-1\r\n              (flush-all)

Delivery discipline (docs/INVARIANTS.md "Tracking laws"):

  * invalidate-before-visible: keys are queued at the SAME hook the
    reply cache invalidates from — before the mutation lands — and
    flush under a dual batch/latency bound (CONSTDB_TRACKING_BATCH /
    CONSTDB_TRACKING_LATENCY_MS), like every other hot path.
  * the PR 12 outbuf cap is respected: a tracked connection whose
    write buffer is over CONSTDB_CLIENT_OUTBUF_MAX when a push flush
    fires is demoted to untracked LOUDLY — warning log, the
    tracking_demotions counter — and its transport is aborted, so the
    client observes a disconnect and the reconnect-flush law restores
    correctness.  Invalidation frames never buffer unbounded.
  * a connection's tracking state dies with the connection
    (unsubscribe from server/io.py's finally) — entries a client
    cached are only trustworthy while the connection that filled them
    is live.
"""

from __future__ import annotations

import logging

from ..resp.codec import encode_into
from ..resp.message import Bulk, NIL, Push

log = logging.getLogger("constdb.tracking")

# tracking modes (ClientConn.tracking)
TRACK_OFF = 0
TRACK_DEFAULT = 1
TRACK_BCAST = 2

_INVALIDATE = Bulk(b"invalidate")
_FLUSH_ALL_FRAME = None  # encoded lazily (stable bytes, shared)


def _flush_all_bytes() -> bytes:
    global _FLUSH_ALL_FRAME
    if _FLUSH_ALL_FRAME is None:
        buf = bytearray()
        encode_into(buf, Push([_INVALIDATE, NIL]))
        _FLUSH_ALL_FRAME = bytes(buf)
    return _FLUSH_ALL_FRAME


def _encode_keys_frame(keys) -> bytes:
    """The RESP3 invalidation push frame for a key list."""
    from ..resp.message import Arr
    buf = bytearray()
    encode_into(buf, Push([_INVALIDATE, Arr([Bulk(k) for k in keys])]))
    return bytes(buf)


class ClientConn:
    """Per-connection client state the command layer can see (ExecCtx
    .client): identity for CLIENT ID/LIST, the negotiated protocol
    (HELLO 3), and the tracking subscription.  Owned by server/io.py's
    connection loop; the registry holds references while tracking is
    on."""

    __slots__ = ("cid", "addr", "writer", "resp3", "tracking", "prefixes",
                 "tracked", "pend", "_timer", "created")

    def __init__(self, cid: int, addr: str, writer=None, created=0.0):
        self.cid = cid
        self.addr = addr
        self.writer = writer
        self.resp3 = False
        self.tracking = TRACK_OFF
        self.prefixes: tuple = ()
        self.tracked: set = set()   # default-mode keys the server records
        self.pend: dict = {}        # pending invalidation keys (ordered)
        self._timer = None          # armed latency-bound flush handle
        self.created = created

    def describe(self) -> str:
        mode = {TRACK_OFF: "off", TRACK_DEFAULT: "on",
                TRACK_BCAST: "bcast"}[self.tracking]
        return (f"id={self.cid} addr={self.addr} resp={3 if self.resp3 else 2}"
                f" tracking={mode}")


class TrackingRegistry:
    """Node-level invalidation fan-out to tracked client connections.

    Hot-path cost when nothing subscribes: one attribute test
    (`registry.active`) at each invalidation tap — the same shape as
    the reply cache's own `len(rc)` gate."""

    __slots__ = ("node", "active", "batch", "latency_s", "max_keys",
                 "key_map", "bcast", "clients", "_bseq", "_bpend",
                 "_btimer", "loop")

    def __init__(self, node):
        from ..conf import env_int
        self.node = node
        self.active = False
        self.batch = max(1, env_int("CONSTDB_TRACKING_BATCH", 128))
        self.latency_s = max(
            0, env_int("CONSTDB_TRACKING_LATENCY_MS", 2)) / 1000.0
        self.max_keys = max(1, env_int("CONSTDB_TRACKING_MAX_KEYS", 65536))
        self.key_map: dict = {}    # key -> set of default-mode ClientConn
        self.bcast: set = set()    # BCAST-mode ClientConn
        self.clients: set = set()  # every tracked ClientConn
        self._bseq = 0             # BCAST flush sequence (encode-once key)
        self._bpend: dict = {}     # pending BCAST keys (ordered, deduped)
        self._btimer = None
        self.loop = None           # armed by subscribe (the serving loop)

    # ------------------------------------------------------- subscription

    def subscribe(self, client: ClientConn, bcast: bool = False,
                  prefixes: tuple = ()) -> None:
        """CLIENT TRACKING on: register `client` in the requested mode
        (re-subscribing switches modes and drops the old state)."""
        if client.tracking != TRACK_OFF:
            self.unsubscribe(client)
        client.tracking = TRACK_BCAST if bcast else TRACK_DEFAULT
        client.prefixes = tuple(prefixes)
        self.clients.add(client)
        if bcast:
            self.bcast.add(client)
        if self.loop is None:
            import asyncio
            try:
                self.loop = asyncio.get_running_loop()
            except RuntimeError:
                self.loop = None  # sync tests: latency bound degrades
                #                   to flush-on-batch-bound only
        self.active = True

    def unsubscribe(self, client: ClientConn) -> None:
        """Tracking off / connection closed: drop every trace of the
        subscription (the connection-liveness half of the law)."""
        if client.tracking == TRACK_DEFAULT:
            km = self.key_map
            for key in client.tracked:
                conns = km.get(key)
                if conns is not None:
                    conns.discard(client)
                    if not conns:
                        del km[key]
        client.tracked.clear()
        client.pend.clear()
        if client._timer is not None:
            client._timer.cancel()
            client._timer = None
        client.tracking = TRACK_OFF
        client.prefixes = ()
        self.bcast.discard(client)
        self.clients.discard(client)
        if not self.clients:
            self.active = False
            self._bpend.clear()
            if self._btimer is not None:
                self._btimer.cancel()
                self._btimer = None

    @property
    def n_clients(self) -> int:
        return len(self.clients)

    # --------------------------------------------------------- read taps

    def note_read(self, client, key: bytes) -> None:
        """Default-mode bookkeeping: `client` has read `key`; the first
        mutation of `key` owes it a one-shot invalidation push.  Fed by
        commands.execute (READONLY commands) and the serve read planner
        (every read in a planned batch, cache hits included)."""
        if client is None or client.tracking != TRACK_DEFAULT:
            return
        tracked = client.tracked
        if key in tracked:
            return
        if len(tracked) >= self.max_keys:
            # bounded memory, never silently stale: flush the client's
            # whole near-cache and start the set over
            self._drop_client_keys(client)
            self._send(client, _flush_all_bytes())
            self.node.stats.tracking_invalidations_sent += 1
            return
        tracked.add(key)
        self.key_map.setdefault(key, set()).add(client)

    def _drop_client_keys(self, client) -> None:
        km = self.key_map
        for key in client.tracked:
            conns = km.get(key)
            if conns is not None:
                conns.discard(client)
                if not conns:
                    del km[key]
        client.tracked.clear()

    # -------------------------------------------------- invalidation taps

    def invalidate_key(self, key: bytes) -> None:
        """One mutated key — queue its push on every owed connection.
        Called from the same seams the reply cache invalidates at,
        BEFORE the mutation lands (invalidate-before-visible)."""
        conns = self.key_map.pop(key, None)
        if conns:
            for c in conns:
                c.tracked.discard(key)
                self._queue(c, key)
        if self.bcast:
            bp = self._bpend
            if key not in bp:
                bp[key] = None
                if len(bp) >= self.batch:
                    self._flush_bcast()
                elif self._btimer is None and self.loop is not None:
                    self._btimer = self.loop.call_later(
                        self.latency_s, self._flush_bcast)

    def invalidate_keys(self, keys) -> None:
        for key in keys:
            self.invalidate_key(bytes(key))

    def flush_all(self) -> None:
        """State-wipe events (full resync, slot import reset): every
        tracked client's near-cache is wholesale untrustworthy."""
        frame = _flush_all_bytes()
        st = self.node.stats
        for c in list(self.clients):
            c.pend.clear()
            if c.tracking == TRACK_DEFAULT:
                self._drop_client_keys(c)
            if self._send(c, frame):
                st.tracking_invalidations_sent += 1
        self._bpend.clear()

    def slots_lost(self, slots) -> None:
        """Cluster slot migration moved ownership away from this node
        (cluster/slots.py adopt hook): every tracked key hashing into a
        moved slot must be invalidated — subsequent writes land on the
        new owner and this node will never see them, so the one-shot
        promise could otherwise never be kept.  BCAST subscribers get a
        flush-all (their subscription is prefix-, not slot-scoped)."""
        if not self.active:
            return
        from ..cluster.slots import slot_of
        moved = [k for k in self.key_map if slot_of(k) in slots]
        for k in moved:
            # default-mode conns only: BCAST gets one flush-all below,
            # not a per-key frame AND a flush-all
            conns = self.key_map.pop(k, None)
            if conns:
                for c in conns:
                    c.tracked.discard(k)
                    self._queue(c, k)
        if self.bcast:
            frame = _flush_all_bytes()
            st = self.node.stats
            for c in list(self.bcast):
                if self._send(c, frame):
                    st.tracking_invalidations_sent += 1

    # ------------------------------------------------------ flush plumbing

    def _queue(self, client, key: bytes) -> None:
        pend = client.pend
        if key in pend:
            return
        pend[key] = None
        if len(pend) >= self.batch:
            self._flush_conn(client)
        elif client._timer is None and self.loop is not None:
            client._timer = self.loop.call_later(
                self.latency_s, self._flush_conn, client)

    def _flush_conn(self, client) -> None:
        if client._timer is not None:
            client._timer.cancel()
            client._timer = None
        pend = client.pend
        if not pend or client.tracking == TRACK_OFF:
            pend.clear()
            return
        keys = list(pend)
        pend.clear()
        if self._send(client, _encode_keys_frame(keys)):
            st = self.node.stats
            st.tracking_invalidations_sent += len(keys)
            st.tracking_pushes += 1

    def _flush_bcast(self) -> None:
        if self._btimer is not None:
            self._btimer.cancel()
            self._btimer = None
        bp = self._bpend
        if not bp or not self.bcast:
            bp.clear()
            return
        keys = list(bp)
        bp.clear()
        seq = self._bseq
        self._bseq = seq + 1
        # group subscribers by prefix class: every subscriber in a class
        # receives byte-identical frames, so the flush encodes ONCE per
        # class through the encode-once cache (first subscriber encodes
        # and publishes; the rest splice the published bytes)
        groups: dict = {}
        for c in self.bcast:
            groups.setdefault(c.prefixes, []).append(c)
        wc = self.node.wire_cache
        st = self.node.stats
        for prefixes, conns in groups.items():
            if prefixes:
                sel = [k for k in keys
                       if any(k.startswith(p) for p in prefixes)]
                if not sel:
                    continue
            else:
                sel = keys
            caps = ("tracking",) + prefixes
            payload = None
            for c in conns:
                if payload is None:
                    ent = wc.get(caps, seq)
                    if ent is not None:
                        payload = ent.payload
                    else:
                        payload = _encode_keys_frame(sel)
                        wc.put(caps, seq, seq + 1, payload,
                               readers=len(conns) - 1)
                if self._send(c, payload):
                    st.tracking_invalidations_sent += len(sel)
                    st.tracking_pushes += 1

    def _send(self, client, payload: bytes) -> bool:
        """Write one push frame to the connection, respecting the PR 12
        outbuf cap: an over-cap tracker demotes to untracked loudly and
        its transport aborts (the client sees a disconnect; the
        reconnect-flush law restores correctness).  Returns True iff the
        frame was written."""
        w = client.writer
        if w is None:
            return False
        tr = w.transport
        if tr.is_closing():
            return False
        app = self.node.app
        cap = getattr(app, "client_outbuf_max", 0) if app is not None else 0
        if cap and tr.get_write_buffer_size() > cap:
            self.unsubscribe(client)
            self.node.stats.tracking_demotions += 1
            log.warning(
                "tracked client %s over the outbuf cap (%d > %d): "
                "demoting to untracked and aborting the connection",
                client.describe(), tr.get_write_buffer_size(), cap)
            tr.abort()
            return False
        try:
            w.write(payload)
        except (ConnectionError, RuntimeError):
            return False
        return True
